"""Search / sort ops.

Parity: argmax/argmin/argsort/top_k_v2/searchsorted/kthvalue/mode/sort
(/root/reference/paddle/fluid/operators/arg_max_op.cc, top_k_v2_op.cc,
argsort_op.cc). Index outputs are nondifferentiable; value outputs carry grad.
"""
from __future__ import annotations

import jax.lax as lax
import jax.numpy as jnp

from ..dtype import to_jax_dtype
from ._primitive import primitive, unwrap, wrap

__all__ = [
    "argmax",
    "argmin",
    "argsort",
    "sort",
    "topk",
    "kthvalue",
    "mode",
    "searchsorted",
    "masked_fill",
    "bucketize",
]


def argmax(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmax(unwrap(x), axis=axis, keepdims=keepdim if axis is not None else False)
    return wrap(out.astype(to_jax_dtype(dtype)))


def argmin(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmin(unwrap(x), axis=axis, keepdims=keepdim if axis is not None else False)
    return wrap(out.astype(to_jax_dtype(dtype)))


def argsort(x, axis=-1, descending=False):
    arr = unwrap(x)
    idx = jnp.argsort(-arr if descending else arr, axis=axis, stable=True)
    return wrap(idx.astype(jnp.int64))


@primitive
def _sort(x, axis, descending):
    s = jnp.sort(x, axis=axis)
    return jnp.flip(s, axis=axis) if descending else s


def sort(x, axis=-1, descending=False):
    return _sort(x, axis, descending)


@primitive(aux=1)
def _topk(x, k, axis, largest):
    if axis != -1 and axis != x.ndim - 1:
        xm = jnp.moveaxis(x, axis, -1)
    else:
        xm = x
    vals, idx = _lax_topk(xm, k, largest)
    if axis != -1 and axis != x.ndim - 1:
        vals = jnp.moveaxis(vals, -1, axis)
        idx = jnp.moveaxis(idx, -1, axis)
    return vals, idx.astype(jnp.int64)


def _lax_topk(x, k, largest):
    if largest:
        return lax.top_k(x, k)
    vals, idx = lax.top_k(-x, k)
    return -vals, idx


def topk(x, k, axis=-1, largest=True, sorted=True):  # noqa: A002
    k = int(unwrap(k))
    return _topk(x, k, axis, largest)


@primitive(aux=1)
def _kthvalue(x, k, axis, keepdim):
    s = jnp.sort(x, axis=axis)
    si = jnp.argsort(x, axis=axis)
    vals = jnp.take(s, k - 1, axis=axis)
    idx = jnp.take(si, k - 1, axis=axis)
    if keepdim:
        vals = jnp.expand_dims(vals, axis)
        idx = jnp.expand_dims(idx, axis)
    return vals, idx.astype(jnp.int64)


def kthvalue(x, k, axis=-1, keepdim=False):
    return _kthvalue(x, k, axis, keepdim)


def mode(x, axis=-1, keepdim=False):
    import numpy as np
    import scipy.stats

    arr = np.asarray(unwrap(x))
    m = scipy.stats.mode(arr, axis=axis, keepdims=keepdim)
    vals = m.mode
    # indices: last occurrence along axis equal to mode (paddle semantics)
    expanded = vals if keepdim else np.expand_dims(vals, axis)
    eq = arr == expanded
    n = arr.shape[axis]
    pos = np.arange(n).reshape([-1 if i == (axis % arr.ndim) else 1 for i in range(arr.ndim)])
    idx = np.max(np.where(eq, pos, -1), axis=axis, keepdims=keepdim)
    return wrap(jnp.asarray(vals)), wrap(jnp.asarray(idx.astype(np.int64)))


def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    side = "right" if right else "left"
    seq, vals = unwrap(sorted_sequence), unwrap(values)
    if seq.ndim == 1:
        out = jnp.searchsorted(seq, vals, side=side)
    else:
        out = jnp.stack(
            [jnp.searchsorted(seq[i], vals[i], side=side) for i in range(seq.shape[0])]
        )
    return wrap(out.astype(jnp.int32 if out_int32 else jnp.int64))


@primitive
def masked_fill(x, mask, value):
    return jnp.where(mask, jnp.asarray(value, x.dtype), x)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    """Bucket index of each x in a 1-D sorted sequence (parity:
    paddle.bucketize — searchsorted with a shared 1-D boundary tensor)."""
    seq = unwrap(sorted_sequence)
    if seq.ndim != 1:
        raise ValueError("sorted_sequence should be a 1-D tensor for bucketize")
    out = jnp.searchsorted(seq, unwrap(x), side="right" if right else "left")
    return wrap(out.astype(jnp.int32 if out_int32 else jnp.int64))
