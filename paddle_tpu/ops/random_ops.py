"""Random sampling ops.

Parity: uniform_random / gaussian_random / randint / randperm / multinomial /
bernoulli operators (/root/reference/paddle/fluid/operators/uniform_random_op.cc
etc.) and python/paddle/tensor/random.py.

TPU-native: every call draws a fresh subkey from the global stateful Generator
(paddle_tpu.random) — functional jax PRNG under a stateful API, so results are
reproducible under paddle.seed() yet safe inside jit traces.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..dtype import to_jax_dtype
from ..random import split_key
from ..tensor import Tensor
from ._primitive import unwrap, wrap

__all__ = [
    "uniform",
    "uniform_",
    "rand",
    "randn",
    "normal",
    "standard_normal",
    "randint",
    "randint_like",
    "randperm",
    "bernoulli",
    "multinomial",
    "poisson",
    "exponential_",
    "gumbel_softmax",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(unwrap(s)) for s in shape)


def _static_rng(op_name, draw, args=()):
    """Static-mode hook: record ``draw(key, *arrays)`` as a per-run rng op
    (the Executor feeds a fresh root key each run). Returns None in eager."""
    from ..static.program import record_rng_op, recording_active

    if not recording_active():
        return None
    return record_rng_op(draw, op_name, args)


def uniform(shape, dtype="float32", min=-1.0, max=1.0, seed=0):  # noqa: A002
    if not seed:
        shp, jdt = _shape(shape), to_jax_dtype(dtype)
        rec = _static_rng(
            "uniform_random",
            lambda key: jax.random.uniform(key, shp, jdt, minval=min, maxval=max),
        )
        if rec is not None:
            return rec
    # a fixed seed reproduces the same numbers every run — identical to the
    # reference's seeded uniform_random op, so a static-capture constant is fine
    key = jax.random.key(seed) if seed else split_key()
    return wrap(
        jax.random.uniform(key, _shape(shape), to_jax_dtype(dtype), minval=min, maxval=max)
    )


def uniform_(x, min=-1.0, max=1.0):  # noqa: A002
    from ._primitive import inplace_guard

    inplace_guard(x, "uniform_")
    x._set_data(
        jax.random.uniform(split_key(), tuple(x._data.shape), x._data.dtype, minval=min, maxval=max)
    )
    return x


def rand(shape, dtype="float32"):
    return uniform(shape, dtype, 0.0, 1.0)


def randn(shape, dtype="float32"):
    shp, jdt = _shape(shape), to_jax_dtype(dtype)
    rec = _static_rng("gaussian_random", lambda key: jax.random.normal(key, shp, jdt))
    if rec is not None:
        return rec
    return wrap(jax.random.normal(split_key(), shp, jdt))


standard_normal = randn


def normal(mean=0.0, std=1.0, shape=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m, s = jnp.asarray(unwrap(mean)), jnp.asarray(unwrap(std))
        out_shape = jnp.broadcast_shapes(m.shape, s.shape)
        return wrap(m + s * jax.random.normal(split_key(), out_shape, jnp.float32))
    return wrap(mean + std * jax.random.normal(split_key(), _shape(shape), jnp.float32))


def randint(low=0, high=None, shape=(1,), dtype="int64"):
    if high is None:
        low, high = 0, low
    shp, jdt = _shape(shape), to_jax_dtype(dtype)
    rec = _static_rng("randint", lambda key: jax.random.randint(key, shp, low, high, jdt))
    if rec is not None:
        return rec
    return wrap(jax.random.randint(split_key(), shp, low, high, jdt))


def randint_like(x, low=0, high=None, dtype=None):
    arr = unwrap(x)
    return randint(low, high, tuple(arr.shape), dtype or str(arr.dtype))


def randperm(n, dtype="int64"):
    return wrap(jax.random.permutation(split_key(), n).astype(to_jax_dtype(dtype)))


def bernoulli(x):
    rec = _static_rng(
        "bernoulli",
        lambda key, arr: jax.random.bernoulli(key, arr, arr.shape).astype(arr.dtype),
        (x,),
    )
    if rec is not None:
        return rec
    arr = unwrap(x)
    return wrap(jax.random.bernoulli(split_key(), arr, arr.shape).astype(arr.dtype))


def multinomial(x, num_samples=1, replacement=False):
    arr = unwrap(x)
    logits = jnp.log(jnp.maximum(arr, 1e-30))
    if replacement:
        out = jax.random.categorical(split_key(), logits, axis=-1, shape=(num_samples,) + arr.shape[:-1])
        out = jnp.moveaxis(out, 0, -1) if arr.ndim > 1 else out
    else:
        # Gumbel top-k sampling without replacement
        g = jax.random.gumbel(split_key(), arr.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return wrap(out.astype(jnp.int64))


def poisson(x):
    arr = unwrap(x)
    return wrap(jax.random.poisson(split_key(), arr, arr.shape).astype(arr.dtype))


def exponential_(x, lam=1.0):
    x._set_data(jax.random.exponential(split_key(), tuple(x._data.shape), x._data.dtype) / lam)
    return x


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1):
    from ._primitive import primitive

    g = jax.random.gumbel(split_key(), tuple(unwrap(x).shape), unwrap(x).dtype)

    @primitive
    def _gs(x):
        y = jax.nn.softmax((x + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.put_along_axis(
                jnp.zeros_like(y), idx, jnp.asarray(1.0, y.dtype), axis=axis, inplace=False
            )
            # straight-through estimator: forward y_hard, grad through soft y
            y = y + jax.lax.stop_gradient(y_hard - y)
        return y

    return _gs(x)
