"""paddle_tpu.ops — the functional op surface.

Aggregates every op module and attaches tensor methods + dunders onto
``Tensor`` (parity: the reference monkey-patches methods in
python/paddle/fluid/dygraph/varbase_patch_methods.py and
python/paddle/tensor/__init__.py).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..tensor import Tensor
from . import (
    creation,
    linalg,
    logic,
    manipulation,
    math,
    array,
    misc_catalog,
    random_ops,
    search,
    sequence,
)
from ._primitive import inplace_guard, primitive, unwrap, wrap
from .creation import *  # noqa: F401,F403
from .array import *  # noqa: F401,F403
from .misc_catalog import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403 — note: no __all__, exports by name below
from .math import *  # noqa: F401,F403
from .random_ops import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .sequence import *  # noqa: F401,F403

# manipulation has no __all__; re-export its public names explicitly
from .manipulation import (  # noqa: F401
    broadcast_shape,
    crop,
    rank,
    reverse,
    scatter_,
    shape,
    squeeze_,
    tolist,
    unsqueeze_,
    broadcast_tensors,
    broadcast_to,
    cast,
    chunk,
    concat,
    expand,
    expand_as,
    flatten,
    flip,
    gather,
    gather_nd,
    index_add,
    index_put,
    index_sample,
    index_select,
    masked_select,
    moveaxis,
    nonzero,
    pad,
    put_along_axis,
    repeat_interleave,
    reshape,
    reshape_,
    roll,
    rot90,
    scatter,
    scatter_nd,
    scatter_nd_add,
    shard_index,
    slice,  # noqa: A004
    split,
    squeeze,
    stack,
    strided_slice,
    swapaxes,
    take_along_axis,
    tile,
    transpose,
    unbind,
    unique,
    unique_consecutive,
    unsqueeze,
    unstack,
    where,
)

# ---------------------------------------------------------------------------
# Tensor method + operator attachment
# ---------------------------------------------------------------------------

_METHODS = {
    # math
    "abs": math.abs, "acos": math.acos, "asin": math.asin, "atan": math.atan,
    "ceil": math.ceil, "cos": math.cos, "cosh": math.cosh, "exp": math.exp,
    "floor": math.floor, "log": math.log, "log2": math.log2, "log10": math.log10,
    "log1p": math.log1p, "neg": math.neg, "reciprocal": math.reciprocal,
    "round": math.round, "rsqrt": math.rsqrt, "sigmoid": math.sigmoid,
    "sign": math.sign, "sin": math.sin, "sinh": math.sinh, "sqrt": math.sqrt,
    "square": math.square, "tan": math.tan, "tanh": math.tanh, "erf": math.erf,
    "lgamma": math.lgamma, "digamma": math.digamma, "trunc": math.trunc,
    "conj": math.conj, "real": math.real, "imag": math.imag, "angle": math.angle,
    "add": math.add, "subtract": math.subtract, "multiply": math.multiply,
    "divide": math.divide, "floor_divide": math.floor_divide, "mod": math.mod,
    "remainder": math.remainder, "pow": math.pow, "maximum": math.maximum,
    "minimum": math.minimum, "fmax": math.fmax, "fmin": math.fmin,
    "atan2": math.atan2, "kron": math.kron, "scale": math.scale,
    "clip": math.clip, "lerp": math.lerp,
    "sum": math.sum, "mean": math.mean, "prod": math.prod, "max": math.max,
    "min": math.min, "amax": math.amax, "amin": math.amin, "std": math.std,
    "var": math.var, "median": math.median, "quantile": math.quantile,
    "nanmean": math.nanmean, "nansum": math.nansum, "logsumexp": math.logsumexp,
    "nanmedian": math.nanmedian, "trapezoid": math.trapezoid,
    "take": math.take, "polar": math.polar,
    "bitwise_left_shift": math.bitwise_left_shift,
    "bitwise_right_shift": math.bitwise_right_shift,
    "all": math.all, "any": math.any, "numel": math.numel,
    "count_nonzero": math.count_nonzero,
    "cumsum": math.cumsum, "cumprod": math.cumprod, "diff": math.diff,
    "addmm": math.addmm, "inner": math.inner, "outer": math.outer,
    "trace": math.trace, "isfinite": math.isfinite, "isinf": math.isinf,
    "isnan": math.isnan, "nan_to_num": math.nan_to_num, "logit": math.logit,
    "frac": math.frac, "heaviside": math.heaviside,
    # manipulation
    "reshape": reshape, "reshape_": reshape_, "flatten": flatten,
    "transpose": transpose, "squeeze": squeeze, "unsqueeze": unsqueeze,
    "expand": expand, "expand_as": expand_as, "broadcast_to": broadcast_to,
    "tile": tile, "roll": roll, "flip": flip, "concat": concat,
    "split": split, "chunk": chunk, "unbind": unbind, "gather": gather,
    "gather_nd": gather_nd, "scatter": scatter, "scatter_nd_add": scatter_nd_add,
    "index_select": index_select, "index_sample": index_sample,
    "masked_select": masked_select, "where": where, "nonzero": nonzero,
    "unique": unique, "slice": slice, "strided_slice": strided_slice,
    "cast": cast, "pad": pad, "tril": creation.tril, "triu": creation.triu,
    "take_along_axis": take_along_axis, "put_along_axis": put_along_axis,
    "repeat_interleave": repeat_interleave, "moveaxis": moveaxis,
    "index_fill": index_fill, "view": view, "view_as": view_as,
    "masked_fill": search.masked_fill,
    # linalg
    "matmul": linalg.matmul, "bmm": linalg.bmm, "dot": linalg.dot,
    "mv": linalg.mv, "t": linalg.t, "norm": linalg.norm, "dist": linalg.dist,
    "cholesky": linalg.cholesky, "inverse": linalg.inverse,
    "matrix_power": linalg.matrix_power,
    # logic
    "equal": logic.equal, "not_equal": logic.not_equal,
    "less_than": logic.less_than, "less_equal": logic.less_equal,
    "greater_than": logic.greater_than, "greater_equal": logic.greater_equal,
    "equal_all": logic.equal_all, "allclose": logic.allclose,
    "isclose": logic.isclose, "logical_and": logic.logical_and,
    "logical_or": logic.logical_or, "logical_not": logic.logical_not,
    "logical_xor": logic.logical_xor, "bitwise_and": logic.bitwise_and,
    "bitwise_or": logic.bitwise_or, "bitwise_xor": logic.bitwise_xor,
    "bitwise_not": logic.bitwise_not,
    # search
    "argmax": search.argmax, "argmin": search.argmin, "argsort": search.argsort,
    "sort": search.sort, "topk": search.topk, "kthvalue": search.kthvalue,
    "mode": search.mode, "searchsorted": search.searchsorted,
    # creation-ish
    "clone": creation.clone, "diagonal": None,  # placeholder filled below
    "zero_": None,
}


@primitive
def diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


_METHODS["diagonal"] = diagonal
_METHODS["squeeze_"] = manipulation.squeeze_
_METHODS["unsqueeze_"] = manipulation.unsqueeze_
_METHODS["scatter_"] = manipulation.scatter_
_METHODS["tanh_"] = math.tanh_
_METHODS["tolist"] = manipulation.tolist
del _METHODS["zero_"]  # defined directly on Tensor

# module-level functions that are NOT Tensor methods (their first arg is a
# shape int, not a tensor)
FREE_FUNCTIONS = {"tril_indices": tril_indices, "triu_indices": triu_indices}

for _name, _fn in _METHODS.items():
    if _fn is not None:
        Tensor._register_method(_name, _fn)


def _swap(fn):
    return lambda a, b: fn(b, a)


_DUNDERS = {
    "__add__": math.add,
    "__radd__": _swap(math.add),
    "__sub__": math.subtract,
    "__rsub__": _swap(math.subtract),
    "__mul__": math.multiply,
    "__rmul__": _swap(math.multiply),
    "__truediv__": math.divide,
    "__rtruediv__": _swap(math.divide),
    "__floordiv__": math.floor_divide,
    "__rfloordiv__": _swap(math.floor_divide),
    "__mod__": math.mod,
    "__rmod__": _swap(math.mod),
    "__pow__": math.pow,
    "__rpow__": _swap(math.pow),
    "__matmul__": linalg.matmul,
    "__rmatmul__": _swap(linalg.matmul),
    "__neg__": math.neg,
    "__abs__": math.abs,
    "__eq__": logic.equal,
    "__ne__": logic.not_equal,
    "__lt__": logic.less_than,
    "__le__": logic.less_equal,
    "__gt__": logic.greater_than,
    "__ge__": logic.greater_equal,
    "__and__": logic.bitwise_and,
    "__or__": logic.bitwise_or,
    "__xor__": logic.bitwise_xor,
    "__invert__": logic.bitwise_not,
}

for _name, _fn in _DUNDERS.items():
    Tensor._register_method(_name, _fn)


# ---------------------------------------------------------------------------
# in-place tensor-method variants (parity: paddle's *_ methods — the
# reference's inplace ops, e.g. REGISTER inplace pass); on a functional
# substrate "in place" rebinds the wrapper's storage
# ---------------------------------------------------------------------------

def _make_inplace(fn, name):
    def method(self, *args, **kwargs):
        inplace_guard(self, name)
        out = fn(self, *args, **kwargs)
        self._set_data(out._data if isinstance(out, Tensor) else out)
        return self

    method.__name__ = name
    return method


_INPLACE = {
    "add_": math.add, "subtract_": math.subtract, "sub_": math.subtract,
    "multiply_": math.multiply, "scale_": math.scale, "exp_": math.exp,
    "sqrt_": math.sqrt, "rsqrt_": math.rsqrt, "clip_": math.clip,
    "ceil_": math.ceil, "floor_": math.floor, "round_": math.round,
    "reciprocal_": math.reciprocal,
    "flatten_": manipulation.flatten,
}
for _name, _fn in _INPLACE.items():
    Tensor._register_method(_name, _make_inplace(_fn, _name))


def _uniform_(self, min=-1.0, max=1.0, seed=0):  # noqa: A002
    inplace_guard(self, "uniform_")
    return random_ops.uniform_(self, min=min, max=max)


def _normal_(self, mean=0.0, std=1.0):
    inplace_guard(self, "normal_")
    from . import random_ops as _ro

    out = _ro.normal(mean=mean, std=std, shape=self.shape)
    self._set_data(out._data.astype(self._data.dtype))
    return self


def _copy_(self, other, blocking=True):
    inplace_guard(self, "copy_")
    src = other._data if isinstance(other, Tensor) else jnp.asarray(other)
    self._set_data(src.astype(self._data.dtype))
    return self


def _element_size(self):
    return int(jnp.dtype(self._data.dtype).itemsize)


def _get_tensor(self):
    """LoDTensor-handle parity: the tensor IS its own dense storage here."""
    return self


Tensor._register_method("uniform_", _uniform_)
Tensor._register_method("normal_", _normal_)
Tensor._register_method("copy_", _copy_)
Tensor._register_method("element_size", _element_size)
Tensor._register_method("get_tensor", _get_tensor)
Tensor._register_method("dim", lambda self: len(self._data.shape))
Tensor._register_method("ndimension", lambda self: len(self._data.shape))
Tensor._register_method("cuda", lambda self, *a, **k: self)  # accelerator-resident already
