"""Tensor creation ops.

Parity: paddle.tensor.creation (python/paddle/tensor/creation.py in the
reference) — fill_constant, arange, linspace, eye, tril/triu, meshgrid, etc.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..dtype import to_jax_dtype
from ..tensor import Tensor, to_tensor
from ._primitive import primitive, unwrap, wrap

__all__ = [
    "to_tensor",
    "zeros",
    "ones",
    "full",
    "empty",
    "zeros_like",
    "ones_like",
    "full_like",
    "empty_like",
    "arange",
    "linspace",
    "logspace",
    "eye",
    "diag",
    "diagflat",
    "meshgrid",
    "tril",
    "triu",
    "clone",
    "assign",
    "complex",
    "create_parameter",
    "vander",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy())
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(unwrap(s)) if not isinstance(s, (int, np.integer)) else int(s) for s in shape)


def zeros(shape, dtype="float32"):
    return wrap(jnp.zeros(_shape(shape), to_jax_dtype(dtype)))


def ones(shape, dtype="float32"):
    return wrap(jnp.ones(_shape(shape), to_jax_dtype(dtype)))


def full(shape, fill_value, dtype="float32"):
    fill_value = unwrap(fill_value)
    return wrap(jnp.full(_shape(shape), fill_value, to_jax_dtype(dtype)))


def empty(shape, dtype="float32"):
    # XLA has no uninitialized buffers; zeros is the honest equivalent
    return zeros(shape, dtype)


@primitive
def _like_zeros(x):
    return jnp.zeros_like(x)


def zeros_like(x, dtype=None):
    x = unwrap(x)
    return wrap(jnp.zeros_like(x, dtype=to_jax_dtype(dtype) if dtype else None))


def ones_like(x, dtype=None):
    x = unwrap(x)
    return wrap(jnp.ones_like(x, dtype=to_jax_dtype(dtype) if dtype else None))


def full_like(x, fill_value, dtype=None):
    x = unwrap(x)
    return wrap(jnp.full_like(x, unwrap(fill_value), dtype=to_jax_dtype(dtype) if dtype else None))


def empty_like(x, dtype=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None):
    if end is None:
        start, end = 0, start
    start, end, step = unwrap(start), unwrap(end), unwrap(step)
    jdt = to_jax_dtype(dtype) if dtype is not None else None
    if jdt is None:
        py_floats = any(isinstance(v, float) for v in (start, end, step))
        jdt = jnp.float32 if py_floats else jnp.int64
    return wrap(jnp.arange(start, end, step, dtype=jdt))


def linspace(start, stop, num, dtype="float32"):
    return wrap(jnp.linspace(unwrap(start), unwrap(stop), int(unwrap(num)), dtype=to_jax_dtype(dtype)))


def logspace(start, stop, num, base=10.0, dtype="float32"):
    return wrap(
        jnp.logspace(unwrap(start), unwrap(stop), int(unwrap(num)), base=base, dtype=to_jax_dtype(dtype))
    )


def eye(num_rows, num_columns=None, dtype="float32"):
    return wrap(jnp.eye(num_rows, num_columns, dtype=to_jax_dtype(dtype)))


@primitive
def diag(x, offset=0, padding_value=0):
    if x.ndim == 1 and padding_value != 0:
        d = jnp.diag(x, k=offset)
        mask = jnp.eye(d.shape[0], d.shape[1], k=offset, dtype=bool)
        return jnp.where(mask, d, jnp.asarray(padding_value, d.dtype))
    return jnp.diag(x, k=offset)


@primitive
def diagflat(x, offset=0):
    return jnp.diagflat(x, k=offset)


def meshgrid(*args):
    args = args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args
    outs = jnp.meshgrid(*[unwrap(a) for a in args], indexing="ij")
    return [wrap(o) for o in outs]


@primitive
def tril(x, diagonal=0):
    return jnp.tril(x, k=diagonal)


@primitive
def triu(x, diagonal=0):
    return jnp.triu(x, k=diagonal)


@primitive
def assign(x):
    """Copy (parity: assign op). Output is a fresh tensor with grad link."""
    return x + 0 if jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact) else jnp.asarray(x)


def clone(x):
    return assign(x)


@primitive
def complex(real, imag):  # noqa: A001
    return jnp.asarray(real) + 1j * jnp.asarray(imag)


def create_parameter(shape, dtype="float32", default_initializer=None):
    from ..nn import initializer as init_mod

    init = default_initializer or init_mod.XavierNormal()
    data = init(_shape(shape), to_jax_dtype(dtype))
    t = Tensor(data, stop_gradient=False)
    t.persistable = True
    return t


def vander(x, n=None, increasing=False, name=None):
    """Vandermonde matrix (parity: paddle.vander)."""

    @primitive
    def _vander(x, n, increasing):
        return jnp.vander(x, N=n, increasing=increasing)

    return _vander(x, n, increasing)
