"""Gradient clipping.

Parity: python/paddle/fluid/clip.py (ClipGradByValue, ClipGradByNorm,
ClipGradByGlobalNorm). Functional cores are pure so the same logic runs inside
jitted distributed train steps (where the reference re-implements global-norm
clip inside HybridParallelOptimizer, dygraph_optimizer/hybrid_parallel_optimizer.py:45).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..tensor import Tensor

__all__ = ["ClipGradBase", "ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm", "clip_grads_functional"]


class ClipGradBase:
    def __call__(self, params_grads):
        """params_grads: list of (param, grad Tensor). Returns new list."""
        raise NotImplementedError

    def _clip_arrays(self, grads):
        """Pure: list of jax arrays -> list of clipped arrays."""
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):  # noqa: A002
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def _clip_arrays(self, grads):
        return [None if g is None else jnp.clip(g, self.min, self.max) for g in grads]

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
            else:
                out.append((p, Tensor(jnp.clip(g._data, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _clip_arrays(self, grads):
        outs = []
        for g in grads:
            if g is None:
                outs.append(None)
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            outs.append((g.astype(jnp.float32) * scale).astype(g.dtype))
        return outs

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
            else:
                out.append((p, Tensor(self._clip_arrays([g._data])[0])))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _clip_arrays(self, grads):
        sq = [jnp.sum(jnp.square(g.astype(jnp.float32))) for g in grads if g is not None]
        if not sq:
            return grads
        global_norm = jnp.sqrt(sum(sq))
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        return [None if g is None else (g.astype(jnp.float32) * scale).astype(g.dtype) for g in grads]

    def __call__(self, params_grads):
        clippable = [(p, g) for p, g in params_grads if g is not None and getattr(p, "need_clip", True)]
        rest = [(p, g) for p, g in params_grads if g is None or not getattr(p, "need_clip", True)]
        clipped = self._clip_arrays([g._data for _, g in clippable])
        return [(p, Tensor(cg)) for (p, _), cg in zip(clippable, clipped)] + rest


def clip_grads_functional(clip, grads_tree):
    """Apply a ClipGradBase to a pytree of grad arrays (for jitted steps)."""
    import jax

    if clip is None:
        return grads_tree
    leaves, treedef = jax.tree_util.tree_flatten(grads_tree)
    return jax.tree_util.tree_unflatten(treedef, clip._clip_arrays(leaves))
