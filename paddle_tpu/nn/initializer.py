"""Weight initializers.

Parity: python/paddle/nn/initializer/ and fluid/initializer.py in the
reference (ConstantInitializer, UniformInitializer, NormalInitializer,
TruncatedNormal, XavierInitializer, MSRAInitializer a.k.a. Kaiming, Assign).
Each initializer is a callable (shape, dtype) -> jax array drawing from the
global seeded generator.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..random import split_key

__all__ = [
    "Initializer",
    "abstract_init",
    "abstract_init_active",
    "Constant",
    "Uniform",
    "Normal",
    "TruncatedNormal",
    "XavierNormal",
    "XavierUniform",
    "KaimingNormal",
    "KaimingUniform",
    "Assign",
    "calculate_gain",
    "Bilinear",
]


def _fans(shape: Sequence[int]):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # paddle linear weight is [in, out]
        return shape[0], shape[1]
    # conv [out_c, in_c, *k] (paddle conv layout)
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


# ---------------------------------------------------------------------------
# abstract initialization (the auto-parallel planner's lowering path)
# ---------------------------------------------------------------------------
# Under ``abstract_init()`` Layer.create_parameter skips the initializer and
# hands the Parameter a jax.ShapeDtypeStruct instead of a materialized array:
# a multi-GB model becomes constructible in microseconds for shape-level
# tracing (jax.make_jaxpr / jax.eval_shape see exactly the same program).
# Thread-local so a planner search in one thread cannot leak abstract params
# into a concurrently-constructed real model.
import threading as _threading

_abstract_tls = _threading.local()


def abstract_init_active() -> bool:
    """True inside an :func:`abstract_init` block (this thread only)."""
    return bool(getattr(_abstract_tls, "depth", 0))


class abstract_init:
    """Context manager: parameters created inside are ShapeDtypeStructs.

    The resulting Layer can be traced (``functional_call_with_state`` swaps
    tracer values in for the stored specs) but never executed eagerly —
    reading a parameter's VALUE raises, by construction, because the spec is
    not an array.  Used by ``analysis.plan`` to lower full-size candidate
    train steps without allocating a byte of HBM.
    """

    def __enter__(self):
        _abstract_tls.depth = getattr(_abstract_tls, "depth", 0) + 1
        return self

    def __exit__(self, *exc):
        _abstract_tls.depth -= 1
        return False


class Initializer:
    def __call__(self, shape, dtype=jnp.float32):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32):
        return jnp.full(tuple(shape), self.value, dtype)


class Uniform(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=jnp.float32):
        return jax.random.uniform(split_key(), tuple(shape), dtype, self.low, self.high)


class Normal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=jnp.float32):
        return self.mean + self.std * jax.random.normal(split_key(), tuple(shape), dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=jnp.float32):
        return self.mean + self.std * jax.random.truncated_normal(
            split_key(), -2.0, 2.0, tuple(shape), dtype
        )


class XavierNormal(Initializer):
    def __init__(self, fan_in: Optional[float] = None, fan_out: Optional[float] = None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=jnp.float32):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(split_key(), tuple(shape), dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in: Optional[float] = None, fan_out: Optional[float] = None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=jnp.float32):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(split_key(), tuple(shape), dtype, -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in: Optional[float] = None, negative_slope: float = 0.0, nonlinearity: str = "relu"):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype=jnp.float32):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return std * jax.random.normal(split_key(), tuple(shape), dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in: Optional[float] = None, negative_slope: float = 0.0, nonlinearity: str = "relu"):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype=jnp.float32):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(split_key(), tuple(shape), dtype, -limit, limit)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32):
        arr = jnp.asarray(
            self.value._data if hasattr(self.value, "_data") else np.asarray(self.value), dtype
        )
        if tuple(arr.shape) != tuple(shape):
            raise ValueError(f"Assign initializer shape {arr.shape} != {tuple(shape)}")
        return arr


def calculate_gain(nonlinearity: str, param: float = 0.0) -> float:
    gains = {
        "sigmoid": 1.0,
        "linear": 1.0,
        "conv1d": 1.0,
        "conv2d": 1.0,
        "conv3d": 1.0,
        "tanh": 5.0 / 3.0,
        "relu": math.sqrt(2.0),
        "leaky_relu": math.sqrt(2.0 / (1 + param**2)),
        "selu": 3.0 / 4.0,
    }
    return gains.get(nonlinearity, 1.0)


class Bilinear(Initializer):
    """Bilinear-upsampling kernel init for transposed conv (parity:
    nn.initializer.Bilinear — weights such that conv_transpose performs
    bilinear interpolation). Weight layout matches Conv*Transpose here:
    (C_in, C_out/groups, kh, kw)."""

    def __call__(self, shape, dtype=jnp.float32):
        import numpy as np

        if len(shape) != 4:
            raise ValueError("Bilinear initializer expects a 4-D weight")
        c_in, c_out, kh, kw = shape
        f_h, f_w = (kh + 1) // 2, (kw + 1) // 2
        cy = f_h - 1 if kh % 2 == 1 else f_h - 0.5
        cx = f_w - 1 if kw % 2 == 1 else f_w - 0.5
        og = np.ogrid[:kh, :kw]
        filt = ((1 - np.abs(og[0] - cy) / f_h)
                * (1 - np.abs(og[1] - cx) / f_w)).astype(np.float32)
        w = np.zeros(shape, np.float32)
        # every (in, out) channel pair on the diagonal (mod the smaller
        # extent) carries the interpolation filter so no channel is dead
        for i in range(c_in):
            for j in range(c_out):
                if i % max(c_out, 1) == j or j % max(c_in, 1) == i:
                    w[i, j] = filt
        return jnp.asarray(w, dtype)
