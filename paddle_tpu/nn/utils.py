"""paddle_tpu.nn.utils — weight reparameterization helpers.

Parity: python/paddle/nn/utils/ in the reference (weight_norm.py,
spectral_norm_hook.py): wrap a layer's weight parameter so every forward
recomputes it from the reparameterized form.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..ops._primitive import primitive, unwrap
from ..tensor import Tensor

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm"]


def _norm_except(w, dim):
    """L2 norm over all axes but ``dim`` (keepdims); whole-tensor scalar
    norm when ``dim`` is None (reference weight_norm: norm_except_dim)."""
    if dim is None:
        return jnp.sqrt(jnp.sum(w * w))
    red = tuple(i for i in range(w.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(w * w, axis=red, keepdims=True))


def _g_broadcast(g, ndim, dim):
    """Reshape the stored g (vector [w.shape[dim]], or scalar for
    dim=None) to its keepdims broadcast shape."""
    if dim is None:
        return g
    shape = [1] * ndim
    shape[dim] = -1
    return g.reshape(shape)


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize ``layer.<name>`` as g * v / ||v|| (weight_norm op
    parity). Registers <name>_g and <name>_v; forward recomputes weight.
    ``dim=None`` normalizes over the whole tensor with a scalar g; else
    ``<name>_g`` is stored as a vector of length ``w.shape[dim]`` matching
    the reference's state-dict shape."""
    w = getattr(layer, name)
    arr = unwrap(w)
    if dim is not None and dim < 0:
        dim += arr.ndim
    g0 = _norm_except(arr, dim)
    if dim is not None:
        g0 = g0.reshape(-1)
    v = Tensor(arr, stop_gradient=False)
    g = Tensor(g0, stop_gradient=False)
    del layer._parameters[name]
    layer._parameters[name + "_v"] = v
    layer._parameters[name + "_g"] = g

    orig_forward = layer.forward

    @primitive
    def _compose(v, g):
        gb = _g_broadcast(g, v.ndim, dim)
        return gb * v / jnp.maximum(_norm_except(v, dim), 1e-12)

    def forward(*args, **kwargs):
        object.__setattr__(layer, "_wn_cache", _compose(
            layer._parameters[name + "_v"], layer._parameters[name + "_g"]))
        layer.__dict__[name] = layer._wn_cache
        try:
            return orig_forward(*args, **kwargs)
        finally:
            layer.__dict__.pop(name, None)

    layer.forward = forward
    layer._wn_name, layer._wn_dim, layer._wn_orig_forward = name, dim, orig_forward
    return layer


def remove_weight_norm(layer, name="weight"):
    """Fold g*v/||v|| back into a plain weight parameter."""
    v = layer._parameters.pop(name + "_v")
    g = layer._parameters.pop(name + "_g")
    dim = layer._wn_dim
    varr = unwrap(v)
    gb = _g_broadcast(unwrap(g), varr.ndim, dim)
    w = gb * varr / jnp.maximum(_norm_except(varr, dim), 1e-12)
    layer._parameters[name] = Tensor(w, stop_gradient=False)
    layer.forward = layer._wn_orig_forward
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12, dim=None):
    """Divide ``layer.<name>`` by its largest singular value each forward
    (spectral_norm op parity; power iteration state persists on the layer)."""
    from .layers.norm import SpectralNorm

    w = getattr(layer, name)
    if dim is None:
        dim = 0
    sn = SpectralNorm(list(unwrap(w).shape), dim=dim,
                      power_iters=n_power_iterations, eps=eps)
    layer.add_sublayer(name + "_spectral_norm", sn)
    orig_forward = layer.forward
    base = layer._parameters.pop(name)
    layer._parameters[name + "_orig"] = base

    def forward(*args, **kwargs):
        layer.__dict__[name] = sn(layer._parameters[name + "_orig"])
        try:
            return orig_forward(*args, **kwargs)
        finally:
            layer.__dict__.pop(name, None)

    layer.forward = forward
    return layer
