"""Loss layers. Parity: python/paddle/nn/layer/loss.py."""
from __future__ import annotations

from .. import functional as F
from ..layer import Layer

__all__ = [
    "SoftMarginLoss",
    "MultiMarginLoss",
    "TripletMarginLoss",
    "CosineEmbeddingLoss",
    "GaussianNLLLoss",
    "PoissonNLLLoss",
    "MultiLabelSoftMarginLoss",
    "CrossEntropyLoss", "MSELoss", "L1Loss", "NLLLoss", "BCELoss",
    "BCEWithLogitsLoss", "KLDivLoss", "SmoothL1Loss", "MarginRankingLoss",
    "CTCLoss", "HSigmoidLoss",
]


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", soft_label=False,
                 axis=-1, use_softmax=True, name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax

    def forward(self, input, label):  # noqa: A002
        return F.cross_entropy(
            input, label, weight=self.weight, ignore_index=self.ignore_index,
            reduction=self.reduction, soft_label=self.soft_label, axis=self.axis,
            use_softmax=self.use_softmax,
        )


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.nll_loss(input, label, self.weight, self.ignore_index, self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.binary_cross_entropy(input, label, self.weight, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None, name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, self.weight, self.reduction, self.pos_weight)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.kl_div(input, label, self.reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):  # noqa: A002
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):  # noqa: A002
        return F.margin_ranking_loss(input, other, label, self.margin, self.reduction)


class CTCLoss(Layer):
    """CTC loss (parity: warpctc op). Log-domain forward algorithm in jax."""

    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths, norm_by_times=False):
        import jax
        import jax.numpy as jnp

        from ...ops._primitive import primitive, unwrap

        blank = self.blank
        reduction = self.reduction

        @primitive
        def _ctc(log_probs, labels, input_lengths, label_lengths):
            # log_probs: [T, B, C] (paddle warpctc layout), labels: [B, L].
            # warpctc normalizes internally (softmax over C); log_softmax is
            # idempotent so pre-normalized inputs are unaffected
            log_probs = jax.nn.log_softmax(log_probs, axis=-1)
            T, B, C = log_probs.shape
            L = labels.shape[1]
            S = 2 * L + 1
            lbl = labels.astype(jnp.int32)
            ext = jnp.full((B, S), blank, jnp.int32)
            ext = ext.at[:, 1::2].set(lbl)
            neg_inf = jnp.asarray(-1e30, log_probs.dtype)

            lp0 = log_probs[0]  # [B, C]
            alpha0 = jnp.full((B, S), neg_inf, log_probs.dtype)
            alpha0 = alpha0.at[:, 0].set(lp0[:, blank])
            alpha0 = alpha0.at[:, 1].set(jnp.take_along_axis(lp0, ext[:, 1:2], axis=1)[:, 0])

            same = ext == jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-1)[:, :S]

            def step(alpha, lp):
                a1 = jnp.pad(alpha, ((0, 0), (1, 0)), constant_values=-1e30)[:, :S]
                a2 = jnp.pad(alpha, ((0, 0), (2, 0)), constant_values=-1e30)[:, :S]
                a2 = jnp.where(same, neg_inf, a2)
                m = jnp.maximum(jnp.maximum(alpha, a1), a2)
                m_safe = jnp.where(m <= -1e29, 0.0, m)
                s = (
                    jnp.exp(alpha - m_safe) + jnp.exp(a1 - m_safe) + jnp.exp(a2 - m_safe)
                )
                new = m_safe + jnp.log(jnp.maximum(s, 1e-37))
                new = jnp.where(m <= -1e29, neg_inf, new)
                emit = jnp.take_along_axis(lp, ext, axis=1)
                return new + emit, new + emit

            alphas_last, alphas = jax.lax.scan(step, alpha0, log_probs[1:])
            all_alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, B, S]
            t_idx = (input_lengths.astype(jnp.int32) - 1).clip(0)
            alpha_T = jnp.take_along_axis(
                all_alphas, t_idx[None, :, None].repeat(S, axis=2), axis=0
            )[0]  # [B, S]
            s_last = 2 * label_lengths.astype(jnp.int32)
            a_end = jnp.take_along_axis(alpha_T, s_last[:, None], axis=1)[:, 0]
            a_end2 = jnp.take_along_axis(alpha_T, (s_last - 1).clip(0)[:, None], axis=1)[:, 0]
            m = jnp.maximum(a_end, a_end2)
            ll = m + jnp.log(jnp.exp(a_end - m) + jnp.exp(a_end2 - m))
            loss = -ll
            if reduction == "mean":
                return jnp.mean(loss / label_lengths.astype(loss.dtype).clip(1))
            if reduction == "sum":
                return jnp.sum(loss)
            return loss

        return _ctc(log_probs, unwrap(labels), unwrap(input_lengths), unwrap(label_lengths))


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.soft_margin_loss(input, label, self.reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean", name=None):
        super().__init__()
        self.p, self.margin, self.weight = p, margin, weight
        self.reduction = reduction

    def forward(self, input, label):  # noqa: A002
        return F.multi_margin_loss(input, label, self.p, self.margin,
                                   self.weight, self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.margin, self.p, self.epsilon, self.swap = margin, p, epsilon, swap
        self.reduction = reduction

    def forward(self, input, positive, negative):  # noqa: A002
        return F.triplet_margin_loss(input, positive, negative, self.margin,
                                     self.p, self.epsilon, self.swap,
                                     self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin, self.reduction = margin, reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin,
                                       self.reduction)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean", name=None):
        super().__init__()
        self.full, self.epsilon, self.reduction = full, epsilon, reduction

    def forward(self, input, label, variance):  # noqa: A002
        return F.gaussian_nll_loss(input, label, variance, self.full,
                                   self.epsilon, self.reduction)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self.log_input, self.full = log_input, full
        self.epsilon, self.reduction = epsilon, reduction

    def forward(self, input, label):  # noqa: A002
        return F.poisson_nll_loss(input, label, self.log_input, self.full,
                                  self.epsilon, self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):  # noqa: A002
        return F.multi_label_soft_margin_loss(input, label, self.weight,
                                              self.reduction)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid (reference python/paddle/nn/layer/loss.py
    HSigmoidLoss over operators/hierarchical_sigmoid_op.h)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False, name=None):
        super().__init__()
        from .. import initializer as init_mod

        if not is_custom and num_classes < 2:
            raise ValueError("num_classes must be >= 2 for the default tree")
        self._num_classes = num_classes
        rows = num_classes - 1 if not is_custom else num_classes
        self.weight = self.create_parameter(
            [rows, feature_size], attr=weight_attr,
            default_initializer=init_mod.XavierNormal())
        self.bias = (None if bias_attr is False
                     else self.create_parameter([rows, 1], attr=bias_attr,
                                                is_bias=True))

    def forward(self, input, label, path_table=None, path_code=None):  # noqa: A002
        return F.hsigmoid_loss(input, label, self._num_classes, self.weight,
                               self.bias, path_table, path_code)
