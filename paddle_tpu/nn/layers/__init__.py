from . import activation, common, conv, loss, norm, pooling, rnn, transformer  # noqa: F401
