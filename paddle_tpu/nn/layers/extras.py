"""Small layers completing the reference nn surface: Bilinear,
PairwiseDistance, MaxUnPool2D, Unfold, LayerDict (parity:
python/paddle/nn/layer/common.py Bilinear/Unfold, distance.py
PairwiseDistance, pooling.py MaxUnPool2D, container.py LayerDict)."""
from __future__ import annotations

from collections import OrderedDict

import numpy as np

from .. import functional as F
from .. import initializer as init_mod
from ..layer import Layer

__all__ = ["Bilinear", "PairwiseDistance", "MaxUnPool2D", "Unfold", "LayerDict"]


class Bilinear(Layer):
    """out = x1 @ W @ x2 + b per output feature."""

    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        bound = float(np.sqrt(1.0 / in1_features))
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr,
            default_initializer=init_mod.Uniform(-bound, bound))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([out_features], attr=bias_attr,
                                              is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class PairwiseDistance(Layer):
    """p-norm distance between paired rows."""

    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        import jax.numpy as jnp

        from ...ops._primitive import primitive

        p, eps, keep = self.p, self.epsilon, self.keepdim

        @primitive
        def _pd(x, y):
            d = x - y + eps
            return jnp.sum(jnp.abs(d) ** p, axis=-1, keepdims=keep) ** (1.0 / p)

        return _pd(x, y)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCHW",
                 output_size=None, name=None):
        super().__init__()
        self._args = (kernel_size, stride, padding, output_size, data_format)

    def forward(self, x, indices):
        ks, st, pd, osz, df = self._args
        return F.max_unpool2d(x, indices, ks, st, pd, osz, df)


class Unfold(Layer):
    """im2col sliding-window extraction (layer over F.unfold)."""

    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self._args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        ks, st, pd, dl = self._args
        return F.unfold(x, ks, st, pd, dl)


class LayerDict(Layer):
    """Ordered string->Layer container (parity: nn.LayerDict)."""

    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, sublayer):
        self.add_sublayer(key, sublayer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def clear(self):
        self._sub_layers.clear()

    def pop(self, key):
        v = self._sub_layers[key]
        del self._sub_layers[key]
        return v

    def keys(self):
        return self._sub_layers.keys()

    def items(self):
        return self._sub_layers.items()

    def values(self):
        return self._sub_layers.values()

    def update(self, sublayers):
        if isinstance(sublayers, (OrderedDict, dict)):
            items = sublayers.items()
        else:
            items = sublayers
        for k, v in items:
            self[k] = v
        return self
