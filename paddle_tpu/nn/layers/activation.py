"""Activation layers. Parity: python/paddle/nn/layer/activation.py."""
from __future__ import annotations

from .. import functional as F
from .. import initializer as init_mod
from ..layer import Layer

__all__ = [
    "RReLU",
    "ReLU", "ReLU6", "LeakyReLU", "PReLU", "ELU", "SELU", "CELU", "GELU",
    "Sigmoid", "LogSigmoid", "Tanh", "Tanhshrink", "Hardshrink", "Softshrink",
    "Hardsigmoid", "Hardswish", "Hardtanh", "Softplus", "Softsign", "Swish",
    "SiLU", "Mish", "Maxout", "Softmax", "LogSoftmax", "ThresholdedReLU",
]


def _simple(fn_name, **fixed):
    class _Act(Layer):
        def __init__(self, name=None):
            super().__init__()

        def forward(self, x):
            return getattr(F, fn_name)(x, **fixed)

    return _Act


ReLU = _simple("relu")
ReLU6 = _simple("relu6")
Sigmoid = _simple("sigmoid")
LogSigmoid = _simple("log_sigmoid")
Tanh = _simple("tanh")
Tanhshrink = _simple("tanhshrink")
Softsign = _simple("softsign")
Swish = _simple("swish")
SiLU = _simple("silu")
Mish = _simple("mish")
Hardswish = _simple("hardswish")

for _cls, _n in ((ReLU, "ReLU"), (ReLU6, "ReLU6"), (Sigmoid, "Sigmoid"),
                 (LogSigmoid, "LogSigmoid"), (Tanh, "Tanh"), (Tanhshrink, "Tanhshrink"),
                 (Softsign, "Softsign"), (Swish, "Swish"), (SiLU, "SiLU"),
                 (Mish, "Mish"), (Hardswish, "Hardswish")):
    _cls.__name__ = _n
    _cls.__qualname__ = _n


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self.negative_slope)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr, default_initializer=init_mod.Constant(init)
        )

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.elu(x, self.alpha)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
        super().__init__()
        self.scale, self.alpha = scale, alpha

    def forward(self, x):
        return F.selu(x, self.scale, self.alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return F.celu(x, self.alpha)


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self.approximate = approximate

    def forward(self, x):
        return F.gelu(x, self.approximate)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.hardshrink(x, self.threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return F.softshrink(x, self.threshold)


class Hardsigmoid(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.hardsigmoid(x)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):  # noqa: A002
        super().__init__()
        self.min, self.max = min, max

    def forward(self, x):
        return F.hardtanh(x, self.min, self.max)


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0, name=None):
        super().__init__()
        self.beta, self.threshold = beta, threshold

    def forward(self, x):
        return F.softplus(x, self.beta, self.threshold)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups, self.axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self.groups, self.axis)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, self.axis)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        import jax.numpy as jnp

        from ...ops._primitive import primitive

        thr = self.threshold

        @primitive
        def _tr(x):
            return jnp.where(x > thr, x, 0.0)

        return _tr(x)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)
