"""Convolution layers. Parity: python/paddle/nn/layer/conv.py."""
from __future__ import annotations

import numpy as np

from .. import functional as F
from .. import initializer as init_mod
from ..layer import Layer

__all__ = [
    "Conv1D",
    "Conv2D",
    "Conv3D",
    "Conv1DTranspose",
    "Conv2DTranspose",
    "Conv3DTranspose",
]


def _ntuple(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(i) for i in v)


class _ConvNd(Layer):
    def __init__(
        self,
        in_channels,
        out_channels,
        kernel_size,
        nd,
        stride=1,
        padding=0,
        dilation=1,
        groups=1,
        padding_mode="zeros",
        weight_attr=None,
        bias_attr=None,
        data_format=None,
        transpose=False,
        output_padding=0,
    ):
        super().__init__()
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _ntuple(kernel_size, nd)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._nd = nd
        self._data_format = data_format
        self._transpose = transpose
        self._output_padding = output_padding
        if transpose:
            w_shape = [in_channels, out_channels // groups, *self._kernel_size]
        else:
            w_shape = [out_channels, in_channels // groups, *self._kernel_size]
        fan_in = (in_channels // groups) * int(np.prod(self._kernel_size))
        self.weight = self.create_parameter(
            w_shape,
            attr=weight_attr,
            default_initializer=init_mod.Uniform(-np.sqrt(1.0 / fan_in), np.sqrt(1.0 / fan_in)),
        )
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([out_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        if not self._transpose:
            fn = {1: F.conv1d, 2: F.conv2d, 3: F.conv3d}[self._nd]
            return fn(
                x, self.weight, self.bias, self._stride, self._padding, self._dilation,
                self._groups, self._data_format,
            )
        fn = {1: F.conv1d_transpose, 2: F.conv2d_transpose, 3: F.conv3d_transpose}[self._nd]
        return fn(
            x, self.weight, self.bias, self._stride, self._padding, self._output_padding,
            self._groups, self._dilation, self._data_format,
        )


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, dilation=1,
                 groups=1, padding_mode="zeros", weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding, dilation,
                         groups, padding_mode, weight_attr, bias_attr, data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, dilation=1,
                 groups=1, padding_mode="zeros", weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding, dilation,
                         groups, padding_mode, weight_attr, bias_attr, data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, dilation=1,
                 groups=1, padding_mode="zeros", weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding, dilation,
                         groups, padding_mode, weight_attr, bias_attr, data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, output_padding=0,
                 groups=1, dilation=1, weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding, dilation,
                         groups, "zeros", weight_attr, bias_attr, data_format, True, output_padding)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, output_padding=0,
                 groups=1, dilation=1, weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding, dilation,
                         groups, "zeros", weight_attr, bias_attr, data_format, True, output_padding)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0, output_padding=0,
                 groups=1, dilation=1, weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding, dilation,
                         groups, "zeros", weight_attr, bias_attr, data_format, True, output_padding)
