"""Recurrent layers.

Parity: python/paddle/nn/layer/rnn.py (SimpleRNNCell/LSTMCell/GRUCell, RNN,
BiRNN, SimpleRNN/LSTM/GRU multi-layer stacks) over the reference's
cudnn-backed rnn_op (/root/reference/paddle/fluid/operators/rnn_op.cu.cc).

TPU-native: the time loop is ``jax.lax.scan`` (compiles to one fused while
loop on TPU); gate matmuls batch onto the MXU. Weight layout matches paddle:
weight_ih [gates*hidden, input], weight_hh [gates*hidden, hidden].
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...ops._primitive import primitive, unwrap, wrap
from .. import initializer as init_mod
from ..layer import Layer

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "BiRNN", "SimpleRNN", "LSTM", "GRU"]


def _cell_step(mode, x, h, c, w_ih, w_hh, b_ih, b_hh, activation="tanh"):
    if mode == "GRU":
        # paddle GRU: r,z,c gate layout with separate hh bias on candidate
        xr, xz, xc = jnp.split(x @ w_ih.T + (b_ih if b_ih is not None else 0.0), 3, axis=-1)
        hr, hz, hc = jnp.split(h @ w_hh.T + (b_hh if b_hh is not None else 0.0), 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        cand = jnp.tanh(xc + r * hc)
        h_new = (1.0 - z) * cand + z * h
        return h_new, None
    gates = x @ w_ih.T + h @ w_hh.T
    if b_ih is not None:
        gates = gates + b_ih
    if b_hh is not None:
        gates = gates + b_hh
    if mode == "LSTM":
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, c_new
    act = jnp.tanh if activation == "tanh" else jax.nn.relu
    h_new = act(gates)
    return h_new, None


class _RNNCellBase(Layer):
    def state_shape(self):
        raise NotImplementedError

    def get_initial_states(self, batch_ref, shape=None, dtype="float32", init_value=0.0, batch_dim_idx=0):
        from ...ops import creation

        batch = unwrap(batch_ref).shape[batch_dim_idx]
        shapes = shape or self.state_shape()
        if isinstance(shapes, tuple):
            return tuple(creation.full([batch] + list(s), init_value, dtype) for s in shapes)
        return creation.full([batch] + list(shapes), init_value, dtype)


class SimpleRNNCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh", weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        std = 1.0 / np.sqrt(hidden_size)
        u = init_mod.Uniform(-std, std)
        self.weight_ih = self.create_parameter([hidden_size, input_size], weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter([hidden_size, hidden_size], weight_hh_attr, default_initializer=u)
        self.bias_ih = None if bias_ih_attr is False else self.create_parameter([hidden_size], bias_ih_attr, default_initializer=u)
        self.bias_hh = None if bias_hh_attr is False else self.create_parameter([hidden_size], bias_hh_attr, default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = self.activation

        @primitive
        def _step(x, h, w_ih, w_hh, b_ih, b_hh):
            h_new, _ = _cell_step("RNN", x, h, None, w_ih, w_hh, b_ih, b_hh, act)
            return h_new

        h = _step(inputs, states, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh)
        return h, h

    def state_shape(self):
        return [self.hidden_size]


class LSTMCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        std = 1.0 / np.sqrt(hidden_size)
        u = init_mod.Uniform(-std, std)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size], weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size], weight_hh_attr, default_initializer=u)
        self.bias_ih = None if bias_ih_attr is False else self.create_parameter([4 * hidden_size], bias_ih_attr, default_initializer=u)
        self.bias_hh = None if bias_hh_attr is False else self.create_parameter([4 * hidden_size], bias_hh_attr, default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, c = states

        @primitive
        def _step(x, h, c, w_ih, w_hh, b_ih, b_hh):
            return _cell_step("LSTM", x, h, c, w_ih, w_hh, b_ih, b_hh)

        h_new, c_new = _step(inputs, h, c, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh)
        return h_new, (h_new, c_new)

    def state_shape(self):
        return ([self.hidden_size], [self.hidden_size])


class GRUCell(_RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        std = 1.0 / np.sqrt(hidden_size)
        u = init_mod.Uniform(-std, std)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size], weight_ih_attr, default_initializer=u)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size], weight_hh_attr, default_initializer=u)
        self.bias_ih = None if bias_ih_attr is False else self.create_parameter([3 * hidden_size], bias_ih_attr, default_initializer=u)
        self.bias_hh = None if bias_hh_attr is False else self.create_parameter([3 * hidden_size], bias_hh_attr, default_initializer=u)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        @primitive
        def _step(x, h, w_ih, w_hh, b_ih, b_hh):
            h_new, _ = _cell_step("GRU", x, h, None, w_ih, w_hh, b_ih, b_hh)
            return h_new

        h = _step(inputs, states, self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh)
        return h, h

    def state_shape(self):
        return [self.hidden_size]


class RNN(Layer):
    """Wrap a cell into a scan over time (parity: nn.RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops import manipulation as manip

        x = inputs if self.time_major else manip.transpose(inputs, [1, 0, 2])
        steps = x.shape[0]
        if self.is_reverse:
            x = manip.flip(x, [0])
        states = initial_states if initial_states is not None else self.cell.get_initial_states(
            inputs, batch_dim_idx=1 if self.time_major else 0
        )
        outs = []
        for t in range(steps):
            out, states = self.cell(x[t], states)
            outs.append(out)
        y = manip.stack(outs, axis=0)
        if self.is_reverse:
            y = manip.flip(y, [0])
        if not self.time_major:
            y = manip.transpose(y, [1, 0, 2])
        return y, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops import manipulation as manip

        s_fw, s_bw = (None, None) if initial_states is None else initial_states
        y_fw, fs = self.rnn_fw(inputs, s_fw, sequence_length)
        y_bw, bs = self.rnn_bw(inputs, s_bw, sequence_length)
        return manip.concat([y_fw, y_bw], axis=-1), (fs, bs)


class _RNNBase(Layer):
    """Multi-layer (bi)directional stack executed as a single lax.scan per
    layer/direction inside one primitive — the TPU-fast path."""

    def __init__(self, mode, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirectional = direction in ("bidirect", "bidirectional")
        self.num_directions = 2 if self.bidirectional else 1
        gates = {"LSTM": 4, "GRU": 3, "RNN_TANH": 1, "RNN_RELU": 1}[mode]
        std = 1.0 / np.sqrt(hidden_size)
        u = init_mod.Uniform(-std, std)
        self._weights = []
        for layer in range(num_layers):
            for direction in range(self.num_directions):
                in_size = input_size if layer == 0 else hidden_size * self.num_directions
                sfx = f"l{layer}" + ("_reverse" if direction else "")
                w_ih = self.create_parameter([gates * hidden_size, in_size], weight_ih_attr, default_initializer=u)
                w_hh = self.create_parameter([gates * hidden_size, hidden_size], weight_hh_attr, default_initializer=u)
                b_ih = None if bias_ih_attr is False else self.create_parameter(
                    [gates * hidden_size], bias_ih_attr, default_initializer=u
                )
                b_hh = None if bias_hh_attr is False else self.create_parameter(
                    [gates * hidden_size], bias_hh_attr, default_initializer=u
                )
                self.add_parameter(f"weight_ih_{sfx}", w_ih)
                self.add_parameter(f"weight_hh_{sfx}", w_hh)
                if b_ih is not None:
                    self.add_parameter(f"bias_ih_{sfx}", b_ih)
                if b_hh is not None:
                    self.add_parameter(f"bias_hh_{sfx}", b_hh)
                self._weights.append((w_ih, w_hh, b_ih, b_hh))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        mode = "RNN" if self.mode.startswith("RNN") else self.mode
        activation = "relu" if self.mode == "RNN_RELU" else "tanh"
        nl, nd, hs = self.num_layers, self.num_directions, self.hidden_size
        time_major = self.time_major
        is_lstm = mode == "LSTM"
        dropout = self.dropout if self.training else 0.0

        batch = unwrap(inputs).shape[1 if time_major else 0]
        if initial_states is None:
            from ...ops import creation

            z = creation.zeros([nl * nd, batch, hs], "float32")
            initial_states = (z, creation.zeros([nl * nd, batch, hs], "float32")) if is_lstm else z
        h0 = initial_states[0] if is_lstm else initial_states
        c0 = initial_states[1] if is_lstm else None

        drop_keys = [jax.random.key(0)] * 0
        if dropout > 0.0:
            from ...random import split_key

            drop_keys = [split_key() for _ in range(nl - 1)]

        flat_w = [w for tup in self._weights for w in tup]

        @primitive(aux=0)
        def _run(x, h0, c0, *weights):
            xs = x if time_major else jnp.swapaxes(x, 0, 1)  # [T, B, F]
            layer_in = xs
            h_finals, c_finals = [], []
            for layer in range(nl):
                outs_dir = []
                for d in range(nd):
                    wi = (layer * nd + d) * 4
                    w_ih, w_hh, b_ih, b_hh = weights[wi : wi + 4]
                    idx = layer * nd + d
                    h_init = h0[idx]
                    c_init = c0[idx] if is_lstm else jnp.zeros_like(h0[idx])
                    seq = jnp.flip(layer_in, 0) if d == 1 else layer_in

                    def step(carry, x_t, w_ih=w_ih, w_hh=w_hh, b_ih=b_ih, b_hh=b_hh):
                        h, c = carry
                        h_new, c_new = _cell_step(mode, x_t, h, c, w_ih, w_hh, b_ih, b_hh, activation)
                        c_new = c_new if c_new is not None else c
                        return (h_new, c_new), h_new

                    (h_f, c_f), ys = jax.lax.scan(step, (h_init, c_init), seq)
                    if d == 1:
                        ys = jnp.flip(ys, 0)
                    outs_dir.append(ys)
                    h_finals.append(h_f)
                    c_finals.append(c_f)
                layer_in = jnp.concatenate(outs_dir, axis=-1) if nd == 2 else outs_dir[0]
                if dropout > 0.0 and layer < nl - 1:
                    keep = jax.random.bernoulli(drop_keys[layer], 1.0 - dropout, layer_in.shape)
                    layer_in = jnp.where(keep, layer_in / (1.0 - dropout), 0.0)
            y = layer_in if time_major else jnp.swapaxes(layer_in, 0, 1)
            h_out = jnp.stack(h_finals, 0)
            if is_lstm:
                return y, h_out, jnp.stack(c_finals, 0)
            return y, h_out

        if is_lstm:
            y, h_n, c_n = _run(inputs, h0, c0, *flat_w)
            return y, (h_n, c_n)
        y, h_n = _run(inputs, h0, c0, *flat_w)
        return y, h_n


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh", **kwargs):
        mode = "RNN_RELU" if activation == "relu" else "RNN_TANH"
        super().__init__(mode, input_size, hidden_size, num_layers, direction, time_major, dropout, **kwargs)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers, direction, time_major, dropout, **kwargs)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers, direction, time_major, dropout, **kwargs)
