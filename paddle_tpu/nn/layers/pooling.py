"""Pooling layers. Parity: python/paddle/nn/layer/pooling.py."""
from __future__ import annotations

from .. import functional as F
from ..layer import Layer

__all__ = [
    "MaxPool1D", "MaxPool2D", "MaxPool3D",
    "AvgPool1D", "AvgPool2D", "AvgPool3D",
    "AdaptiveAvgPool1D", "AdaptiveAvgPool2D", "AdaptiveAvgPool3D",
    "AdaptiveMaxPool1D", "AdaptiveMaxPool2D", "AdaptiveMaxPool3D",
]


class _Pool(Layer):
    _fn = None

    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False, return_mask=False,
                 exclusive=True, divisor_override=None, data_format=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.data_format = data_format

    def forward(self, x):
        return type(self)._fn(x, self.kernel_size, self.stride, self.padding,
                              ceil_mode=self.ceil_mode, data_format=self.data_format)


class MaxPool1D(_Pool):
    _fn = staticmethod(F.max_pool1d)

    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False, name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, data_format="NCL")


class MaxPool2D(_Pool):
    _fn = staticmethod(F.max_pool2d)

    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
                 data_format="NCHW", name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, data_format=data_format)


class MaxPool3D(_Pool):
    _fn = staticmethod(F.max_pool3d)

    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
                 data_format="NCDHW", name=None):
        super().__init__(kernel_size, stride, padding, ceil_mode, data_format=data_format)


class _AvgPool(Layer):
    _fn = None

    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False,
                 divisor_override=None, data_format=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.exclusive = exclusive
        self.ceil_mode = ceil_mode
        self.data_format = data_format

    def forward(self, x):
        return type(self)._fn(x, self.kernel_size, self.stride, self.padding,
                              ceil_mode=self.ceil_mode, exclusive=self.exclusive,
                              data_format=self.data_format)


class AvgPool1D(_AvgPool):
    @staticmethod
    def _fn(x, k, s, p, ceil_mode=False, exclusive=True, data_format="NCL"):
        return F.avg_pool1d(x, k, s, p, exclusive=exclusive, ceil_mode=ceil_mode, data_format=data_format)

    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, name=None):
        super().__init__(kernel_size, stride, padding, exclusive, ceil_mode, data_format="NCL")


class AvgPool2D(_AvgPool):
    _fn = staticmethod(F.avg_pool2d)

    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
                 divisor_override=None, data_format="NCHW", name=None):
        super().__init__(kernel_size, stride, padding, exclusive, ceil_mode, data_format=data_format)


class AvgPool3D(_AvgPool):
    _fn = staticmethod(F.avg_pool3d)

    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
                 divisor_override=None, data_format="NCDHW", name=None):
        super().__init__(kernel_size, stride, padding, exclusive, ceil_mode, data_format=data_format)


class _AdaptivePool(Layer):
    _fn = None

    def __init__(self, output_size, data_format=None, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return type(self)._fn(x, self.output_size, data_format=self.data_format)


class AdaptiveAvgPool1D(_AdaptivePool):
    _fn = staticmethod(F.adaptive_avg_pool1d)

    def __init__(self, output_size, name=None):
        super().__init__(output_size, "NCL")


class AdaptiveAvgPool2D(_AdaptivePool):
    _fn = staticmethod(F.adaptive_avg_pool2d)

    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__(output_size, data_format)


class AdaptiveAvgPool3D(_AdaptivePool):
    _fn = staticmethod(F.adaptive_avg_pool3d)

    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__(output_size, data_format)


class AdaptiveMaxPool1D(_AdaptivePool):
    @staticmethod
    def _fn(x, output_size, data_format="NCL"):
        return F.adaptive_max_pool1d(x, output_size, data_format=data_format)

    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(output_size, "NCL")


class AdaptiveMaxPool2D(_AdaptivePool):
    @staticmethod
    def _fn(x, output_size, data_format="NCHW"):
        return F.adaptive_max_pool2d(x, output_size, data_format=data_format)

    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(output_size, "NCHW")


class AdaptiveMaxPool3D(_AdaptivePool):
    @staticmethod
    def _fn(x, output_size, data_format="NCDHW"):
        return F.adaptive_max_pool3d(x, output_size, data_format=data_format)

    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__(output_size, "NCDHW")
