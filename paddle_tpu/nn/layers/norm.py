"""Normalization layers. Parity: python/paddle/nn/layer/norm.py.

SyncBatchNorm note: under SPMD jit over a data-parallel mesh axis, XLA already
computes batch statistics over the *global* batch when the reduction is traced
inside shard_map/pjit with the batch dim sharded — SyncBatchNorm is therefore
an annotation-level concern on TPU, not a separate NCCL kernel like the
reference's sync_batch_norm_op.cu. The class is kept for API parity and
optionally psums stats when run inside shard_map.
"""
from __future__ import annotations

from ...tensor import Tensor
from .. import functional as F
from .. import initializer as init_mod
from ..layer import Layer

__all__ = [
    "BatchNorm",
    "BatchNorm1D",
    "BatchNorm2D",
    "BatchNorm3D",
    "SyncBatchNorm",
    "LayerNorm",
    "GroupNorm",
    "InstanceNorm1D",
    "InstanceNorm2D",
    "InstanceNorm3D",
    "LocalResponseNorm",
    "SpectralNorm",
]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr, default_initializer=init_mod.Constant(1.0)
            )
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([num_features], attr=bias_attr, is_bias=True)
        import jax.numpy as jnp

        self.register_buffer("_mean", Tensor(jnp.zeros(num_features, jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features, jnp.float32)))

    def forward(self, x):
        return F.batch_norm(
            x,
            self._mean,
            self._variance,
            self.weight,
            self.bias,
            training=self.training,
            momentum=self._momentum,
            epsilon=self._epsilon,
            data_format=self._data_format,
            use_global_stats=self._use_global_stats,
        )


class BatchNorm(_BatchNormBase):
    """Legacy fluid.dygraph.BatchNorm signature kept for parity."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5, **kwargs):
        super().__init__(num_channels, momentum=momentum, epsilon=epsilon)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act == "relu":
            out = F.relu(out)
        elif self._act == "sigmoid":
            out = F.sigmoid(out)
        return out


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCL", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         "NCL" if data_format in ("NCL", "NC") else data_format, use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """See module docstring: stats are global under SPMD tracing."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            new = SyncBatchNorm(layer._num_features, layer._momentum, layer._epsilon,
                                data_format=layer._data_format)
            if layer.weight is not None:
                new.weight.set_value(layer.weight)
            if layer.bias is not None:
                new.bias.set_value(layer.bias)
            new._mean.set_value(layer._mean)
            new._variance.set_value(layer._variance)
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr, default_initializer=init_mod.Constant(1.0)
            )
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None, bias_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._num_channels = num_channels
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                [num_channels], attr=weight_attr, default_initializer=init_mod.Constant(1.0)
            )
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight, self.bias)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False:
            self.scale = None
        else:
            self.scale = self.create_parameter(
                [num_features], attr=weight_attr, default_initializer=init_mod.Constant(1.0)
            )
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias, eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta, self.k, self.data_format)


class SpectralNorm(Layer):
    """Parity: spectral_norm op — power-iteration weight normalization."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12, name=None):
        super().__init__()
        import jax.numpy as jnp

        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        self.weight_u = self.create_parameter([h], default_initializer=init_mod.Normal(0, 1))
        self.weight_v = self.create_parameter([w], default_initializer=init_mod.Normal(0, 1))
        self.weight_u.stop_gradient = True
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        import jax
        import jax.numpy as jnp

        from ...ops._primitive import primitive, unwrap

        dim, eps, iters = self._dim, self._eps, self._power_iters
        # power iteration advances OUTSIDE the grad graph and persists
        # (parity: reference spectral_norm keeps U/V across forwards)
        w_arr = jax.lax.stop_gradient(unwrap(weight))
        wm_ng = jnp.moveaxis(w_arr, dim, 0).reshape(w_arr.shape[dim], -1)
        u, v = self.weight_u._data, self.weight_v._data
        for _ in range(iters):
            v = wm_ng.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = wm_ng @ v
            u = u / (jnp.linalg.norm(u) + eps)
        self.weight_u._set_data(u)
        self.weight_v._set_data(v)

        @primitive
        def _sn(weight):
            wm = jnp.moveaxis(weight, dim, 0).reshape(weight.shape[dim], -1)
            sigma = u @ wm @ v
            return weight / sigma

        return _sn(weight)
