"""Layer — the module system.

Parity: the reference dygraph ``Layer``
(/root/reference/python/paddle/fluid/dygraph/layers.py — sublayer registry,
parameter registry, forward pre/post hooks, state_dict/set_state_dict,
train/eval, apply, buffers) and ``ParamBase``
(framework.py ParamBase over VarBase).

TPU-native notes: a Layer is also a pytree-convertible parameter container —
``layer.state_pytree()`` / ``functional_call`` bridge eager Layers into pure
``jit``/``pjit`` train steps (this replaces the reference's
program-translation path as the performance story).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Iterator, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..dtype import to_jax_dtype
from ..tensor import Tensor
from . import initializer as init_mod
from .param_attr import ParamAttr

__all__ = ["Layer", "Parameter", "Sequential", "LayerList", "ParameterList"]


class Parameter(Tensor):
    """Trainable tensor (parity: framework.py ParamBase)."""

    def __init__(self, data, trainable: bool = True, name: Optional[str] = None):
        super().__init__(data, stop_gradient=not trainable, name=name)
        self.persistable = True
        self.trainable = trainable

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


_layer_counter = {}


def _unique_name(prefix: str) -> str:
    idx = _layer_counter.get(prefix, 0)
    _layer_counter[prefix] = idx + 1
    return f"{prefix}_{idx}"


class HookRemoveHelper:
    def __init__(self, hooks: OrderedDict, idx: int):
        self._hooks = hooks
        self._idx = idx

    def remove(self):
        self._hooks.pop(self._idx, None)


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype="float32"):
        object.__setattr__(self, "_parameters", OrderedDict())
        object.__setattr__(self, "_sub_layers", OrderedDict())
        object.__setattr__(self, "_buffers", OrderedDict())
        object.__setattr__(self, "_non_persistable_buffer_names", set())
        self.training = True
        self._dtype = dtype
        self._full_name = _unique_name(name_scope or type(self).__name__.lower())
        self._forward_pre_hooks: OrderedDict = OrderedDict()
        self._forward_post_hooks: OrderedDict = OrderedDict()
        self._hook_counter = 0

    # ------------------------------------------------------------------
    # registration
    # ------------------------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        subs = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if subs is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            subs[name] = value
            self.__dict__.pop(name, None)
            # structure changed ANYWHERE: bump the global version so every
            # layer's eager-jit caches (including ancestors whose cached
            # sublayer walks contain this subtree) revalidate
            _bump_structure_version()
        else:
            if params is not None and name in params:
                if value is None:
                    del params[name]
                else:
                    raise TypeError(f"cannot assign non-Parameter to parameter {name}")
            elif subs is not None and name in subs and value is None:
                del subs[name]
            elif buffers is not None and name in buffers:
                if value is None:
                    del buffers[name]
                else:
                    buffers[name] = value if isinstance(value, Tensor) else Tensor(value)
                    return
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__} has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def add_sublayer(self, name: str, sublayer: "Layer") -> "Layer":
        self._sub_layers[str(name)] = sublayer
        _bump_structure_version()
        return sublayer

    def add_parameter(self, name: str, parameter: Optional[Parameter]) -> Optional[Parameter]:
        if parameter is not None:
            self._parameters[str(name)] = parameter
        return parameter

    def register_buffer(self, name: str, tensor, persistable: bool = True):
        t = tensor if isinstance(tensor, Tensor) or tensor is None else Tensor(tensor)
        self._buffers[str(name)] = t
        if not persistable:
            self._non_persistable_buffer_names.add(str(name))
        return t

    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias: bool = False,
        default_initializer=None,
    ) -> Parameter:
        """Parity: Layer.create_parameter (layers.py). ParamAttr carries name /
        initializer / trainable / learning-rate scaling."""
        attr = ParamAttr._to_attr(attr)
        dtype = to_jax_dtype(dtype or self._dtype)
        init = None
        if attr is not None and attr.initializer is not None:
            init = attr.initializer
        elif default_initializer is not None:
            init = default_initializer
        else:
            init = init_mod.Constant(0.0) if is_bias else init_mod.XavierNormal()
        # initializers always run eagerly — under static mode they play the
        # startup-program role (params exist before Executor.run)
        from ..static.program import dygraph_guard

        if init_mod.abstract_init_active():
            # planner lowering path: a shape/dtype spec instead of a
            # materialized array — full-size models become constructible
            # without allocating (analysis/plan.py candidate lowering)
            import jax as _jax

            data = _jax.ShapeDtypeStruct(
                tuple(int(s) for s in shape), np.dtype(dtype))
        else:
            with dygraph_guard():
                data = init(tuple(int(s) for s in shape), dtype)
        p = Parameter(data, trainable=(attr.trainable if attr else True))
        p.name = attr.name if attr and attr.name else _unique_name(self._full_name + ".w")
        if attr is not None:
            p.optimize_attr = {"learning_rate": attr.learning_rate}
            p.regularizer = attr.regularizer
            p.need_clip = attr.need_clip
        else:
            p.optimize_attr = {"learning_rate": 1.0}
            p.regularizer = None
            p.need_clip = True
        return p

    # ------------------------------------------------------------------
    # forward
    # ------------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError(f"{type(self).__name__}.forward not implemented")

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        if _jit_forward_applicable(self, inputs, kwargs):
            outputs = _jit_forward_call(self, inputs)
        else:
            outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            out = hook(self, inputs, outputs)
            if out is not None:
                outputs = out
        return outputs

    def register_forward_pre_hook(self, hook: Callable) -> HookRemoveHelper:
        self._hook_counter += 1
        self._forward_pre_hooks[self._hook_counter] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_counter)

    def register_forward_post_hook(self, hook: Callable) -> HookRemoveHelper:
        self._hook_counter += 1
        self._forward_post_hooks[self._hook_counter] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_counter)

    # ------------------------------------------------------------------
    # traversal
    # ------------------------------------------------------------------
    def parameters(self, include_sublayers: bool = True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(
        self, prefix: str = "", include_sublayers: bool = True
    ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for layer_name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for pname, p in layer._parameters.items():
                if id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{layer_name}.{pname}" if layer_name else pname), p

    def sublayers(self, include_self: bool = False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(
        self, prefix: str = "", include_self: bool = False, layers_set=None
    ) -> Iterator[Tuple[str, "Layer"]]:
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from sub.named_sublayers(prefix=sub_prefix, include_self=True, layers_set=layers_set)

    def children(self):
        return [l for _, l in self.named_children()]

    def named_children(self):
        for name, sub in self._sub_layers.items():
            if sub is not None:
                yield name, sub

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True):
        seen = set()
        for layer_name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{layer_name}.{bname}" if layer_name else bname), b

    def buffers(self, include_sublayers: bool = True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    # ------------------------------------------------------------------
    # modes / functional
    # ------------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    def apply(self, fn: Callable[["Layer"], None]):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    def to(self, device=None, dtype=None, blocking=None):  # noqa: ARG002
        if dtype is not None:
            jdt = to_jax_dtype(dtype)
            for _, p in self.named_parameters():
                if jnp.issubdtype(p._data.dtype, jnp.floating):
                    p._set_data(p._data.astype(jdt))
            for _, b in self.named_buffers():
                if jnp.issubdtype(b._data.dtype, jnp.floating):
                    b._set_data(b._data.astype(jdt))
        if device is not None:
            import jax as _jax

            from ..device import _place_from

            dev = _place_from(device).jax_device()
            for _, p in self.named_parameters():
                p._set_data(_jax.device_put(p._data, dev))
            for _, b in self.named_buffers():
                b._set_data(_jax.device_put(b._data, dev))
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def full_name(self):
        return self._full_name

    # ------------------------------------------------------------------
    # state dict
    # ------------------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers: bool = True, use_hook=True):
        dest = destination if destination is not None else OrderedDict()
        for name, p in self.named_parameters(include_sublayers=include_sublayers):
            dest[name] = p
        for layer_name, layer in self.named_sublayers(include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                key = f"{layer_name}.{bname}" if layer_name else bname
                dest[key] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name: bool = True):
        own = self.state_dict()
        missing = []
        for name, t in own.items():
            if name not in state_dict:
                missing.append(name)
                continue
            src = state_dict[name]
            arr = src._data if isinstance(src, Tensor) else jnp.asarray(np.asarray(src))
            if tuple(arr.shape) != tuple(t._data.shape):
                raise ValueError(
                    f"shape mismatch for {name}: {tuple(arr.shape)} vs {tuple(t._data.shape)}"
                )
            t._set_data(arr.astype(t._data.dtype))
        unexpected = [k for k in state_dict if k not in own]
        return missing, unexpected

    load_dict = set_state_dict

    # ------------------------------------------------------------------
    # pytree bridge for jit/pjit training (TPU-native extension)
    # ------------------------------------------------------------------
    def state_pytree(self, trainable_only: bool = False):
        """Return {name: jax.Array} of params (+buffers unless trainable_only)."""
        out = {}
        for name, p in self.named_parameters():
            if trainable_only and p.stop_gradient:
                continue
            out[name] = p._data
        if not trainable_only:
            for name, b in self.named_buffers():
                out[f"buffer:{name}"] = b._data
        return out

    def load_state_pytree(self, tree):
        params = dict(self.named_parameters())
        buffers = dict(self.named_buffers())
        for name, arr in tree.items():
            if name.startswith("buffer:"):
                buffers[name[len("buffer:"):]]._set_data(arr)
            else:
                params[name]._set_data(arr)

    def functional_call_with_state(self, params_tree, buffers_tree, *inputs, _call_fn=None, **kwargs):
        """Pure-style call for jit tracing: swap params+buffers in, run
        forward, read back mutated buffer values (BN running stats), restore
        originals. Returns (outputs, new_buffers_tree). ``_call_fn`` overrides
        the callable (used by to_static to reach the pre-wrap forward)."""
        params = dict(self.named_parameters())
        buffers = dict(self.named_buffers())
        saved_p = {n: params[n]._data for n in params_tree}
        saved_b = {n: buffers[n]._data for n in buffers_tree}
        try:
            for n, arr in params_tree.items():
                params[n]._set_data(arr)
            for n, arr in buffers_tree.items():
                buffers[n]._set_data(arr)
            out = (_call_fn or self.__call__)(*inputs, **kwargs)
            new_buffers = {n: buffers[n]._data for n in buffers_tree}
            return out, new_buffers
        finally:
            for n, arr in saved_p.items():
                params[n]._set_data(arr)
            for n, arr in saved_b.items():
                buffers[n]._set_data(arr)

    def functional_call(self, tree, *inputs, **kwargs):
        """Run forward with parameters taken from ``tree`` (pure w.r.t. the
        tree): temporarily swaps arrays in, calls forward, restores. Used by
        jit'd train steps to express the Layer as a pure function."""
        params = dict(self.named_parameters())
        buffers = dict(self.named_buffers())
        saved = {}
        try:
            for name, arr in tree.items():
                if name.startswith("buffer:"):
                    t = buffers[name[len("buffer:"):]]
                else:
                    t = params[name]
                saved[name] = t._data
                t._set_data(arr)
            return self(*inputs, **kwargs)
        finally:
            for name, arr in saved.items():
                if name.startswith("buffer:"):
                    buffers[name[len("buffer:"):]]._set_data(arr)
                else:
                    params[name]._set_data(arr)

    def __repr__(self):
        lines = [type(self).__name__ + "("]
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            lines.append(f"  ({name}): " + ("\n  ".join(sub_repr)))
        lines.append(")")
        return "\n".join(lines) if len(lines) > 2 else type(self).__name__ + "()"


class Sequential(Layer):
    """Parity: paddle.nn.Sequential."""

    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and not isinstance(layers[0], Layer):
            layers = layers[0]
        for i, l in enumerate(layers):
            if isinstance(l, (list, tuple)):
                name, l = l
                self.add_sublayer(str(name), l)
            else:
                self.add_sublayer(str(i), l)

    def forward(self, x):
        for l in self._sub_layers.values():
            x = l(x)
        return x

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def append(self, sublayer):
        self.add_sublayer(str(len(self._sub_layers)), sublayer)
        return self

    def insert(self, index, sublayer):
        layers = list(self._sub_layers.values())
        layers.insert(index, sublayer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, sublayers):
        for l in sublayers:
            self.append(l)
        return self

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return list(self._sub_layers.values())[idx]
        return self._sub_layers[str(idx if idx >= 0 else len(self) + idx)]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self

    def __getitem__(self, idx):
        return self._parameters[str(idx if idx >= 0 else len(self) + idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())


# ---------------------------------------------------------------------------
# transparent per-layer jit caching for eager mode
#
# Parity: the reference's generated core.ops.* fast path
# (/root/reference/paddle/fluid/pybind/op_function_generator.cc:551) — one
# C-level call instead of per-op Python dispatch. TPU-native version: the
# whole Layer.forward is traced ONCE into a jitted closure (keyed by layer
# structure + input avals) and each eager call dispatches one XLA program
# instead of one per op. Gradients still flow through the autograd tape: the
# jitted forward is recorded as a single taped primitive whose vjp is the
# compiled backward.
#
# Escape hatch: paddle.set_flags({"FLAGS_eager_layer_jit": False}). The
# default (True) engages on TPU only — on CPU op-by-op dispatch is cheap and
# tests exercise the un-jitted paths; the value "force" engages anywhere
# (used by the parity tests).
# ---------------------------------------------------------------------------
_JIT_FORWARD_ACTIVE = False  # true while tracing a jitted layer forward
_STRUCTURE_VERSION = [0]  # bumped on ANY sublayer registration (cache guard)


def _bump_structure_version():
    _STRUCTURE_VERSION[0] += 1


def _eager_jit_mode():
    from ..framework.flags import flag

    v = str(flag("FLAGS_eager_layer_jit") or "").strip().lower()
    if v == "force":
        return "force"  # engage on any backend (parity tests)
    if v in ("1", "true", "yes", "on"):
        return True  # engage on TPU only
    return None


def _jit_forward_applicable(layer, inputs, kwargs) -> bool:
    global _JIT_FORWARD_ACTIVE
    if _JIT_FORWARD_ACTIVE:
        return False
    mode = _eager_jit_mode()
    if mode is None:
        return False
    import paddle_tpu as _pd

    if _pd._static_mode:
        return False
    if mode != "force":
        import jax

        try:
            if jax.devices()[0].platform != "tpu":
                return False
        except RuntimeError:
            return False
    # only plain positional calls: every arg a Tensor or a hashable scalar
    if kwargs:
        return False
    for x in inputs:
        if isinstance(x, Tensor):
            if not isinstance(x._data, jnp.ndarray):
                return False  # static Variable / symbolic
        elif not isinstance(x, (int, float, bool, str, type(None))):
            return False
    if not any(isinstance(x, Tensor) for x in inputs):
        return False
    return _jit_forward_supported(layer)


def _jit_forward_supported(layer) -> bool:
    """Structure gate: no exempt sublayers (MoE aux-loss side outputs), no
    active generation caches, no floating (stats-like) buffers to write
    back. The sublayer list is walked once and cached against the GLOBAL
    structure version (bumped by any sublayer registration, so ancestors'
    cached walks revalidate too)."""
    cached = layer.__dict__.get("_jit_sub_cache")
    if cached is None or cached[0] != _STRUCTURE_VERSION[0]:
        sub = [l for _, l in layer.named_sublayers(include_self=True)]
        layer.__dict__["_jit_sub_cache"] = (_STRUCTURE_VERSION[0], sub)
    else:
        sub = cached[1]
    for l in sub:
        if getattr(type(l), "_jit_forward_exempt", False):
            return False
        if "_gen_cache" in l.__dict__:
            return False
        for b in l._buffers.values():
            if b is not None and jnp.issubdtype(b._data.dtype, jnp.floating):
                return False
    return True


def _jit_forward_call(layer, inputs):
    """Dispatch through the per-(training, amp, statics) cached jitted
    closure; jax.jit's own aval cache handles input shapes/dtypes."""
    global _JIT_FORWARD_ACTIVE
    import jax

    from ..amp.auto_cast import amp_state
    from ..autograd import tape as _tape
    from ..ops._primitive import primitive
    from ..random import get_rng_state, set_rng_state, split_key

    amp = amp_state()
    statics = tuple(x if not isinstance(x, Tensor) else None for x in inputs)
    # which positions are Tensors must be part of the key: a Tensor maps to
    # None in `statics`, so f(ids, pos_tensor) and f(ids, None) would
    # otherwise collide on one entry and silently drop/crash the other form
    tpos_key = tuple(i for i, x in enumerate(inputs) if isinstance(x, Tensor))
    key = (layer.training, bool(amp.enable), getattr(amp, "dtype", None),
           getattr(amp, "level", None), statics, len(inputs), tpos_key,
           _STRUCTURE_VERSION[0])  # stale closures die on structure change
    cache = layer.__dict__.setdefault("_eager_jit_cache", {})
    entry = cache.get(key)
    if entry is None:
        tensor_pos = [i for i, x in enumerate(inputs) if isinstance(x, Tensor)]
        out_box = {}
        # close over the NON-tensor args only (part of the cache key);
        # closing over `inputs` would pin the first call's activations
        static_args = list(statics)

        def raw(ptree, btree, rng_key, *xs):
            global _JIT_FORWARD_ACTIVE
            args = list(static_args)
            for i, a in zip(tensor_pos, xs):
                args[i] = Tensor(a)
            saved = get_rng_state()
            set_rng_state(rng_key)
            was = _JIT_FORWARD_ACTIVE
            _JIT_FORWARD_ACTIVE = True
            try:
                with _tape.no_grad():
                    out, _ = layer.functional_call_with_state(
                        ptree, btree, *args)
            finally:
                _JIT_FORWARD_ACTIVE = was
                set_rng_state(saved)
            leaves, treedef = jax.tree_util.tree_flatten(
                out, is_leaf=lambda x: isinstance(x, Tensor))
            leaves = [l._data if isinstance(l, Tensor) else l for l in leaves]
            out_box["treedef"] = treedef
            return tuple(leaves) if len(leaves) != 1 else leaves[0]

        entry = (primitive(jax.jit(raw), name=f"jit:{type(layer).__name__}"),
                 out_box, tensor_pos)
    wrapped, out_box, tensor_pos = entry

    ptree = {n: p for n, p in layer.named_parameters()}
    btree = {n: b._data for n, b in layer.named_buffers()}
    rng_key = split_key()
    # keyed per input avals: an output pytree whose structure varies with
    # input shape must not reuse the treedef from a different trace
    aval_key = tuple((tuple(inputs[i]._data.shape), str(inputs[i]._data.dtype))
                     for i in tensor_pos)
    out = wrapped(ptree, btree, rng_key,
                  *[inputs[i] for i in tensor_pos])
    # only publish the cache entry once a call has succeeded (a failed
    # first trace must not leave an entry with no recorded treedef)
    cache[key] = entry
    by_aval = out_box.setdefault("by_aval", {})
    if aval_key not in by_aval:
        by_aval[aval_key] = out_box["treedef"]  # set by the trace just run
    treedef = by_aval[aval_key]
    leaves = list(out) if isinstance(out, tuple) else [out]
    return jax.tree_util.tree_unflatten(treedef, leaves)
