"""Scaled dot-product attention with TPU kernel dispatch.

This is the single attention entry point for the whole framework (MHA layers,
fused transformer blocks, GPT/BERT models). Parity target: the reference's
fused attention CUDA ops (/root/reference/paddle/fluid/operators/fused/
fused_attention_op.cu, fmha_ref.h).

Dispatch policy:
- TPU + no-weights-needed + supported shapes → Pallas flash-attention kernel
  (paddle_tpu/ops/pallas/flash_attention.py) — O(T) memory, fused softmax.
- otherwise → plain XLA einsum path (still fuses well on TPU for short T).
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..ops._primitive import primitive, unwrap
from ..random import split_key

__all__ = ["scaled_dot_product_attention"]

_FLASH_MIN_SEQ = 512  # below this the XLA path is as fast and simpler


def _use_flash(q, k, dropout_p, need_weights, attn_mask, is_causal):
    if need_weights or dropout_p > 0.0:
        return False
    if attn_mask is not None and not is_causal:
        return False  # general additive masks go through the XLA path
    try:
        dev = jax.devices()[0].platform
    except RuntimeError:
        return False
    if dev != "tpu":
        return False
    T, S, D = q.shape[-2], k.shape[-2], q.shape[-1]
    # D=64 is viable since the whole-sequence-block layout (v5e-measured:
    # beats the XLA einsum path at B8 H16 T1024 D64 — see flash_attention);
    # non-64-multiple D (e.g. 760M's 96) is zero-padded by the kernel
    # wrapper, and ragged causal T==S is tail-padded exactly (masked keys)
    if T < _FLASH_MIN_SEQ or S < _FLASH_MIN_SEQ or D < 32:
        return False
    if T % 128 == 0 and S % 128 == 0:
        return True
    return bool(is_causal) and T == S


def scaled_dot_product_attention(
    q,
    k,
    v,
    attn_mask=None,
    dropout_p: float = 0.0,
    is_causal: bool = False,
    scale: Optional[float] = None,
    return_weights: bool = False,
):
    """q,k,v: [B, H, T, D]; attn_mask: additive float mask broadcastable to
    [B, H, T, S]. Returns (out, weights_or_None)."""
    q_arr = unwrap(q)
    scale = scale if scale is not None else 1.0 / math.sqrt(q_arr.shape[-1])

    if _use_flash(q_arr, unwrap(k), dropout_p, return_weights, attn_mask, is_causal):
        from ..ops.pallas.flash_attention import flash_attention

        @primitive
        def _flash(q, k, v):
            return flash_attention(q, k, v, causal=is_causal, sm_scale=scale)

        return _flash(q, k, v), None

    keep = None
    if dropout_p > 0.0:
        b, h, t = q_arr.shape[0], q_arr.shape[1], q_arr.shape[2]
        s = unwrap(k).shape[2]
        keep = jax.random.bernoulli(split_key(), 1.0 - dropout_p, (b, h, t, s))

    @primitive(aux=1)
    def _attn(q, k, v, attn_mask):
        logits = jnp.einsum("bhtd,bhsd->bhts", q, k) * scale
        if is_causal:
            t, s = logits.shape[-2], logits.shape[-1]
            causal = jnp.tril(jnp.ones((t, s), bool), k=s - t)
            logits = jnp.where(causal, logits, jnp.asarray(-1e9, logits.dtype))
        if attn_mask is not None:
            logits = logits + attn_mask
        weights = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
        w = weights
        if keep is not None:
            w = jnp.where(keep, w / (1.0 - dropout_p), 0.0)
        out = jnp.einsum("bhts,bhsd->bhtd", w, v)
        return out, jax.lax.stop_gradient(weights)

    out, weights = _attn(q, k, v, attn_mask)
    return out, (weights if return_weights else None)
