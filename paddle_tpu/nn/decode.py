"""Beam-search decoding (parity: fluid/layers/rnn.py BeamSearchDecoder +
dynamic_decode in the reference, backed there by the beam_search /
beam_search_decode / gather_tree ops).

TPU-native notes: decoding is a host-driven loop over a jit-compiled step
(each step is pure jnp through the framework's primitive funnel); the final
backtrace reuses nn.functional.gather_tree. Scores use log-probabilities with
the finished-beam convention of the reference: a finished beam can only
extend with end_token at probability 1."""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..ops._primitive import unwrap, wrap
from ..tensor import Tensor
from . import functional as F

__all__ = ["BeamSearchDecoder", "dynamic_decode"]


class BeamSearchDecoder:
    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- helpers ---------------------------------------------------------
    def _tile_beam(self, x):
        arr = unwrap(x)
        tiled = jnp.repeat(arr[:, None], self.beam_size, axis=1)
        return tiled

    def initialize(self, initial_cell_states):
        states = jax.tree_util.tree_map(
            lambda s: self._tile_beam(s).reshape((-1,) + unwrap(s).shape[1:]),
            initial_cell_states, is_leaf=lambda v: isinstance(v, Tensor))
        leaves = jax.tree_util.tree_leaves(
            initial_cell_states, is_leaf=lambda v: isinstance(v, Tensor))
        batch = unwrap(leaves[0]).shape[0]
        log_probs = jnp.full((batch, self.beam_size), -1e9, jnp.float32)
        log_probs = log_probs.at[:, 0].set(0.0)
        finished = jnp.zeros((batch, self.beam_size), bool)
        lengths = jnp.zeros((batch, self.beam_size), jnp.int64)
        tokens = jnp.full((batch, self.beam_size), self.start_token, jnp.int64)
        return states, (log_probs, finished, lengths), tokens

    def step(self, tokens, cell_states, beam_state):
        log_probs, finished, lengths = beam_state
        batch = log_probs.shape[0]
        inputs = wrap(tokens.reshape(-1))
        if self.embedding_fn is not None:
            inputs = self.embedding_fn(inputs)
        cell_out, next_states = self.cell(inputs, cell_states)
        if self.output_fn is not None:
            cell_out = self.output_fn(cell_out)
        logits = unwrap(cell_out).astype(jnp.float32)  # [batch*beam, V]
        vocab = logits.shape[-1]
        step_lp = jax.nn.log_softmax(logits, -1).reshape(batch, self.beam_size, vocab)
        # finished beams: only end_token continues, at log-prob 0
        fin_row = jnp.full((vocab,), -1e9, jnp.float32).at[self.end_token].set(0.0)
        step_lp = jnp.where(finished[:, :, None], fin_row[None, None, :], step_lp)
        scores = (log_probs[:, :, None] + step_lp).reshape(batch, -1)
        top_scores, top_idx = jax.lax.top_k(scores, self.beam_size)
        parents = (top_idx // vocab).astype(jnp.int64)
        new_tokens = (top_idx % vocab).astype(jnp.int64)
        was_finished = jnp.take_along_axis(finished, parents, axis=1)
        new_finished = was_finished | (new_tokens == self.end_token)
        new_lengths = jnp.take_along_axis(lengths, parents, axis=1) + \
            (~was_finished).astype(jnp.int64)

        # regroup cell states by parent beam
        def regroup(s):
            arr = unwrap(s).reshape((batch, self.beam_size) + unwrap(s).shape[1:])
            idx = parents.reshape(parents.shape + (1,) * (arr.ndim - 2))
            out = jnp.take_along_axis(arr, idx.astype(jnp.int32), axis=1)
            return wrap(out.reshape((-1,) + arr.shape[2:]))

        next_states = jax.tree_util.tree_map(
            regroup, next_states, is_leaf=lambda v: isinstance(v, Tensor))
        return (new_tokens, parents,
                next_states, (top_scores, new_finished, new_lengths))


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, return_length=False, **kwargs):
    """Run the decoder until every beam finishes or max_step_num steps
    (parity: fluid.layers.dynamic_decode). Returns (ids, scores) with ids of
    shape [batch, T, beam] ([T, batch, beam] when time-major), plus lengths
    when return_length=True."""
    if max_step_num is None:
        max_step_num = 256
    cell_states, beam_state, tokens = decoder.initialize(inits)
    step_ids, step_parents = [], []
    for _ in range(int(max_step_num)):
        tokens, parents, cell_states, beam_state = decoder.step(
            tokens, cell_states, beam_state)
        step_ids.append(tokens)
        step_parents.append(parents)
        if bool(np.asarray(beam_state[1]).all()):
            break
    ids = jnp.stack(step_ids)       # [T, batch, beam]
    parents = jnp.stack(step_parents)
    full = F.gather_tree(wrap(ids), wrap(parents))  # backtraced beams
    out = unwrap(full)
    if not output_time_major:
        out = jnp.transpose(out, (1, 0, 2))
    scores = beam_state[0]
    if return_length:
        return wrap(out), wrap(scores), wrap(beam_state[2])
    return wrap(out), wrap(scores)
