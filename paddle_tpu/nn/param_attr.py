"""ParamAttr — parameter configuration.

Parity: python/paddle/fluid/param_attr.py (name, initializer, learning_rate,
regularizer, trainable, need_clip; WeightNormParamAttr omitted v1).
"""
from __future__ import annotations

from typing import Optional

__all__ = ["ParamAttr"]


class ParamAttr:
    def __init__(
        self,
        name: Optional[str] = None,
        initializer=None,
        learning_rate: float = 1.0,
        regularizer=None,
        trainable: bool = True,
        need_clip: bool = True,
    ):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        from . import initializer as init_mod

        if attr is None:
            return None
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if isinstance(attr, init_mod.Initializer):
            return ParamAttr(initializer=attr)
        if attr is False:
            return ParamAttr(trainable=False)
        raise TypeError(f"cannot convert {attr!r} to ParamAttr")
