"""nn functional ops.

Parity: python/paddle/nn/functional/ in the reference (activation.py, common.py,
conv.py, loss.py, norm.py, pooling.py, input.py) over the C++ kernels in
/root/reference/paddle/fluid/operators/ (conv_cudnn_op.cu, pool_op.cu,
layer_norm_op.cu, softmax_with_cross_entropy_op.cu, lookup_table_v2_op.cu ...).

TPU-native: convs/matmuls lower to the MXU through lax.conv_general_dilated /
jnp.matmul; XLA fuses the elementwise epilogues that the reference implements
as fused_* CUDA ops. Dropout draws from the global seeded PRNG (TP-aware via
paddle_tpu.random's state tracker).
"""
from __future__ import annotations

import math
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..ops._primitive import primitive, unwrap, wrap
from ..random import split_key
from ..tensor import Tensor

# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------

relu = primitive(jax.nn.relu, name="relu")
relu6 = primitive(jax.nn.relu6, name="relu6")
elu = primitive(lambda x, alpha=1.0: jax.nn.elu(x, alpha), name="elu")
selu = primitive(
    lambda x, scale=1.0507009873554805, alpha=1.6732632423543772: scale
    * jnp.where(x > 0, x, alpha * jnp.expm1(x)),
    name="selu",
)
celu = primitive(lambda x, alpha=1.0: jax.nn.celu(x, alpha), name="celu")
silu = primitive(jax.nn.silu, name="silu")
swish = silu
mish = primitive(lambda x: x * jnp.tanh(jax.nn.softplus(x)), name="mish")
sigmoid = primitive(jax.nn.sigmoid, name="sigmoid")
log_sigmoid = primitive(jax.nn.log_sigmoid, name="log_sigmoid")
tanh = primitive(jnp.tanh, name="tanh")
softsign = primitive(jax.nn.soft_sign, name="softsign")
tanhshrink = primitive(lambda x: x - jnp.tanh(x), name="tanhshrink")


@primitive
def gelu(x, approximate=False):
    return jax.nn.gelu(x, approximate=approximate)


@primitive
def leaky_relu(x, negative_slope=0.01):
    return jax.nn.leaky_relu(x, negative_slope)


@primitive
def prelu(x, weight, data_format="NCHW"):
    if weight.size == 1:
        w = weight.reshape(())
    else:
        shape = [1] * x.ndim
        ch_axis = 1 if data_format == "NCHW" else x.ndim - 1
        shape[ch_axis] = weight.size
        w = weight.reshape(shape)
    return jnp.where(x > 0, x, w * x)


@primitive
def hardtanh(x, min=-1.0, max=1.0):  # noqa: A002
    return jnp.clip(x, min, max)


@primitive
def hardshrink(x, threshold=0.5):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


@primitive
def softshrink(x, threshold=0.5):
    return jnp.where(x > threshold, x - threshold, jnp.where(x < -threshold, x + threshold, 0.0))


@primitive
def hardsigmoid(x, slope=0.1666667, offset=0.5):
    return jnp.clip(slope * x + offset, 0.0, 1.0)


@primitive
def hardswish(x):
    return x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0


@primitive
def softplus(x, beta=1.0, threshold=20.0):
    return jnp.where(x * beta > threshold, x, jax.nn.softplus(x * beta) / beta)


@primitive
def maxout(x, groups, axis=1):
    shape = list(x.shape)
    c = shape[axis]
    shape[axis : axis + 1] = [c // groups, groups]
    return jnp.max(x.reshape(shape), axis=axis + 1)


@primitive
def glu(x, axis=-1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


@primitive
def softmax(x, axis=-1, dtype=None):
    if dtype is not None:
        x = x.astype(dtype)
    return jax.nn.softmax(x, axis=axis)


@primitive
def log_softmax(x, axis=-1, dtype=None):
    if dtype is not None:
        x = x.astype(dtype)
    return jax.nn.log_softmax(x, axis=axis)


@primitive
def temperature_scaled_softmax(x, temperature=1.0, axis=-1):
    return jax.nn.softmax(x / temperature, axis=axis)


# ---------------------------------------------------------------------------
# linear / embedding
# ---------------------------------------------------------------------------


def _linear_fp_raw(x, weight, bias=None):
    y = jnp.matmul(x, weight)
    if bias is not None:
        y = y + bias
    return y


_linear_fp = primitive(_linear_fp_raw, name="linear")


@primitive(nondiff=True)
def _linear_int8(x, weight, weight_scale, act_scale, bias=None):
    """W8A8 int8 matmul (ISSUE 18): ``weight`` is int8
    ``[in_features, out_features]`` with per-out-channel f32
    ``weight_scale`` ``[out]``; the activation is quantized per-tensor
    (calibrated ``act_scale`` when present, dynamic absmax otherwise),
    the contraction runs int8 x int8 -> int32 on the MXU, and BOTH
    scales fuse into the int32 accumulator — the f32 weight copy is
    never materialized (the analysis dtype rule certifies this)."""
    if act_scale is None:
        sx = jnp.maximum(jnp.max(jnp.abs(x)) / 127.0, 1e-8)
    else:
        sx = jnp.maximum(act_scale.astype(jnp.float32).reshape(()), 1e-8)
    sx = sx.astype(jnp.float32)
    xq = jnp.clip(jnp.round(x / sx), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        xq, weight, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    y = (acc.astype(jnp.float32)
         * (sx * weight_scale.astype(jnp.float32))).astype(x.dtype)
    if bias is not None:
        y = y + bias
    return y


def linear(x, weight, bias=None, weight_scale=None, act_scale=None):
    """y = x @ W (+ b); paddle weight layout [in_features, out_features]
    (reference: matmul_v2 + elementwise_add, python/paddle/nn/functional/common.py).

    When ``weight_scale`` is given the weight is taken as PTQ int8
    (``quantization/ptq.py``) and the matmul runs through the scale-fused
    int8 path instead."""
    if weight_scale is not None:
        return _linear_int8(x, weight, weight_scale, act_scale, bias)
    return _linear_fp(x, weight, bias)


@primitive
def _embedding(weight, ids, padding_idx):
    if padding_idx is not None:
        # freeze the padding row: value passes through, grad is zeroed
        row = jax.lax.stop_gradient(weight[padding_idx])
        weight = weight.at[padding_idx].set(row)
    return jnp.take(weight, ids, axis=0)


def embedding(x, weight, padding_idx=None, sparse=False):  # noqa: ARG001 - sparse n/a on TPU
    return _embedding(weight, unwrap(x), padding_idx)


def one_hot(x, num_classes):
    return wrap(jax.nn.one_hot(unwrap(x), num_classes, dtype=jnp.float32))


# ---------------------------------------------------------------------------
# dropout
# ---------------------------------------------------------------------------


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train"):
    """Parity: dropout op (reference operators/dropout_op.cu);
    'upscale_in_train' (default) and 'downscale_in_infer' modes."""
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            from ..ops import math as M

            return M.scale(x, scale=1.0 - p)
        return x
    if p == 1.0:
        from ..ops import creation

        return creation.zeros_like(x) * x if isinstance(x, Tensor) else wrap(jnp.zeros_like(unwrap(x)))
    axes = None if axis is None else (axis if isinstance(axis, (list, tuple)) else [axis])

    def _mask_shape(shape):
        if axes is None:
            return tuple(shape)
        return tuple(s if i in axes else 1 for i, s in enumerate(shape))

    from ..static.program import recording_active

    if recording_active():
        # static mode: the mask key is a per-run feed, shapes come from the
        # runtime array (so symbolic batch dims stay correct at replay)
        from ..static.program import record_rng_op

        def _dropout_rng(key, arr):
            keep = jax.random.bernoulli(key, 1.0 - p, _mask_shape(arr.shape))
            scaled = arr / (1.0 - p) if mode == "upscale_in_train" else arr
            return jnp.where(keep, scaled, 0.0).astype(arr.dtype)

        out = record_rng_op(_dropout_rng, "dropout", (x,))
        out._program.ops[-1].tags = {"dropout": True, "p": p, "mode": mode}
        return out

    arr = unwrap(x)
    keep = jax.random.bernoulli(split_key(), 1.0 - p, _mask_shape(arr.shape))

    @primitive
    def _dropout(x):
        scaled = x / (1.0 - p) if mode == "upscale_in_train" else x
        return jnp.where(keep, scaled, 0.0).astype(x.dtype)

    return _dropout(x)


def dropout2d(x, p=0.5, training=True, data_format="NCHW"):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW"):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True):
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale_ = 1.0507009873554805
    alpha_p = -alpha * scale_
    arr = unwrap(x)
    keep = jax.random.bernoulli(split_key(), 1.0 - p, tuple(arr.shape))
    a = (1.0 - p + p * alpha_p**2) ** -0.5
    b = -a * p * alpha_p

    @primitive
    def _ad(x):
        return (a * jnp.where(keep, x, alpha_p) + b).astype(x.dtype)

    return _ad(x)


# ---------------------------------------------------------------------------
# convolution
# ---------------------------------------------------------------------------


def _norm_tuple(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    v = tuple(int(i) for i in v)
    if len(v) == 1:
        return v * n
    return v


def _conv_padding(padding, nd, strides, dilations, ksize):
    """Convert paddle padding spec to lax padding list of (lo, hi)."""
    if isinstance(padding, str):
        return padding.upper()  # 'SAME' / 'VALID'
    if isinstance(padding, (int, np.integer)):
        return [(int(padding), int(padding))] * nd
    padding = list(padding)
    if len(padding) == nd and all(isinstance(p, (int, np.integer)) for p in padding):
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * nd:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(nd)]
    if all(isinstance(p, (list, tuple)) for p in padding):
        return [tuple(int(v) for v in p) for p in padding]
    raise ValueError(f"bad padding {padding}")


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, nd, data_format, transpose=False, output_padding=0):
    strides = _norm_tuple(stride, nd)
    dilations = _norm_tuple(dilation, nd)
    spatial = "DHW"[-nd:]
    if data_format in (f"NC{spatial}", "NCHW", "NCL", "NCDHW"):
        lhs_spec = "NC" + spatial
    else:
        lhs_spec = "N" + spatial + "C"
    rhs_spec = "OI" + spatial
    out_spec = lhs_spec
    dn = jax.lax.conv_dimension_numbers(
        unwrap(x).shape, unwrap(weight).shape, (lhs_spec, rhs_spec, out_spec)
    )
    pad = _conv_padding(padding, nd, strides, dilations, None)

    @primitive
    def _conv(x, weight, bias):
        # mixed-precision harmonization: lax.conv requires matching dtypes;
        # when weights were cast down (compute_dtype / AMP O2 master-weight
        # pattern) the activations follow them onto the MXU
        if x.dtype != weight.dtype and jnp.issubdtype(x.dtype, jnp.floating) \
                and jnp.issubdtype(weight.dtype, jnp.floating):
            low = min(x.dtype, weight.dtype, key=lambda d: jnp.finfo(d).bits)
            x = x.astype(low)
            weight = weight.astype(low)
        if not transpose:
            out = jax.lax.conv_general_dilated(
                x,
                weight,
                window_strides=strides,
                padding=pad,
                rhs_dilation=dilations,
                dimension_numbers=dn,
                feature_group_count=groups,
            )
        else:
            # conv_transpose: gradient of conv. weight layout [in_c, out_c/groups, *k]
            pads = pad
            if isinstance(pads, str):
                pads_l = pads
            else:
                k_eff = [
                    (weight.shape[2 + i] - 1) * dilations[i] + 1 for i in range(nd)
                ]
                opad = _norm_tuple(output_padding, nd)
                pads_l = [
                    (k_eff[i] - 1 - pads[i][0], k_eff[i] - 1 - pads[i][1] + opad[i])
                    for i in range(nd)
                ]
            w = jnp.swapaxes(weight, 0, 1)  # -> [out_c/g, in_c, *k]
            w = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
            if groups > 1:
                # grouped transpose conv: block-diagonal equivalent
                w_groups = jnp.split(w, groups, axis=1)
                x_groups = jnp.split(x, groups, axis=1 if lhs_spec.startswith("NC") else -1)
                outs = [
                    jax.lax.conv_general_dilated(
                        xg,
                        wg,
                        window_strides=(1,) * nd,
                        padding=pads_l,
                        lhs_dilation=strides,
                        dimension_numbers=dn,
                    )
                    for xg, wg in zip(x_groups, w_groups)
                ]
                out = jnp.concatenate(outs, axis=1 if lhs_spec.startswith("NC") else -1)
            else:
                out = jax.lax.conv_general_dilated(
                    x,
                    w,
                    window_strides=(1,) * nd,
                    padding=pads_l,
                    lhs_dilation=strides,
                    dimension_numbers=dn,
                )
        if bias is not None:
            bshape = [1] * out.ndim
            bshape[1 if lhs_spec.startswith("NC") else -1] = bias.shape[0]
            out = out + bias.reshape(bshape)
        return out

    return _conv(x, weight, bias)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCL"):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 1, data_format)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCHW"):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1, data_format="NCDHW"):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3, data_format)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, data_format="NCL"):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 1, data_format, transpose=True, output_padding=output_padding)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, data_format="NCHW"):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 2, data_format, transpose=True, output_padding=output_padding)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1, dilation=1, data_format="NCDHW"):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3, data_format, transpose=True, output_padding=output_padding)


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------


def _pool_nd(x, kernel_size, stride, padding, nd, data_format, kind, exclusive=True, ceil_mode=False):
    ks = _norm_tuple(kernel_size, nd)
    st = _norm_tuple(stride if stride is not None else kernel_size, nd)
    pad = _conv_padding(padding, nd, st, (1,) * nd, ks)
    channel_first = data_format.startswith("NC")
    if channel_first:
        window = (1, 1) + ks
        strides = (1, 1) + st
        pads = [(0, 0), (0, 0)] + (pad if isinstance(pad, list) else [])
    else:
        window = (1,) + ks + (1,)
        strides = (1,) + st + (1,)
        pads = [(0, 0)] + (pad if isinstance(pad, list) else []) + [(0, 0)]
    if isinstance(pad, str):
        pads = pad

    @primitive
    def _pool(x):
        if ceil_mode and not isinstance(pads, str):
            # extend hi padding so the last partial window is included
            sp_dims = range(2, 2 + nd) if channel_first else range(1, 1 + nd)
            new_pads = list(pads)
            for i, d in enumerate(sp_dims):
                size = x.shape[d] + pads[d][0] + pads[d][1]
                rem = (size - ks[i]) % st[i]
                if rem != 0:
                    lo, hi = new_pads[d]
                    new_pads[d] = (lo, hi + (st[i] - rem))
            eff_pads = new_pads
        else:
            eff_pads = pads
        if kind == "max":
            init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
            return jax.lax.reduce_window(x, init, jax.lax.max, window, strides, eff_pads)
        s = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides, eff_pads)
        if exclusive and (isinstance(eff_pads, str) or any(p != (0, 0) for p in eff_pads)):
            ones = jnp.ones_like(x)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, eff_pads)
            return s / cnt
        return s / float(np.prod(ks))

    return _pool(x)


def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False, return_mask=False, data_format="NCL"):
    out = _pool_nd(x, kernel_size, stride, padding, 1, data_format, "max", ceil_mode=ceil_mode)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, return_mask=False, data_format="NCHW"):
    if return_mask:
        if data_format != "NCHW":
            raise ValueError("return_mask requires NCHW")
        return _max_pool2d_with_index(x, kernel_size, stride, padding, ceil_mode)
    return _pool_nd(x, kernel_size, stride, padding, 2, data_format, "max", ceil_mode=ceil_mode)


def _max_pool2d_with_index(x, kernel_size, stride, padding, ceil_mode=False):
    """max_pool2d returning flat-spatial argmax indices (parity:
    max_pool2d_with_index op — the indices max_unpool2d consumes)."""
    ks = _norm_tuple(kernel_size, 2)
    st = _norm_tuple(stride if stride is not None else kernel_size, 2)
    pd = _norm_tuple(padding, 2)

    @primitive(aux=1)
    def _pool_idx(x):
        n, c, h, w = x.shape
        if ceil_mode:
            hout = -((h + 2 * pd[0] - ks[0]) // -st[0]) + 1
            wout = -((w + 2 * pd[1] - ks[1]) // -st[1]) + 1
        else:
            hout = (h + 2 * pd[0] - ks[0]) // st[0] + 1
            wout = (w + 2 * pd[1] - ks[1]) // st[1] + 1
        # window gather: positions (hout, kh) x (wout, kw) in padded coords
        hy = jnp.arange(hout)[:, None] * st[0] + jnp.arange(ks[0])[None, :] - pd[0]
        wx = jnp.arange(wout)[:, None] * st[1] + jnp.arange(ks[1])[None, :] - pd[1]
        valid = ((hy >= 0) & (hy < h))[:, None, :, None] & ((wx >= 0) & (wx < w))[None, :, None, :]
        hc = jnp.clip(hy, 0, h - 1)
        wc = jnp.clip(wx, 0, w - 1)
        win = x[:, :, hc[:, None, :, None], wc[None, :, None, :]]  # (n,c,hout,wout,kh,kw)
        neg = jnp.asarray(-3.4e38, x.dtype)
        win = jnp.where(valid[None, None], win, neg)
        flat = win.reshape(n, c, hout, wout, ks[0] * ks[1])
        out = flat.max(-1)
        kbest = jnp.argmax(flat, axis=-1)
        ky, kx = kbest // ks[1], kbest % ks[1]
        src_h = jnp.take_along_axis(
            jnp.broadcast_to(hc[None, None, :, None, :], (n, c, hout, wout, ks[0])),
            ky[..., None], axis=-1)[..., 0]
        src_w = jnp.take_along_axis(
            jnp.broadcast_to(wc[None, None, None, :, :], (n, c, hout, wout, ks[1])),
            kx[..., None], axis=-1)[..., 0]
        idx = (src_h * w + src_w).astype(jnp.int32)
        return out, idx

    return _pool_idx(x)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, return_mask=False, data_format="NCDHW"):
    return _pool_nd(x, kernel_size, stride, padding, 3, data_format, "max", ceil_mode=ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False, data_format="NCL"):
    return _pool_nd(x, kernel_size, stride, padding, 1, data_format, "avg", exclusive, ceil_mode)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCHW"):
    return _pool_nd(x, kernel_size, stride, padding, 2, data_format, "avg", exclusive, ceil_mode)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, divisor_override=None, data_format="NCDHW"):
    return _pool_nd(x, kernel_size, stride, padding, 3, data_format, "avg", exclusive, ceil_mode)


def _adaptive_pool(x, output_size, nd, kind, data_format):
    out_size = _norm_tuple(output_size, nd)
    channel_first = data_format.startswith("NC")

    @primitive
    def _apool(x):
        sp = x.shape[2 : 2 + nd] if channel_first else x.shape[1 : 1 + nd]
        out = x
        for i in range(nd):
            in_s, out_s = sp[i], out_size[i]
            axis = (2 + i) if channel_first else (1 + i)
            if in_s % out_s == 0:
                k = in_s // out_s
                shape = list(out.shape)
                shape[axis : axis + 1] = [out_s, k]
                r = out.reshape(shape)
                out = jnp.max(r, axis=axis + 1) if kind == "max" else jnp.mean(r, axis=axis + 1)
            else:
                # general adaptive: per-output-bin segment reduce
                starts = [math.floor(j * in_s / out_s) for j in range(out_s)]
                ends = [math.ceil((j + 1) * in_s / out_s) for j in range(out_s)]
                pieces = []
                for s_, e_ in zip(starts, ends):
                    sl = [builtins_slice(None)] * out.ndim
                    sl[axis] = builtins_slice(s_, e_)
                    seg = out[tuple(sl)]
                    red = jnp.max(seg, axis=axis, keepdims=True) if kind == "max" else jnp.mean(seg, axis=axis, keepdims=True)
                    pieces.append(red)
                out = jnp.concatenate(pieces, axis=axis)
        return out

    return _apool(x)


builtins_slice = slice


def adaptive_avg_pool1d(x, output_size, data_format="NCL"):
    return _adaptive_pool(x, output_size, 1, "avg", data_format)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW"):
    return _adaptive_pool(x, output_size, 2, "avg", data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW"):
    return _adaptive_pool(x, output_size, 3, "avg", data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, data_format="NCL"):
    return _adaptive_pool(x, output_size, 1, "max", data_format)


def adaptive_max_pool2d(x, output_size, return_mask=False, data_format="NCHW"):
    return _adaptive_pool(x, output_size, 2, "max", data_format)


def adaptive_max_pool3d(x, output_size, return_mask=False, data_format="NCDHW"):
    return _adaptive_pool(x, output_size, 3, "max", data_format)


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------


@primitive
def _ln(x, weight, bias, eps, begin_axis):
    axes = tuple(range(begin_axis, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + eps)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5):
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    begin = unwrap(x).ndim - len(tuple(normalized_shape))
    return _ln(x, weight, bias, epsilon, begin)


@primitive
def _bn_infer(x, mean, var, weight, bias, eps, ch_axis):
    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]
    out = (x - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + eps)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


@primitive(aux=2)
def _bn_train(x, weight, bias, eps, ch_axis):
    axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    mean = jnp.mean(x, axis=axes)
    var = jnp.var(x, axis=axes)
    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]
    out = (x - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + eps)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out, jax.lax.stop_gradient(mean), jax.lax.stop_gradient(var)


def batch_norm(
    x,
    running_mean,
    running_var,
    weight=None,
    bias=None,
    training=False,
    momentum=0.9,
    epsilon=1e-5,
    data_format="NCHW",
    use_global_stats=None,
):
    """Parity: batch_norm op (reference operators/batch_norm_op.cu). Updates
    running stats in-place on the provided Tensors when training."""
    from ..static.program import recording_active

    ch_axis = 1 if data_format.startswith("NC") or data_format in ("NC", "NCL") else (
        x.ndim if hasattr(x, "ndim") else unwrap(x).ndim) - 1
    if use_global_stats is None:
        use_global_stats = not training

    if recording_active():
        # static mode: one moded op whose `training` literal Program.clone
        # (for_test=True) can flip to inference behavior (parity: the
        # reference's op attr rewrite in clone-for-test)
        out, new_rm, new_rv = _bn_moded(
            x, running_mean, running_var, weight, bias, epsilon, ch_axis,
            momentum, not use_global_stats,
        )
        prog = out._program
        rec = prog.ops[-1]
        rec.tags = {"bn": True}
        if not use_global_stats and running_mean is not None:
            running_mean.set_value(new_rm)
            running_var.set_value(new_rv)
        return out

    if use_global_stats:
        return _bn_infer(x, running_mean, running_var, weight, bias, epsilon, ch_axis)
    out, batch_mean, batch_var = _bn_train(x, weight, bias, epsilon, ch_axis)
    if running_mean is not None:
        # reference updates running_var with the BIASED batch variance
        # (batch_norm_op.cc:380-416) — keep that exactly for eval parity.
        # Routed through a primitive so static-mode recording captures the
        # stat update as a program state write.
        running_mean._set_data(_bn_stat_update(running_mean, batch_mean, momentum))
        running_var._set_data(_bn_stat_update(running_var, batch_var, momentum))
    return out


@primitive(nondiff=True)
def _bn_stat_update(running, batch, momentum):
    return momentum * running + (1.0 - momentum) * batch


@primitive
def _bn_moded(x, rm, rv, weight, bias, eps, ch_axis, momentum, training):
    """Static-mode batch norm: `training` is a trace-time literal so the
    recorded op can be flipped to inference by Program.clone(for_test=True)."""
    if training:
        axes = tuple(i for i in range(x.ndim) if i != ch_axis)
        mean = jnp.mean(x, axis=axes)
        var = jnp.var(x, axis=axes)
    else:
        mean, var = rm, rv
    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]
    out = (x - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + eps)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    if training and rm is not None:
        new_rm = momentum * rm + (1.0 - momentum) * jax.lax.stop_gradient(mean)
        new_rv = momentum * rv + (1.0 - momentum) * jax.lax.stop_gradient(var)
    else:
        new_rm, new_rv = rm, rv
    return out, new_rm, new_rv


builtins_max = max


@primitive
def _in_norm(x, weight, bias, eps):
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + eps)
    if weight is not None:
        shape = [1, -1] + [1] * (x.ndim - 2)
        out = out * weight.reshape(shape)
    if bias is not None:
        shape = [1, -1] + [1] * (x.ndim - 2)
        out = out + bias.reshape(shape)
    return out


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None, use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW"):
    return _in_norm(x, weight, bias, eps)


@primitive
def _gn(x, weight, bias, eps, groups):
    n, c = x.shape[0], x.shape[1]
    g = groups
    xg = x.reshape((n, g, c // g) + x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    out = ((xg - mean) * jax.lax.rsqrt(var + eps)).reshape(x.shape)
    if weight is not None:
        shape = [1, c] + [1] * (x.ndim - 2)
        out = out * weight.reshape(shape)
    if bias is not None:
        shape = [1, c] + [1] * (x.ndim - 2)
        out = out + bias.reshape(shape)
    return out


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None, data_format="NCHW"):
    return _gn(x, weight, bias, epsilon, num_groups)


@primitive
def normalize(x, p=2, axis=1, epsilon=1e-12):
    n = jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=axis, keepdims=True), 1.0 / p)
    return x / jnp.maximum(n, epsilon)


@primitive
def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW"):
    sq = jnp.square(x)
    half = size // 2
    c = x.shape[1]
    pads = [(0, 0), (half, size - 1 - half)] + [(0, 0)] * (x.ndim - 2)
    padded = jnp.pad(sq, pads)
    acc = sum(padded[:, i : i + c] for i in range(size))
    return x / jnp.power(k + alpha * acc / size, beta)


# ---------------------------------------------------------------------------
# vision ops
# ---------------------------------------------------------------------------


@primitive
def pixel_shuffle(x, upscale_factor, data_format="NCHW"):
    r = upscale_factor
    n, c, h, w = x.shape
    out = x.reshape(n, c // (r * r), r, r, h, w)
    out = out.transpose(0, 1, 4, 2, 5, 3)
    return out.reshape(n, c // (r * r), h * r, w * r)


def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format="NCHW"):
    """Parity: *_interp_v2 ops. Supports nearest/bilinear/bicubic/trilinear/
    linear/area via jax.image.resize; align_corners handled with a custom grid."""
    arr = unwrap(x)
    nd = arr.ndim - 2
    if size is None:
        sf = _norm_tuple(scale_factor, nd)
        size = [int(arr.shape[2 + i] * sf[i]) for i in range(nd)]
    else:
        size = [int(unwrap(s)) for s in (size if isinstance(size, (list, tuple, Tensor)) else [size])]
    method = {
        "nearest": "nearest",
        "bilinear": "linear",
        "linear": "linear",
        "trilinear": "linear",
        "bicubic": "cubic",
        "area": "linear",
    }[mode]

    @primitive
    def _resize(x):
        out_shape = x.shape[:2] + tuple(size)
        if not align_corners or method == "nearest":
            return jax.image.resize(x, out_shape, method=method)
        # align_corners=True: gather on an endpoint-inclusive grid
        out = x
        for i in range(nd):
            axis = 2 + i
            in_s, out_s = x.shape[axis], size[i]
            if out_s == 1:
                coords = jnp.zeros((1,))
            else:
                coords = jnp.linspace(0.0, in_s - 1, out_s)
            lo = jnp.floor(coords).astype(jnp.int32)
            hi = jnp.minimum(lo + 1, in_s - 1)
            w_hi = (coords - lo).astype(x.dtype)
            out_lo = jnp.take(out, lo, axis=axis)
            out_hi = jnp.take(out, hi, axis=axis)
            bshape = [1] * out.ndim
            bshape[axis] = out_s
            w_hi = w_hi.reshape(bshape)
            out = out_lo * (1 - w_hi) + out_hi * w_hi
        return out

    return _resize(x)


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False, align_mode=0, data_format="NCHW"):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


@primitive
def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    """im2col (reference operators/unfold_op.cc)."""
    ks = _norm_tuple(kernel_sizes, 2)
    st = _norm_tuple(strides, 2)
    dl = _norm_tuple(dilations, 2)
    pd = _norm_tuple(paddings, 2) if not isinstance(paddings, (list, tuple)) or len(paddings) <= 2 else tuple(paddings)
    if len(pd) == 2:
        pd = (pd[0], pd[0], pd[1], pd[1])
    n, c, h, w = x.shape
    xp = jnp.pad(x, [(0, 0), (0, 0), (pd[0], pd[2]), (pd[1], pd[3])])
    out_h = (xp.shape[2] - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
    out_w = (xp.shape[3] - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
    cols = []
    for i in range(ks[0]):
        for j in range(ks[1]):
            patch = xp[
                :,
                :,
                i * dl[0] : i * dl[0] + out_h * st[0] : st[0],
                j * dl[1] : j * dl[1] + out_w * st[1] : st[1],
            ]
            cols.append(patch)
    out = jnp.stack(cols, axis=2)  # [n, c, k*k, oh, ow]
    return out.reshape(n, c * ks[0] * ks[1], out_h * out_w)


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------


def _reduce_loss(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def cross_entropy(
    input,  # noqa: A002
    label,
    weight=None,
    ignore_index=-100,
    reduction="mean",
    soft_label=False,
    axis=-1,
    use_softmax=True,
):
    """Parity: softmax_with_cross_entropy / cross_entropy2
    (reference operators/softmax_with_cross_entropy_op.cu)."""

    @primitive
    def _ce(input, label, weight):
        logp = jax.nn.log_softmax(input, axis=axis) if use_softmax else jnp.log(
            jnp.maximum(input, 1e-30)
        )
        if soft_label:
            loss = -jnp.sum(label * logp, axis=axis)
            if weight is not None:
                loss = loss * jnp.sum(label * weight, axis=axis)
            return _reduce_loss(loss, reduction)
        lbl = label
        if lbl.ndim == logp.ndim:
            lbl = jnp.squeeze(lbl, axis=axis)
        lbl = lbl.astype(jnp.int32)
        valid = lbl != ignore_index
        safe = jnp.where(valid, lbl, 0)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, axis), axis=axis)
        loss = -jnp.squeeze(picked, axis=axis)
        if weight is not None:
            w = weight[safe]
            loss = loss * w
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            if weight is not None:
                denom = jnp.sum(jnp.where(valid, weight[safe], 0.0))
            else:
                denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
            return jnp.sum(loss) / denom
        return _reduce_loss(loss, reduction)

    return _ce(input, unwrap(label), weight)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100, axis=-1, return_softmax=False):
    loss = cross_entropy(logits, label, reduction="none", soft_label=soft_label, ignore_index=ignore_index, axis=axis)
    from ..ops.manipulation import unsqueeze

    loss = unsqueeze(loss, axis if axis >= 0 else loss.ndim + 1 + axis)
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


@primitive
def _mse(input, label, reduction):
    return _reduce_loss(jnp.square(input - label), reduction)


def mse_loss(input, label, reduction="mean"):  # noqa: A002
    return _mse(input, unwrap(label), reduction)


@primitive
def _l1(input, label, reduction):
    return _reduce_loss(jnp.abs(input - label), reduction)


def l1_loss(input, label, reduction="mean"):  # noqa: A002
    return _l1(input, unwrap(label), reduction)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean"):  # noqa: A002
    @primitive
    def _nll(input, label, weight):
        lbl = label.astype(jnp.int32)
        valid = lbl != ignore_index
        safe = jnp.where(valid, lbl, 0)
        picked = jnp.take_along_axis(input, safe[:, None], axis=1)[:, 0]
        loss = -picked
        if weight is not None:
            loss = loss * weight[safe]
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            denom = (
                jnp.sum(jnp.where(valid, weight[safe], 0.0))
                if weight is not None
                else jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
            )
            return jnp.sum(loss) / denom
        return _reduce_loss(loss, reduction)

    return _nll(input, unwrap(label), weight)


def binary_cross_entropy(input, label, weight=None, reduction="mean"):  # noqa: A002
    @primitive
    def _bce(input, label, weight):
        eps = 1e-12
        loss = -(label * jnp.log(jnp.maximum(input, eps)) + (1 - label) * jnp.log(jnp.maximum(1 - input, eps)))
        if weight is not None:
            loss = loss * weight
        return _reduce_loss(loss, reduction)

    return _bce(input, unwrap(label), weight)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean", pos_weight=None):
    @primitive
    def _bcel(logit, label, weight, pos_weight):
        neg_abs = -jnp.abs(logit)
        loss = jnp.maximum(logit, 0.0) - logit * label + jnp.log1p(jnp.exp(neg_abs))
        if pos_weight is not None:
            log_w = (pos_weight - 1.0) * label + 1.0
            loss = loss * log_w
        if weight is not None:
            loss = loss * weight
        return _reduce_loss(loss, reduction)

    return _bcel(logit, unwrap(label), weight, pos_weight)


@primitive
def _sl1(input, label, reduction, delta):
    d = jnp.abs(input - label)
    loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
    return _reduce_loss(loss, reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0):  # noqa: A002
    return _sl1(input, unwrap(label), reduction, delta)


def kl_div(input, label, reduction="mean"):  # noqa: A002
    @primitive
    def _kl(input, label):
        loss = label * (jnp.log(jnp.maximum(label, 1e-30)) - input)
        if reduction == "batchmean":
            return jnp.sum(loss) / input.shape[0]
        return _reduce_loss(loss, reduction)

    return _kl(input, unwrap(label))


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean"):  # noqa: A002
    @primitive
    def _mr(input, other, label):
        loss = jnp.maximum(-label * (input - other) + margin, 0.0)
        return _reduce_loss(loss, reduction)

    return _mr(input, other, unwrap(label))


@primitive
def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.sqrt(jnp.sum(x1 * x1, axis=axis))
    n2 = jnp.sqrt(jnp.sum(x2 * x2, axis=axis))
    return dot / jnp.maximum(n1 * n2, eps)


@primitive
def label_smooth(label, prior_dist=None, epsilon=0.1):
    k = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * prior_dist
    return (1 - epsilon) * label + epsilon / k


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0, reduction="sum"):
    @primitive
    def _focal(logit, label, normalizer):
        p = jax.nn.sigmoid(logit)
        ce = jnp.maximum(logit, 0.0) - logit * label + jnp.log1p(jnp.exp(-jnp.abs(logit)))
        p_t = p * label + (1 - p) * (1 - label)
        a_t = alpha * label + (1 - alpha) * (1 - label)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if normalizer is not None:
            loss = loss / normalizer
        return _reduce_loss(loss, reduction)

    return _focal(logit, unwrap(label), normalizer)


def square_error_cost(input, label):  # noqa: A002
    @primitive
    def _sec(input, label):
        return jnp.square(input - label)

    return _sec(input, unwrap(label))


# ---------------------------------------------------------------------------
# sequence utilities
# ---------------------------------------------------------------------------


def sequence_mask(lengths, maxlen=None, dtype="int64"):
    from ..dtype import to_jax_dtype

    arr = unwrap(lengths)
    if maxlen is None:
        maxlen = int(jnp.max(arr))
    mask = jnp.arange(maxlen)[None, :] < arr[..., None]
    return wrap(mask.astype(to_jax_dtype(dtype)))


def diag_embed(input, offset=0, dim1=-2, dim2=-1):  # noqa: A002
    @primitive
    def _de(input):
        out = jnp.zeros(input.shape + (input.shape[-1],), input.dtype)
        idx = jnp.arange(input.shape[-1])
        out = out.at[..., idx, idx].set(input)
        return out

    return _de(input)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """Connectionist temporal classification loss (parity: the warpctc op,
    reference operators/warpctc_op.* and python/paddle/nn/functional/loss.py
    ctc_loss). Like warpctc, inputs are unnormalized logits — log_softmax is
    applied internally (idempotent if the input is already log-probs).

    log_probs: (T, B, C); labels: (B, L) padded."""
    from .layers.loss import CTCLoss

    return CTCLoss(blank=blank, reduction=reduction)(
        log_probs, labels, input_lengths, label_lengths, norm_by_times)


def gather_tree(ids, parents):
    """Backtrack beam-search trees: reconstruct full beams from per-step ids
    and parent indices (parity: gather_tree op,
    reference operators/gather_tree_op.cc; python/paddle/nn/functional —
    used by fluid.layers.BeamSearchDecoder).

    ids, parents: (max_time, batch, beam) int. Returns same shape."""

    @primitive(nondiff=True)
    def _gt(ids, parents):
        T = ids.shape[0]
        beam = ids.shape[2]
        beam_idx = jnp.arange(beam, dtype=parents.dtype)

        def step(parent, tp):
            step_ids, step_parents = tp
            out = jnp.take_along_axis(step_ids, parent, axis=-1)
            new_parent = jnp.take_along_axis(step_parents, parent, axis=-1)
            return new_parent, out

        init = jnp.broadcast_to(beam_idx, ids.shape[1:])
        # walk from the last step backwards
        _, outs = jax.lax.scan(step, init, (ids, parents), reverse=True)
        return outs

    return _gt(ids, parents)


def edit_distance(input, label, normalized=True, ignored_tokens=None,  # noqa: A002
                  input_length=None, label_length=None):
    """Levenshtein distance per batch row (parity: edit_distance op,
    reference operators/edit_distance_op.* and fluid/layers/nn.py). Padded
    dense layout: input (B, L1), label (B, L2) int64 with optional lengths.

    Returns (distance (B, 1) float32, sequence_num (1,) int64)."""

    @primitive(nondiff=True)
    def _ed(hyp, ref, hyp_len, ref_len):
        B, L1 = hyp.shape
        L2 = ref.shape[1]
        row0 = jnp.broadcast_to(
            jnp.arange(L2 + 1, dtype=jnp.float32), (B, L2 + 1))

        def outer(row_prev, i):
            # compute row i of the DP table for all batches
            def inner(left, j):
                # left = d[i][j-1]; row_prev[j-1] = d[i-1][j-1]; row_prev[j] = d[i-1][j]
                sub = row_prev[:, j - 1] + (hyp[:, i - 1] != ref[:, j - 1])
                val = jnp.minimum(jnp.minimum(row_prev[:, j] + 1.0, left + 1.0), sub)
                return val, val

            first = jnp.full((B,), i, jnp.float32)
            _, rest = jax.lax.scan(inner, first, jnp.arange(1, L2 + 1))
            row = jnp.concatenate([first[:, None], rest.T], axis=1)
            return row, row

        _, rows = jax.lax.scan(outer, row0, jnp.arange(1, L1 + 1))
        table = jnp.concatenate([row0[None], rows], axis=0)  # (L1+1, B, L2+1)
        d = table[hyp_len, jnp.arange(B), ref_len]
        # all-empty hypothesis/reference corner: d(0, n) = n handled by table
        return d

    hyp = unwrap(input)
    ref = unwrap(label)
    B, L1 = hyp.shape
    L2 = ref.shape[1]
    # lengths may come as (B,) or paddle's documented (B, 1)
    hyp_len = unwrap(input_length).astype(jnp.int32).reshape(-1) \
        if input_length is not None else jnp.full((B,), L1, jnp.int32)
    ref_len = unwrap(label_length).astype(jnp.int32).reshape(-1) \
        if label_length is not None else jnp.full((B,), L2, jnp.int32)

    if ignored_tokens:
        ign = jnp.asarray(list(ignored_tokens))

        def _compress(seq, ln):
            keep = ~jnp.isin(seq, ign) & (jnp.arange(seq.shape[1])[None] < ln[:, None])
            # stable partition: kept tokens first, padding after
            order = jnp.argsort(~keep, axis=1, stable=True)
            return jnp.take_along_axis(seq, order, axis=1), keep.sum(1).astype(jnp.int32)

        hyp, hyp_len = _compress(hyp, hyp_len)
        ref, ref_len = _compress(ref, ref_len)

    d = _ed(hyp, ref, hyp_len, ref_len)
    dist = d._data if isinstance(d, Tensor) else d
    if normalized:
        dist = dist / jnp.maximum(ref_len.astype(jnp.float32), 1.0)
    return wrap(dist[:, None]), wrap(jnp.asarray(np.array([B], dtype=np.int64)))


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCHW", name=None):
    """Inverse of max_pool2d(return_mask=True): scatter pooled values back
    to their argmax positions (parity: unpool op, operators/unpool_op.*)."""
    ks = _norm_tuple(kernel_size, 2)
    st = _norm_tuple(stride if stride is not None else kernel_size, 2)
    pd = _norm_tuple(padding, 2)

    @primitive
    def _unpool(x, indices):
        n, c, hout, wout = x.shape
        if output_size is not None:
            oh, ow = int(output_size[-2]), int(output_size[-1])
        else:
            oh = (hout - 1) * st[0] - 2 * pd[0] + ks[0]
            ow = (wout - 1) * st[1] - 2 * pd[1] + ks[1]
        flat = jnp.zeros((n, c, oh * ow), x.dtype)
        idx = indices.reshape(n, c, hout * wout).astype(jnp.int32)
        vals = x.reshape(n, c, hout * wout)
        # assignment, not accumulation: overlapping windows sharing an argmax
        # all carry the same source value (reference unpool writes out[idx]=v)
        flat = flat.at[
            jnp.arange(n)[:, None, None], jnp.arange(c)[None, :, None], idx
        ].set(vals)
        return flat.reshape(n, c, oh, ow)

    return _unpool(x, indices)


def thresholded_relu(x, threshold=1.0, name=None):
    """x if x > threshold else 0 (parity: thresholded_relu op)."""

    @primitive
    def _tr(x):
        return jnp.where(x > threshold, x, 0.0)

    return _tr(x)


def dice_loss(input, label, epsilon=1e-5, name=None):  # noqa: A002
    """Dice coefficient loss over the last (class-prob) axis (parity:
    fluid.layers.dice_loss)."""

    @primitive
    def _dice(input, label):
        lab = jax.nn.one_hot(label[..., 0].astype(jnp.int32), input.shape[-1],
                             dtype=input.dtype)
        red = tuple(range(1, input.ndim))
        inter = (input * lab).sum(red)
        union = input.sum(red) + lab.sum(red)
        return 1.0 - ((2.0 * inter + epsilon) / (union + epsilon)).mean()

    return _dice(input, unwrap(label))


def log_loss(input, label, epsilon=1e-4, name=None):  # noqa: A002
    """Negative log likelihood of a binary probability (parity: log_loss op)."""

    @primitive
    def _ll(input, label):
        return (-label * jnp.log(input + epsilon)
                - (1.0 - label) * jnp.log(1.0 - input + epsilon))

    return _ll(input, label)


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """N-pair metric loss (parity: fluid.layers.npair_loss composition)."""

    @primitive
    def _npair(anchor, positive, labels):
        lab = labels.reshape(-1)
        batch = lab.shape[0]
        same = (lab[:, None] == lab[None, :]).astype(anchor.dtype)
        tgt = same / jnp.maximum(same.sum(-1, keepdims=True), 1.0)
        logits = jnp.matmul(anchor, positive.T)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ce = -(tgt * logp).sum(-1).mean()
        reg = (jnp.sum(anchor * anchor) + jnp.sum(positive * positive)) / batch
        return ce + l2_reg * reg * 0.25

    return _npair(anchor, positive, unwrap(labels))


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    """Gumbel-softmax sampling with optional straight-through hard one-hot
    (parity: gumbel_softmax op)."""
    g = -jnp.log(-jnp.log(
        jax.random.uniform(split_key(), unwrap(x).shape, jnp.float32, 1e-10, 1.0)))

    @primitive
    def _gs(x):
        y = jax.nn.softmax((x + g.astype(x.dtype)) / temperature, axis=axis)
        if hard:
            oh = jax.nn.one_hot(jnp.argmax(y, axis=axis), y.shape[axis],
                                axis=axis, dtype=y.dtype)
            # straight-through: hard value, soft gradient
            y = jax.lax.stop_gradient(oh - y) + y
        return y

    return _gs(x)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    """TSM channel shift across the time axis (parity: temporal_shift op):
    the first shift_ratio*C channels shift t-1, the next shift t+1."""
    if data_format != "NCHW":
        raise ValueError("temporal_shift supports NCHW")

    @primitive
    def _ts(x):
        nt, c, h, w = x.shape
        n = nt // seg_num
        v = x.reshape(n, seg_num, c, h, w)
        c1 = int(c * shift_ratio)
        c2 = int(c * 2 * shift_ratio)
        fwd = jnp.pad(v[:, 1:, :c1], ((0, 0), (0, 1), (0, 0), (0, 0), (0, 0)))
        bwd = jnp.pad(v[:, :-1, c1:c2], ((0, 0), (1, 0), (0, 0), (0, 0), (0, 0)))
        out = jnp.concatenate([fwd, bwd, v[:, :, c2:]], axis=2)
        return out.reshape(nt, c, h, w)

    return _ts(x)


def bilinear(x1, x2, weight, bias=None, name=None):
    """Bilinear transform out[b, o] = x1[b] @ W[o] @ x2[b] (parity:
    bilinear_tensor_product op)."""

    @primitive
    def _bl(x1, x2, weight, bias):
        out = jnp.einsum("bi,oij,bj->bo", x1, weight, x2)
        if bias is not None:
            out = out + bias
        return out

    return _bl(x1, x2, weight, bias)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """Affine sampling grid from 2x3 batch matrices (parity: affine_grid op).
    Returns (N, H, W, 2) normalized coords."""
    if not isinstance(out_shape, (list, tuple)):
        out_shape = [int(v) for v in np.asarray(unwrap(out_shape))]
    n, _, h, w = [int(v) for v in out_shape]

    @primitive
    def _ag(theta):
        def axis_coords(size):
            if align_corners:
                return jnp.linspace(-1.0, 1.0, size)
            step = 2.0 / size
            return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, size)

        ys = axis_coords(h)
        xs = axis_coords(w)
        gx, gy = jnp.meshgrid(xs, ys)  # (h, w)
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # (h, w, 3)
        return jnp.einsum("hwk,nck->nhwc", base, theta.astype(jnp.float32)
                          ).astype(theta.dtype)

    return _ag(theta)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample x (N,C,H,W) at normalized grid coords (N,Hg,Wg,2) (parity:
    grid_sampler op)."""

    @primitive
    def _gs(x, grid):
        n, c, h, w = x.shape
        gx = grid[..., 0].astype(jnp.float32)
        gy = grid[..., 1].astype(jnp.float32)
        if align_corners:
            fx = (gx + 1.0) * (w - 1) / 2.0
            fy = (gy + 1.0) * (h - 1) / 2.0
        else:
            fx = ((gx + 1.0) * w - 1.0) / 2.0
            fy = ((gy + 1.0) * h - 1.0) / 2.0
        if padding_mode == "border":
            fx = jnp.clip(fx, 0, w - 1)
            fy = jnp.clip(fy, 0, h - 1)
        elif padding_mode == "reflection":
            def reflect(v, size):
                if align_corners:
                    span = 2.0 * (size - 1)
                    v = jnp.abs(jnp.mod(v, span))
                    return jnp.where(v > size - 1, span - v, v)
                span = 2.0 * size
                v = jnp.abs(jnp.mod(v + 0.5, span))
                v = jnp.where(v > size, span - v, v) - 0.5
                return jnp.clip(v, 0, size - 1)

            fx = reflect(fx, w)
            fy = reflect(fy, h)

        def sample_one(fm, yy, xx):
            if mode == "nearest":
                xi = jnp.round(xx).astype(jnp.int32)
                yi = jnp.round(yy).astype(jnp.int32)
                inb = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
                xi = jnp.clip(xi, 0, w - 1)
                yi = jnp.clip(yi, 0, h - 1)
                return jnp.where(inb[None], fm[:, yi, xi], 0.0)
            x0 = jnp.floor(xx).astype(jnp.int32)
            y0 = jnp.floor(yy).astype(jnp.int32)
            x1, y1 = x0 + 1, y0 + 1
            lx, ly = xx - x0, yy - y0

            def tap(yi, xi, wgt):
                inb = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
                v = fm[:, jnp.clip(yi, 0, h - 1), jnp.clip(xi, 0, w - 1)]
                return jnp.where(inb[None], v, 0.0) * wgt[None]

            return (tap(y0, x0, (1 - ly) * (1 - lx)) + tap(y0, x1, (1 - ly) * lx)
                    + tap(y1, x0, ly * (1 - lx)) + tap(y1, x1, ly * lx))

        return jax.vmap(sample_one)(x, fy, fx).astype(x.dtype)

    return _gs(x, grid)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False,
                         reduction="mean"):
    """Combined-margin softmax CE over cosine logits (parity:
    margin_cross_entropy op, operators/margin_cross_entropy_op.cu —
    ArcFace/CosFace family: target logit cos(m1*theta + m2) - m3)."""

    @primitive
    def _mce(logits, label):
        lab = label.reshape(-1).astype(jnp.int32)
        cos = jnp.clip(logits, -1.0, 1.0)
        theta = jnp.arccos(cos)
        tgt = jnp.cos(margin1 * theta + margin2) - margin3
        oh = jax.nn.one_hot(lab, logits.shape[-1], dtype=logits.dtype)
        out = jnp.where(oh > 0, tgt, cos) * scale
        logp = jax.nn.log_softmax(out, axis=-1)
        loss = -jnp.take_along_axis(logp, lab[:, None], axis=-1)
        sm = jnp.exp(logp)
        return loss, sm

    loss, sm = _mce(logits, unwrap(label))
    if reduction == "mean":
        loss = loss.mean()
    elif reduction == "sum":
        loss = loss.sum()
    if return_softmax:
        return loss, sm
    return loss


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample class centers: all positive classes plus random negatives up
    to num_samples; labels remapped into the sampled list (parity:
    class_center_sample op). Host-side sampling (eager data-prep op)."""
    lab = np.asarray(unwrap(label)).reshape(-1)
    pos = np.unique(lab)
    if len(pos) >= num_samples:
        # reference semantics: every positive class is kept even when that
        # exceeds num_samples (the output simply grows)
        sampled = pos
    else:
        neg_pool = np.setdiff1d(np.arange(num_classes), pos, assume_unique=False)
        # negatives drawn from the framework PRNG stream (paddle.seed-driven,
        # varies per call like dropout/gumbel keys)
        seed = int(np.asarray(
            jax.random.randint(split_key(), (), 0, 2**31 - 1)))
        extra = np.random.default_rng(seed).choice(
            neg_pool, num_samples - len(pos), replace=False)
        sampled = np.concatenate([pos, np.sort(extra)])
    remap = -np.ones(num_classes, np.int64)
    remap[sampled] = np.arange(len(sampled))
    return (wrap(jnp.asarray(remap[lab])), wrap(jnp.asarray(sampled.astype(np.int64))))


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     name=None):
    """Block-sparse attention given a CSR pattern (parity: sparse_attention
    op, operators/sparse_attention_op.cu). TPU-native: the CSR pattern is
    densified to an additive mask once (host side) and the product runs
    through the fused XLA softmax path — HBM-efficient sparse kernels are
    the flash/ring Pallas paths; this op exists for API parity."""
    offs = np.asarray(unwrap(sparse_csr_offset))
    cols = np.asarray(unwrap(sparse_csr_columns))
    T = int(unwrap(query).shape[-2])
    # build (..., T, T) mask from CSR in one vectorized shot: the row of
    # nonzero j is the number of offset entries <= j, minus one
    lead = offs.shape[:-1]
    nnz = cols.shape[-1]
    j = np.arange(nnz)
    rows = (offs[..., :-1, None] <= j).sum(axis=-2) - 1  # (..., nnz)
    valid = (j < offs[..., -1:])  # entries beyond offs[-1] are padding
    # extra scrap slot absorbs padding writes without clobbering cell 0
    mask = np.zeros(lead + (T * T + 1,), bool)
    flat_idx = np.where(valid, np.clip(rows, 0, T - 1) * T + cols, T * T)
    np.put_along_axis(mask, flat_idx, True, axis=-1)
    mask = mask[..., : T * T].reshape(lead + (T, T))
    amask = jnp.where(jnp.asarray(mask), 0.0, -1e9)

    @primitive
    def _sa(q, k, v):
        s = jnp.einsum("...td,...sd->...ts", q, k) / math.sqrt(q.shape[-1])
        w = jax.nn.softmax(s + amask.astype(s.dtype), axis=-1)
        return jnp.einsum("...ts,...sd->...td", w, v)

    return _sa(query, key, value)


def fused_multi_head_attention(x, qkv_weight, linear_weight, pre_layer_norm=False,
                               pre_ln_scale=None, pre_ln_bias=None, ln_scale=None,
                               ln_bias=None, pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, attn_mask=None, dropout_rate=0.0,
                               attn_dropout_rate=0.0, ln_epsilon=1e-5,
                               num_heads=None, name=None):
    """Functional fused MHA block (parity: fused_attention op,
    operators/fused/fused_attention_op.cu — LN + qkv matmul + attention +
    out-proj + residual + LN, one graph for XLA to fuse)."""
    from .functional_attention import scaled_dot_product_attention

    residual = x
    if pre_layer_norm:
        x = layer_norm(x, x.shape[-1:], pre_ln_scale, pre_ln_bias, pre_ln_epsilon)
    qw = unwrap(qkv_weight)
    # accept (3, H, D, hidden) paddle layout or (hidden, 3*hidden)
    if qw.ndim == 4:
        three, heads, hd, hidden = qw.shape

        @primitive
        def _qkv(x, qw, qb):
            out = jnp.einsum("bth,nkdh->btnkd", x, qw)  # n=3, k=heads
            if qb is not None:
                out = out + qb.reshape(1, 1, three, heads, hd)
            return out

        qkv_out = _qkv(x, qkv_weight, qkv_bias)
        from ..ops import manipulation as manip

        q = manip.transpose(qkv_out[:, :, 0], [0, 2, 1, 3])
        k = manip.transpose(qkv_out[:, :, 1], [0, 2, 1, 3])
        v = manip.transpose(qkv_out[:, :, 2], [0, 2, 1, 3])
    else:
        hidden = qw.shape[0]
        heads = num_heads
        if heads is None:
            raise ValueError("num_heads required with 2-D qkv_weight")
        hd = hidden // heads
        from ..ops import manipulation as manip

        qkv_out = linear(x, qkv_weight, qkv_bias)
        b, t = qkv_out.shape[0], qkv_out.shape[1]
        qkv_out = manip.reshape(qkv_out, [b, t, 3, heads, hd])
        qkv_out = manip.transpose(qkv_out, [2, 0, 3, 1, 4])
        q, k, v = qkv_out[0], qkv_out[1], qkv_out[2]
    out, _ = scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask, dropout_p=attn_dropout_rate)
    from ..ops import manipulation as manip

    b, t = out.shape[0], out.shape[2]
    out = manip.transpose(out, [0, 2, 1, 3])
    out = manip.reshape(out, [b, t, -1])
    out = linear(out, linear_weight, linear_bias)
    if dropout_rate:
        out = dropout(out, p=dropout_rate)
    out = residual + out
    if not pre_layer_norm:
        out = layer_norm(out, out.shape[-1:], ln_scale, ln_bias, ln_epsilon)
    return out


def hsigmoid_loss(input, label, num_classes, weight, bias=None,  # noqa: A002
                  path_table=None, path_code=None, is_sparse=False, name=None):
    """Functional hierarchical sigmoid (parity: hierarchical_sigmoid op,
    default complete-binary-tree mode)."""

    @primitive
    def _hs(input, label, weight, bias):
        # complete binary tree over num_classes leaves: internal nodes
        # num_classes-1; path of leaf c = bits of (c + num_classes) walk
        code_len = int(np.ceil(np.log2(max(num_classes, 2))))
        lab = label.reshape(-1).astype(jnp.int32)
        node = lab + num_classes
        losses = jnp.zeros(lab.shape, input.dtype)
        for _ in range(code_len):
            parent = node // 2
            bit = (node % 2).astype(input.dtype)  # 1 = right child
            valid = parent >= 1
            w = weight[jnp.clip(parent - 1, 0, weight.shape[0] - 1)]
            logit = jnp.einsum("bh,bh->b", input, w)
            if bias is not None:
                logit = logit + bias[jnp.clip(parent - 1, 0, bias.shape[0] - 1)].reshape(-1)
            step_loss = jnp.maximum(logit, 0) - logit * bit + jnp.log1p(jnp.exp(-jnp.abs(logit)))
            losses = losses + jnp.where(valid, step_loss, 0.0)
            node = parent
        return losses[:, None]  # per-sample [N, 1] (reference hsigmoid_loss)

    return _hs(input, unwrap(label), weight, bias)


# in-place activation variants (parity: paddle's *_ inplace APIs)
def relu_(x):
    from ..ops._primitive import inplace_guard

    inplace_guard(x, "relu_")
    x._set_data(jax.nn.relu(x._data))
    return x


def elu_(x, alpha=1.0):
    from ..ops._primitive import inplace_guard

    inplace_guard(x, "elu_")
    x._set_data(jax.nn.elu(x._data, alpha))
    return x


def softmax_(x, axis=-1):
    from ..ops._primitive import inplace_guard

    inplace_guard(x, "softmax_")
    x._set_data(jax.nn.softmax(x._data, axis=axis))
    return x


# paddle.nn.functional re-exports of tensor ops sharing one implementation
from ..ops.manipulation import pad  # noqa: E402,F401
from ..ops.math import tanh_  # noqa: E402,F401


# ---------------------------------------------------------------------------
# round-2 gap fill: vision rearrange + loss family completion (reference
# functional surface: pixel_unshuffle/channel_shuffle/fold + margin losses)
# ---------------------------------------------------------------------------
def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = int(downscale_factor)

    @primitive(name="pixel_unshuffle")
    def _op(x):
        if data_format == "NCHW":
            n, c, h, w = x.shape
            x2 = x.reshape(n, c, h // r, r, w // r, r)
            return x2.transpose(0, 1, 3, 5, 2, 4).reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = x.shape
        x2 = x.reshape(n, h // r, r, w // r, r, c)
        return x2.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // r, w // r, c * r * r)

    return _op(x)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    g = int(groups)

    @primitive(name="channel_shuffle")
    def _op(x):
        if data_format == "NCHW":
            n, c, h, w = x.shape
            return x.reshape(n, g, c // g, h, w).transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)
        n, h, w, c = x.shape
        return x.reshape(n, h, w, g, c // g).transpose(0, 1, 2, 4, 3).reshape(n, h, w, c)

    return _op(x)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    l, r, t, b = (padding if isinstance(padding, (list, tuple))
                  else (padding,) * 4)

    @primitive(name="zeropad2d")
    def _op(x):
        if data_format == "NCHW":
            return jnp.pad(x, ((0, 0), (0, 0), (t, b), (l, r)))
        return jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0)))

    return _op(x)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """col2im (reference fold op): inverse of unfold with overlap-add."""
    pair = lambda v: tuple(v) if isinstance(v, (list, tuple)) else (int(v),) * 2
    H, W = pair(output_sizes)
    kh, kw = pair(kernel_sizes)
    sh, sw = pair(strides)
    ph, pw = pair(paddings)
    dh, dw = pair(dilations)
    oh = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    ow = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1

    @primitive(name="fold")
    def _op(x):
        n, ckk, l = x.shape
        c = ckk // (kh * kw)
        cols = x.reshape(n, c, kh, kw, oh, ow)
        out = jnp.zeros((n, c, H + 2 * ph, W + 2 * pw), x.dtype)
        for i in range(kh):
            for j in range(kw):
                out = out.at[:, :, i * dh: i * dh + sh * oh: sh,
                             j * dw: j * dw + sw * ow: sw].add(cols[:, :, i, j])
        return out[:, :, ph: ph + H, pw: pw + W]

    return _op(x)


def _reduce_loss_t(loss, reduction):
    """Tensor-level reduction (taped ops; _reduce_loss works on raw arrays
    inside primitive closures)."""
    if reduction == "mean":
        return loss.mean()
    if reduction == "sum":
        return loss.sum()
    return loss


def soft_margin_loss(input, label, reduction="mean", name=None):  # noqa: A002
    @primitive(name="soft_margin_loss")
    def _op(x, y):
        # softplus(-yx) == log1p(exp(-yx)), overflow-stable at large logits
        return jax.nn.softplus(-y.astype(x.dtype) * x)

    return _reduce_loss_t(_op(input, unwrap(label)), reduction)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,  # noqa: A002
                      reduction="mean", name=None):
    @primitive(name="multi_margin_loss")
    def _op(x, y):
        n, c = x.shape
        correct = jnp.take_along_axis(x, y[:, None].astype(jnp.int32), axis=1)
        m = jnp.maximum(0.0, margin - correct + x) ** p
        if weight is not None:
            m = m * jnp.take(unwrap(weight), y.astype(jnp.int32))[:, None]
        mask = jnp.arange(c)[None, :] != y[:, None]
        return jnp.where(mask, m, 0.0).sum(-1) / c

    return _reduce_loss_t(_op(input, unwrap(label)), reduction)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    @primitive(name="pairwise_distance")
    def _op(x, y):
        d = x - y + epsilon
        return jnp.linalg.norm(d, ord=p, axis=-1, keepdims=keepdim)

    return _op(x, y)


def pdist(x, p=2.0, name=None):
    n = unwrap(x).shape[0]
    import numpy as _np

    i_idx, j_idx = _np.triu_indices(n, k=1)

    @primitive(name="pdist")
    def _op(x):
        # gather the distinct pairs FIRST: norms at exactly zero have NaN
        # vjp, and the diagonal would poison gradients even when discarded
        d = x[jnp.asarray(i_idx)] - x[jnp.asarray(j_idx)] + 1e-6
        return jnp.linalg.norm(d, ord=p, axis=-1)

    return _op(x)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,  # noqa: A002
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    dp = pairwise_distance(input, positive, p, epsilon)
    dn = pairwise_distance(input, negative, p, epsilon)
    if swap:
        dn2 = pairwise_distance(positive, negative, p, epsilon)
        from ..ops import math as M

        dn = M.minimum(dn, dn2)

    @primitive(name="triplet_margin_loss")
    def _op(dp, dn):
        return jnp.maximum(dp - dn + margin, 0.0)

    return _reduce_loss_t(_op(dp, dn), reduction)


def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction="mean", name=None):
    @primitive(name="cosine_embedding_loss")
    def _op(a, b, y):
        cos = (a * b).sum(-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        return jnp.where(y.astype(jnp.int32) == 1, 1.0 - cos,
                         jnp.maximum(0.0, cos - margin))

    return _reduce_loss_t(_op(input1, input2, unwrap(label)), reduction)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,  # noqa: A002
                      reduction="mean", name=None):
    @primitive(name="gaussian_nll_loss")
    def _op(mu, y, var):
        var = jnp.maximum(var, epsilon)
        loss = 0.5 * (jnp.log(var) + (y - mu) ** 2 / var)
        if full:
            loss = loss + 0.5 * jnp.log(jnp.asarray(2.0 * jnp.pi, mu.dtype))
        return loss

    return _reduce_loss_t(_op(input, unwrap(label), unwrap(variance)), reduction)


def poisson_nll_loss(input, label, log_input=True, full=False,  # noqa: A002
                     epsilon=1e-8, reduction="mean", name=None):
    @primitive(name="poisson_nll_loss")
    def _op(x, y):
        if log_input:
            loss = jnp.exp(x) - y * x
        else:
            loss = x - y * jnp.log(jnp.maximum(x, epsilon))
        if full:
            # Stirling approximation for target! (reference semantics)
            stir = y * jnp.log(jnp.maximum(y, 1.0)) - y + 0.5 * jnp.log(
                jnp.maximum(2.0 * jnp.pi * y, 1.0))
            loss = loss + jnp.where(y > 1, stir, 0.0)
        return loss

    return _reduce_loss_t(_op(input, unwrap(label)), reduction)


def multi_label_soft_margin_loss(input, label, weight=None,  # noqa: A002
                                 reduction="mean", name=None):
    @primitive(name="multi_label_soft_margin_loss")
    def _op(x, y):
        loss = y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x)
        if weight is not None:
            loss = loss * unwrap(weight)
        return -loss.mean(-1)

    return _reduce_loss_t(_op(input, unwrap(label)), reduction)


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True, name=None):
    if not training:
        @primitive(name="rrelu")
        def _op(x):
            neg = (lower + upper) / 2.0
            return jnp.where(x >= 0, x, x * neg)

        return _op(x)
    arr = unwrap(x)
    slope = jax.random.uniform(split_key(), arr.shape, jnp.float32,
                               lower, upper).astype(arr.dtype)

    @primitive(name="rrelu_train")
    def _op(x):
        return jnp.where(x >= 0, x, x * slope)

    return _op(x)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,  # noqa: A002
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (reference:
    operators/hierarchical_sigmoid_op.h + math/matrix_bit_code.h SimpleCode).

    Default tree: class c encodes as ``c + num_classes``; walking the
    code's bits gives, per level j, the internal-node weight row
    ``(code >> (j+1)) - 1`` and the binary target ``(code >> j) & 1``.
    ``pre_out[i, j] = clip(bias[node] + w[node] . x[i], -40, 40)`` for
    levels on the path (zero off-path — the reference's padded slots
    contribute the constant ln 2 via softplus, kept for parity), and
    ``loss_i = sum_j softplus(pre_out) - sum_{j: bit set} pre_out``.

    Custom tree: ``path_table``/``path_code`` rows give the node ids /
    binary codes (entry < 0 = padding); rows are indexed by the sample's
    label, or taken per sample when the leading dim equals the batch.
    weight: [num_classes - 1, D] (default tree). Returns [N, 1] losses.
    """
    nc = int(num_classes)

    @primitive
    def _hs(x, lbl, w, b, ptab, pcode):
        lbl = lbl.reshape(-1).astype(jnp.int32)
        bsz = x.shape[0]
        if ptab is None:
            L = max(int(nc - 1).bit_length(), 1)
            c = lbl + nc  # SimpleCode: root id 1 => encode as c + num_classes
            js = jnp.arange(L)
            node = (c[:, None] >> (js[None, :] + 1)) - 1      # [B, L]
            bit = (c[:, None] >> js[None, :]) & 1
            valid = ((c[:, None] >> (js[None, :] + 1)) > 0)
        else:
            rows = ptab if ptab.shape[0] == bsz else jnp.take(
                ptab, lbl, axis=0)
            codes = pcode if pcode.shape[0] == bsz else jnp.take(
                pcode, lbl, axis=0)
            node = rows.astype(jnp.int32)
            bit = codes.astype(jnp.int32)
            valid = node >= 0
            node = jnp.where(valid, node, 0)
        wn = jnp.take(w, node, axis=0)                        # [B, L, D]
        pre = jnp.einsum("bld,bd->bl", wn, x)
        if b is not None:
            pre = pre + jnp.take(b.reshape(-1), node, axis=0)
        pre = jnp.clip(pre, -40.0, 40.0)
        pre = jnp.where(valid, pre, 0.0)
        soft = jnp.log1p(jnp.exp(pre))                        # softplus
        loss = soft.sum(-1) - jnp.where(valid & (bit > 0), pre, 0.0).sum(-1)
        return loss[:, None]

    return _hs(input, unwrap(label), weight,
               None if bias is None else unwrap(bias),
               None if path_table is None else unwrap(path_table),
               None if path_code is None else unwrap(path_code))


def nce(input, label, num_total_classes, weight, bias=None,  # noqa: A002
        num_neg_samples=10, sampler="uniform", custom_dist=None,
        sample_weight=None, seed=None, is_test=False, name=None):
    """Noise-contrastive estimation loss (reference: operators/nce_op.h
    NCEKernel; python fluid.layers.nce): per row, the true classes and
    ``num_neg_samples`` sampled noise classes get logits
    sigmoid(bias[c] + x . w[c]); cost sums -log(o/(o+b)) over true and
    -log(b/(o+b)) over noise with b = P_noise(c) * num_neg_samples.

    Samplers: 'uniform', 'log_uniform' (inverse-CDF draw of the reference
    LogUniformSampler's (log(v+2)-log(v+1))/log(range+2) distribution) and
    'custom_dist' (categorical over ``custom_dist`` — the reference's
    alias tables are a CPU sampling trick and are not needed here).
    Noise draws come from the framework PRNG each call. Returns [N, 1].
    """
    from ..random import split_key

    n_neg = int(num_neg_samples)
    nt = int(num_total_classes)
    mode = {"uniform": 0, "log_uniform": 1, "custom_dist": 2}[sampler]
    probs = None
    if mode == 2:
        if custom_dist is None:
            raise ValueError("custom_dist sampler needs custom_dist probs")
        probs = unwrap(custom_dist)
    kd = jax.random.key_data(split_key())

    @primitive
    def _nce(x, lbl, w, b, sw, probs, kd):
        key = jax.random.wrap_key_data(kd)
        bsz = x.shape[0]
        lbl2 = lbl.reshape(bsz, -1).astype(jnp.int32)
        n_true = lbl2.shape[1]
        rng_range = nt - 1  # reference samplers draw over [0, range]
        if mode == 0:
            neg = jax.random.randint(key, (bsz, n_neg), 0, rng_range + 1)
            p_of = lambda c: jnp.full(c.shape, 1.0 / (rng_range + 1),
                                      jnp.float32)
        elif mode == 1:
            u = jax.random.uniform(key, (bsz, n_neg))
            log_range = jnp.log(float(rng_range + 2))
            neg = jnp.clip(jnp.exp(u * log_range).astype(jnp.int32) - 1,
                           0, rng_range)
            p_of = lambda c: (jnp.log((c.astype(jnp.float32) + 2.0)
                                      / (c.astype(jnp.float32) + 1.0))
                              / log_range)
        else:
            neg = jax.random.categorical(
                key, jnp.log(jnp.maximum(probs, 1e-30))[None, :],
                shape=(bsz, n_neg))
            p_of = lambda c: jnp.take(probs, c)
        samples = jnp.concatenate([lbl2, neg.astype(jnp.int32)], axis=1)
        logits = jnp.einsum("bd,bsd->bs", x, jnp.take(w, samples, axis=0))
        if b is not None:
            logits = logits + jnp.take(b.reshape(-1), samples, axis=0)
        o = jax.nn.sigmoid(logits)
        pb = p_of(samples) * n_neg
        is_true = jnp.arange(samples.shape[1])[None, :] < n_true
        cost = jnp.where(is_true, -jnp.log(o / (o + pb)),
                         -jnp.log(pb / (o + pb)))
        row = cost.sum(axis=1)
        if sw is not None:
            row = row * sw.reshape(-1)
        return row[:, None]

    return _nce(input, unwrap(label), weight,
                None if bias is None else unwrap(bias),
                None if sample_weight is None else unwrap(sample_weight),
                probs, kd)
