"""paddle_tpu.nn — layers, functional ops, initializers, clipping.

Parity: python/paddle/nn/__init__.py surface of the reference.
"""
from . import functional  # noqa: F401
from . import utils  # noqa: F401
from . import initializer  # noqa: F401
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
from .functional_attention import scaled_dot_product_attention  # noqa: F401
from .layer import Layer, LayerList, Parameter, ParameterList, Sequential  # noqa: F401
from .layers.activation import *  # noqa: F401,F403
from .layers.common import *  # noqa: F401,F403
from .layers.conv import *  # noqa: F401,F403
from .layers.loss import *  # noqa: F401,F403
from .layers.norm import *  # noqa: F401,F403
from .layers.pooling import *  # noqa: F401,F403
from .layers.rnn import *  # noqa: F401,F403
from .layers.transformer import *  # noqa: F401,F403
from .layers.extras import (  # noqa: F401
    Bilinear,
    LayerDict,
    MaxUnPool2D,
    PairwiseDistance,
    Unfold,
)
from .decode import BeamSearchDecoder, dynamic_decode  # noqa: F401
from .param_attr import ParamAttr  # noqa: F401

initializer.set_global_initializer = lambda *a, **k: None  # parity stub

# reference-name aliases
from .layers.activation import SiLU as Silu  # noqa: E402,F401
from .layers.rnn import _RNNCellBase as RNNCellBase  # noqa: E402,F401
from .layers import loss  # noqa: E402,F401
