"""GPT decoder family — the flagship pretraining model (BASELINE config #4).

Parity: the reference trains GPT through PaddleNLP's gpt modeling on top of
fleet meta-parallel layers (/root/reference/python/paddle/distributed/fleet/
meta_parallel/parallel_layers/mp_layers.py) and the fused attention CUDA op
(/root/reference/paddle/fluid/operators/fused/fused_attention_op.cu).

TPU-native design:
- weights carry ``partition_spec`` annotations (vocab/column dims on 'mp');
  under jit GSPMD inserts exactly the collectives the reference codes by
  hand (c_identity / c_allreduce_sum around sharded matmuls).
- attention runs through nn.functional_attention which dispatches to the
  Pallas flash kernel on TPU (ops/pallas/flash_attention.py).
- the loss head is ParallelCrossEntropy (vocab-sharded softmax-CE, parity
  with c_softmax_with_cross_entropy_op.cu).
- everything is static-shape and jit-friendly: one jitted train step covers
  dp/mp/fsdp; the pipeline schedule lives in distributed.meta_parallel.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from ..distributed.meta_parallel.mp_layers import (
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from ..distributed.spmd import P
from ..nn import functional as F
from ..nn.functional_attention import scaled_dot_product_attention
from ..nn.layer import Layer, LayerList
from ..nn.layers.common import Dropout, Embedding
from ..nn.layers.norm import LayerNorm
from ..ops import manipulation as manip
from ..ops import creation

__all__ = [
    "GPTConfig",
    "GPTModel",
    "GPTForPretraining",
    "GPTPretrainingCriterion",
    "GPTEmbeddings",
    "GPTDecoderLayer",
    "gpt_config",
    "GPT_CONFIGS",
]


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: Optional[int] = None  # default 4*hidden
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    layer_norm_epsilon: float = 1e-5
    use_recompute: bool = False
    # remat policy (PaddleNLP recompute_granularity analog): 'full' remats
    # the whole block (min memory, ~4/3x fwd flops); 'selective' keeps
    # weight-matmul outputs (jax dots_with_no_batch_dims_saveable) AND the
    # flash-attention forward outputs (checkpoint_name-tagged o/lse) so only
    # cheap elementwise work reruns; 'core_attn' keeps ONLY the flash
    # outputs (reference PaddleNLP core_attn granularity) — near-'full'
    # memory but the expensive attention kernel never re-runs in backward
    recompute_granularity: str = "full"
    # remat every k-th block only (reference PipelineLayer recompute_interval):
    # 0 = off, 1 = every block, 2 = blocks 0,2,4,... — trades memory for
    # fewer recompute flops when the model almost fits without remat
    recompute_interval: int = 1
    # MoE (ERNIE-MoE analog, BASELINE #5): 0 experts = dense model
    num_experts: int = 0
    moe_every: int = 2  # every moe_every-th block uses an MoE FFN
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_loss_weight: float = 0.01
    # long-context sequence parallelism over the 'sp' mesh axis (explicit
    # shard_map mode): "none" | "ring" | "ulysses"
    sequence_parallel: str = "none"
    # FFN activation: "gelu" (GPT-3) or "swiglu" (llama family) — swiglu
    # runs the fused Pallas gate kernel (ops/pallas/swiglu.py) on TPU
    activation: str = "gelu"
    # positions: "learned" (GPT-3 wpe) or "rope" (llama family) — rope runs
    # the fused Pallas rotary kernel (ops/pallas/rope.py) on TPU
    position_embedding: str = "learned"
    rope_base: float = 10000.0

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


# GPT-3 paper table 2.1 sizes (vocab padded to a 128-multiple so the 'mp'
# axis always divides it)
GPT_CONFIGS = {
    "gpt2-small": dict(vocab_size=50304, hidden_size=768, num_layers=12,
                       num_attention_heads=12, max_position_embeddings=1024),
    "gpt3-125m": dict(vocab_size=50304, hidden_size=768, num_layers=12,
                      num_attention_heads=12, max_position_embeddings=2048),
    "gpt3-350m": dict(vocab_size=50304, hidden_size=1024, num_layers=24,
                      num_attention_heads=16, max_position_embeddings=2048),
    "gpt3-760m": dict(vocab_size=50304, hidden_size=1536, num_layers=24,
                      num_attention_heads=16, max_position_embeddings=2048),
    # 1.3B: 24 layers x 2048 hidden x 16 heads (head_dim 128 = MXU lane width)
    "gpt3-1.3b": dict(vocab_size=50304, hidden_size=2048, num_layers=24,
                      num_attention_heads=16, max_position_embeddings=2048),
    "gpt3-2.7b": dict(vocab_size=50304, hidden_size=2560, num_layers=32,
                      num_attention_heads=32, max_position_embeddings=2048),
    "gpt3-6.7b": dict(vocab_size=50304, hidden_size=4096, num_layers=32,
                      num_attention_heads=32, max_position_embeddings=2048),
    # ERNIE-3.0-style MoE (BASELINE #5): dense backbone + 64 experts every
    # other layer, expert-parallel over the 'ep' mesh axis
    "ernie-moe-base": dict(vocab_size=50304, hidden_size=768, num_layers=12,
                           num_attention_heads=12, max_position_embeddings=2048,
                           num_experts=64, moe_every=2),
    # llama family: rope positions + fused-swiglu FFN (the Pallas kernels
    # ops/pallas/{rope,swiglu}.py are the production path on TPU)
    "llama-7b": dict(vocab_size=32000, hidden_size=4096, num_layers=32,
                     num_attention_heads=32, max_position_embeddings=4096,
                     intermediate_size=11008, activation="swiglu",
                     position_embedding="rope", hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0),
    "llama-1b": dict(vocab_size=32000, hidden_size=2048, num_layers=22,
                     num_attention_heads=16, max_position_embeddings=4096,
                     intermediate_size=5632, activation="swiglu",
                     position_embedding="rope", hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0),
}


def gpt_config(name: str, **overrides) -> GPTConfig:
    cfg = dict(GPT_CONFIGS[name])
    cfg.update(overrides)
    return GPTConfig(**cfg)


def _constrain_heads(x):
    """Hint GSPMD to keep the head dim on 'mp' for [B, H, T, D] tensors."""
    from ..distributed.env import get_mesh
    from ..distributed.meta_parallel.mp_layers import mp_axis_bound
    from ..distributed.spmd import with_sharding_constraint

    mesh = get_mesh()
    if mesh is None or "mp" not in mesh.shape or int(mesh.shape["mp"]) == 1:
        return x
    if mp_axis_bound():
        # explicit shard_map region: tensors are already the local head
        # shard — GSPMD constraints don't apply to manual axes
        return x
    return with_sharding_constraint(x, P(None, "mp", None, None))


class GPTAttention(Layer):
    """Causal self-attention with TP head sharding.

    qkv projection is column-parallel (heads sharded over 'mp'), the output
    projection row-parallel — the Megatron split the reference implements
    via ColumnParallelLinear/RowParallelLinear (mp_layers.py:97,170).
    """

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.num_heads = config.num_attention_heads
        self.head_dim = config.head_dim
        self.dropout_p = config.attention_dropout_prob
        self.sequence_parallel = config.sequence_parallel
        self.use_rope = config.position_embedding == "rope"
        self.rope_base = config.rope_base
        self._rope_cache = None
        h = config.hidden_size
        self.qkv_proj = ColumnParallelLinear(h, 3 * h, gather_output=False)
        self.out_proj = RowParallelLinear(h, h, input_is_parallel=True)

    def _apply_rope(self, q, k, offset: int = 0):
        """Fused rotary embedding on q/k (ops/pallas/rope.py on TPU)."""
        from ..ops._primitive import primitive
        from ..ops.pallas.rope import build_rope_cache, rope

        t = q.shape[2]
        need = offset + t
        if self._rope_cache is None or self._rope_cache[0].shape[0] < need:
            # grow geometrically: rebuilding to exactly `need` would
            # recompute the table every autoregressive decode step
            self._rope_cache = build_rope_cache(
                max(need * 2, 64), self.head_dim, self.rope_base)
        cos, sin = self._rope_cache
        cos, sin = cos[offset:need], sin[offset:need]

        @primitive
        def _rope(q, k):
            return rope(q, cos, sin), rope(k, cos, sin)

        return _rope(q, k)

    def _local_heads(self):
        """Head count on this shard: under an explicit 'mp' shard_map region
        the qkv projection produced the local head slice (Megatron head
        parallelism), so reshapes must use num_heads / mp."""
        from ..distributed.meta_parallel.mp_layers import MP_AXIS, mp_axis_bound

        if mp_axis_bound():
            import jax

            return self.num_heads // jax.lax.axis_size(MP_AXIS)
        return self.num_heads

    def _finish(self, out, b, t):
        """Shared epilogue: [B, H, T, D] -> out_proj([B, T, H*D])."""
        out = manip.transpose(out, [0, 2, 1, 3])
        out = manip.reshape(out, [b, t, -1])
        return self.out_proj(out)

    def forward(self, x):
        b, t = x.shape[0], x.shape[1]
        qkv = self.qkv_proj(x)  # [B, T, 3H] ([B, T, 3H/mp] per explicit shard)
        # head-major interleaved qkv layout [nh, 3, hd]: a contiguous 1/mp
        # column slice is a whole-head slice, so the Megatron explicit path
        # and the GSPMD path read the same parameterization
        qkv = manip.reshape(qkv, [b, t, self._local_heads(), 3, self.head_dim])
        qkv = manip.transpose(qkv, [3, 0, 2, 1, 4])  # [3, B, H, T, D]
        q, k, v = qkv[0], qkv[1], qkv[2]
        # incremental-decoding KV cache (models/generation.py owns the
        # lifecycle; None = normal training/eval forward)
        cache = getattr(self, "_gen_cache", None)
        if cache is not None and cache.get("mode") == "paged":
            # block-paged KV pool (serving continuous batching, ISSUE 11):
            # K/V live in a [n_pages, H, page_size, D] pool shared by every
            # slot; each slot reads/writes through a padded page table
            # [B, max_pages]. Writes are per-position scatters into
            # (table[pos // ps], pos % ps); reads gather the table's pages
            # back into position order and mask past the live length —
            # static shapes throughout, so the one-jitted-decode-step /
            # bounded-compile-cache invariants of the slot cache survive.
            if self.use_rope:
                raise NotImplementedError(
                    "paged KV cache with rope positions is not wired "
                    "(learned-position GPT configs only)")
            from ..ops._primitive import primitive
            from ..profiler.scope import scope

            scale = 1.0 / (self.head_dim ** 0.5)
            ps = int(cache["page_size"])
            # r20 engine flag: "xla" = gather path (default, and the
            # bit-comparison oracle); "pallas" = paged flash-decode kernel
            attn_impl = str(cache.get("attn_impl", "xla"))
            # int8 KV layout (ISSUE 18): per-token f32 absmax scales ride
            # alongside the pool — quant on scatter-in, dequant on gather
            quant = cache.get("k_scale") is not None

            @primitive
            def _paged_attn(q, k, v, poolk, poolv, pages, pos, *scales):
                import jax
                import jax.numpy as jnp

                bb, hh, tt, dd = q.shape
                mp = pages.shape[1]
                cap = mp * ps
                pos = pos.astype(jnp.int32).reshape(-1)  # [B]
                # absolute write position of query row r in slot b
                wpos = pos[:, None] + jnp.arange(tt, dtype=jnp.int32)[None, :]
                # positions past the slot's page capacity (chunk padding)
                # are redirected to the reserved trash page 0 — they are
                # never gathered unmasked
                pidx = jnp.clip(wpos // ps, 0, mp - 1)
                pg = jnp.take_along_axis(pages, pidx, axis=1)
                pg = jnp.where(wpos < cap, pg, 0)
                off = wpos % ps
                kw = k.transpose(0, 2, 1, 3).reshape(bb * tt, hh, dd)
                vw = v.transpose(0, 2, 1, 3).reshape(bb * tt, hh, dd)
                if scales:
                    sk_pool, sv_pool = scales
                    # one f32 absmax scale per written TOKEN (shared
                    # across heads and head_dim — [L, n_pages, ps] rides
                    # beside the pool); floor keeps all-zero rows finite
                    ks = jnp.maximum(
                        jnp.max(jnp.abs(kw), axis=(1, 2)) / 127.0, 1e-8)
                    vs = jnp.maximum(
                        jnp.max(jnp.abs(vw), axis=(1, 2)) / 127.0, 1e-8)
                    kq = jnp.clip(jnp.round(kw / ks[:, None, None]),
                                  -127, 127)
                    vq = jnp.clip(jnp.round(vw / vs[:, None, None]),
                                  -127, 127)
                    poolk = poolk.at[
                        pg.reshape(-1), :, off.reshape(-1), :].set(
                        kq.astype(poolk.dtype))
                    poolv = poolv.at[
                        pg.reshape(-1), :, off.reshape(-1), :].set(
                        vq.astype(poolv.dtype))
                    sk_pool = sk_pool.at[
                        pg.reshape(-1), off.reshape(-1)].set(
                        ks.astype(sk_pool.dtype))
                    sv_pool = sv_pool.at[
                        pg.reshape(-1), off.reshape(-1)].set(
                        vs.astype(sv_pool.dtype))
                    scales = (sk_pool, sv_pool)
                else:
                    poolk = poolk.at[
                        pg.reshape(-1), :, off.reshape(-1), :].set(
                        kw.astype(poolk.dtype))
                    poolv = poolv.at[
                        pg.reshape(-1), :, off.reshape(-1), :].set(
                        vw.astype(poolv.dtype))
                if attn_impl == "pallas":
                    # paged flash-decode kernel (r20): reads the pool
                    # through the page table block by block — the gathered
                    # [B, H, cap, D] tensor below never materializes
                    if scales:
                        from ..ops.pallas.paged_attention import (
                            paged_flash_attention_int8,
                        )

                        out = paged_flash_attention_int8(
                            q, poolk, poolv, scales[0], scales[1],
                            pages, pos, page_size=ps, sm_scale=scale)
                    else:
                        from ..ops.pallas.paged_attention import (
                            paged_flash_attention,
                        )

                        out = paged_flash_attention(
                            q, poolk, poolv, pages, pos, page_size=ps,
                            sm_scale=scale)
                    return (out, poolk, poolv) + tuple(scales)
                # gather the table's pages back into position order: the
                # j axis below IS absolute sequence position, so the mask
                # and reductions match the contiguous slot buffer bit for
                # bit (trailing pad is where()-masked to exactly -1e30)
                gk = poolk[pages].transpose(0, 2, 1, 3, 4).reshape(
                    bb, hh, cap, dd)
                gv = poolv[pages].transpose(0, 2, 1, 3, 4).reshape(
                    bb, hh, cap, dd)
                gk = gk.astype(q.dtype)
                gv = gv.astype(q.dtype)
                if scales:
                    # dequant on gather: the int8 page entries scale back
                    # by their per-token factors — the convert is fed by
                    # the GATHER (pool-sized int8 stays the resident form;
                    # no dequantized full-pool copy materializes)
                    gsk = scales[0][pages].reshape(bb, 1, cap, 1)
                    gsv = scales[1][pages].reshape(bb, 1, cap, 1)
                    gk = gk * gsk.astype(q.dtype)
                    gv = gv * gsv.astype(q.dtype)
                scores = jnp.einsum("bhtd,bhsd->bhts", q, gk) * scale
                j = jnp.arange(cap)[None, None, None, :]
                mask = j <= wpos[:, None, :, None]
                scores = jnp.where(mask, scores,
                                   jnp.asarray(-1e30, scores.dtype))
                probs = jax.nn.softmax(
                    scores.astype(jnp.float32), axis=-1).astype(q.dtype)
                out = jnp.einsum("bhts,bhsd->bhtd", probs, gv)
                return (out, poolk, poolv) + tuple(scales)

            # named region (r6 scope): the perf doctor ranks the gather-
            # based attention row as serving.paged_attn
            extra = (cache["k_scale"], cache["v_scale"]) if quant else ()
            with scope("serving.paged_attn"):
                res = _paged_attn(
                    q, k, v, cache["k"], cache["v"], cache["pages"],
                    cache["pos"], *extra)
            out, new_k, new_v = res[0], res[1], res[2]
            self._gen_cache = {"mode": "paged", "k": new_k, "v": new_v,
                               "pages": cache["pages"], "pos": cache["pos"],
                               "page_size": ps, "attn_impl": attn_impl}
            if quant:
                self._gen_cache["k_scale"] = res[3]
                self._gen_cache["v_scale"] = res[4]
            return self._finish(out, b, t)
        if cache is not None and cache.get("mode") == "buffer":
            # fixed-capacity export mode (inference.save_for_generation):
            # K/V live in a [B, H, S, D] buffer written at `pos` via
            # dynamic_update_slice, so the whole decode step jits with
            # static shapes and ships as a StableHLO artifact
            # (AnalysisPredictor KV-cache decoding role)
            if self.use_rope:
                raise NotImplementedError(
                    "buffer-mode KV cache with rope positions is not wired "
                    "(learned-position GPT configs only)")
            from ..ops._primitive import primitive

            scale = 1.0 / (self.head_dim ** 0.5)

            @primitive
            def _buffer_attn(q, k, v, bufk, bufv, pos):
                import jax
                import jax.numpy as jnp
                from jax import lax

                pos = pos.astype(jnp.int32)
                z = jnp.zeros((), jnp.int32)
                if pos.ndim >= 1 and pos.shape[0] > 1:
                    # per-ROW write positions [B] (serving continuous
                    # batching: each slot decodes at its own offset); vmap
                    # of dynamic_update_slice lowers to a batched scatter
                    pos = pos.reshape(-1)

                    def _write(buf, new, p):
                        return lax.dynamic_update_slice(
                            buf, new.astype(buf.dtype), (z, p, z))

                    bufk = jax.vmap(_write)(bufk, k, pos)
                    bufv = jax.vmap(_write)(bufv, v, pos)
                    posb = pos[:, None, None, None]  # [B,1,1,1]
                else:
                    pos = pos.reshape(())
                    bufk = lax.dynamic_update_slice(
                        bufk, k.astype(bufk.dtype), (z, z, pos, z))
                    bufv = lax.dynamic_update_slice(
                        bufv, v.astype(bufv.dtype), (z, z, pos, z))
                    posb = pos
                s = bufk.shape[2]
                tq = q.shape[2]
                scores = jnp.einsum("bhtd,bhsd->bhts", q, bufk) * scale
                j = jnp.arange(s)[None, None, None, :]
                r = jnp.arange(tq)[None, None, :, None]
                mask = j <= (posb + r)
                scores = jnp.where(mask, scores, jnp.asarray(-1e30, scores.dtype))
                probs = jax.nn.softmax(
                    scores.astype(jnp.float32), axis=-1).astype(q.dtype)
                out = jnp.einsum("bhts,bhsd->bhtd", probs, bufv)
                return out, bufk, bufv

            out, new_k, new_v = _buffer_attn(q, k, v, cache["k"], cache["v"],
                                             cache["pos"])
            self._gen_cache = {"mode": "buffer", "k": new_k, "v": new_v,
                               "pos": cache["pos"]}
            return self._finish(out, b, t)
        if cache is not None:
            offset = cache["k"].shape[2] if cache.get("k") is not None else 0
            if self.use_rope:
                q, k = self._apply_rope(q, k, offset)
            if cache.get("k") is not None:
                k = manip.concat([cache["k"], k], axis=2)
                v = manip.concat([cache["v"], v], axis=2)
            self._gen_cache = {"k": k, "v": v}
            # prefill (q spans the whole prompt) needs the causal mask;
            # single-token steps attend the full cache
            causal = q.shape[2] == k.shape[2]
            out, _ = scaled_dot_product_attention(q, k, v, is_causal=causal)
            return self._finish(out, b, t)
        if self.use_rope:
            q, k = self._apply_rope(q, k)
        if self.sequence_parallel != "none":
            from ..distributed.meta_parallel.sequence_parallel import (
                ring_attention,
                sp_axis_bound,
                ulysses_attention,
            )

            if sp_axis_bound():
                # x is this shard's sequence slice [B, T/n, H]; attention
                # spans the full sequence via ring ppermute / Ulysses a2a
                if self.use_rope:
                    raise ValueError(
                        "position_embedding='rope' with sequence_parallel "
                        "needs per-shard position offsets; not wired yet — "
                        "use learned positions for sp runs")
                if self.training and self.dropout_p > 0.0:
                    raise ValueError(
                        "attention_dropout_prob > 0 is not supported with "
                        "sequence_parallel (ring/Ulysses attention has no "
                        "weight-dropout path); set attention_dropout_prob=0 "
                        "and use hidden_dropout_prob instead")
                fn = ring_attention if self.sequence_parallel == "ring" else ulysses_attention
                out = fn(q, k, v, causal=True)
                return self._finish(out, b, t)
        q = _constrain_heads(q)
        k = _constrain_heads(k)
        v = _constrain_heads(v)
        out, _ = scaled_dot_product_attention(
            q, k, v, is_causal=True,
            dropout_p=self.dropout_p if self.training else 0.0,
        )
        return self._finish(out, b, t)


class GPTMLP(Layer):
    """Dense FFN: gelu (GPT-3) or fused-swiglu gate (llama family,
    ops/pallas/swiglu.py on TPU)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.activation = config.activation
        h, f = config.hidden_size, config.intermediate_size
        if self.activation == "swiglu":
            self.gate_proj = ColumnParallelLinear(h, f, gather_output=False,
                                                  has_bias=False)
            self.up_proj = ColumnParallelLinear(h, f, gather_output=False,
                                                has_bias=False)
        else:
            self.fc_in = ColumnParallelLinear(h, f, gather_output=False)
        self.fc_out = RowParallelLinear(f, h, input_is_parallel=True)

    def forward(self, x):
        if self.activation == "swiglu":
            from ..distributed.meta_parallel.mp_layers import (
                _c_identity,
                mp_axis_bound,
            )
            from ..ops._primitive import primitive
            from ..ops.pallas.swiglu import swiglu, swiglu_reference

            explicit_mp = mp_axis_bound()
            from ..distributed.env import get_mesh

            mesh = get_mesh()
            gspmd_mp = (not explicit_mp and mesh is not None
                        and int(mesh.shape.get("mp", 1)) > 1)
            if explicit_mp:
                x = _c_identity(x)  # column-parallel input identity/psum-bwd

            @primitive
            def _glu(x, wg, wu):
                lead = x.shape[:-1]
                x2 = x.reshape(-1, x.shape[-1])
                if gspmd_mp:
                    # GSPMD shards these matmuls; the pallas path would
                    # force replication — use the fusable jnp form
                    out = swiglu_reference(x2, wg, wu)
                else:
                    out = swiglu(x2, wg, wu)
                return out.reshape(*lead, wg.shape[1])

            h = _glu(x, self.gate_proj.weight, self.up_proj.weight)
            if gspmd_mp:
                from ..distributed.spmd import P, with_sharding_constraint

                h = with_sharding_constraint(
                    h, P(*([None] * (len(x.shape) - 1) + ["mp"])))
            return self.fc_out(h)
        return self.fc_out(F.gelu(self.fc_in(x), approximate=True))


class GPTDecoderLayer(Layer):
    """Pre-LN decoder block: x + attn(ln1(x)); x + mlp(ln2(x)).

    With ``config.num_experts > 0``, every ``moe_every``-th block swaps the
    dense MLP for an expert-parallel :class:`MoELayer` (all2all over 'ep').
    """

    def __init__(self, config: GPTConfig, layer_idx: int = 0):
        super().__init__()
        self.ln_1 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.ln_2 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.is_moe = (config.num_experts > 0
                       and (layer_idx + 1) % max(config.moe_every, 1) == 0)
        if self.is_moe:
            from ..distributed.meta_parallel.moe_layer import MoELayer

            self.mlp = MoELayer(
                config.hidden_size, config.intermediate_size, config.num_experts,
                top_k=config.moe_top_k, capacity_factor=config.moe_capacity_factor)
        else:
            self.mlp = GPTMLP(config)
        self.dropout1 = Dropout(config.hidden_dropout_prob, mode="upscale_in_train")
        self.dropout2 = Dropout(config.hidden_dropout_prob, mode="upscale_in_train")
        # remat of an MoE block would trap l_aux inside the checkpoint trace,
        # so MoE blocks always run un-rematerialized
        # interval semantics follow the reference PipelineLayer: 0 disables
        # recompute entirely, k >= 1 remats blocks 0, k, 2k, ...
        interval = int(getattr(config, "recompute_interval", 1))
        self._use_recompute = (config.use_recompute and not self.is_moe
                               and interval >= 1
                               and layer_idx % interval == 0)
        self._recompute_granularity = config.recompute_granularity

    def _block(self, x):
        # profiler scopes (r6): pure HLO-metadata names inside a trace —
        # they compile away, but the perf doctor's scope-attribution table
        # (observability/perf.py) slices roofline cost by them, so the
        # attention and FFN matmuls are nameable Pallas targets
        from ..profiler.scope import scope

        with scope("gpt.attn"):
            a = self.attn(self.ln_1(x))
        x = x + self.dropout1(a)
        with scope("gpt.mlp"):
            m = self.mlp(self.ln_2(x))
        x = x + self.dropout2(m)
        return x

    def forward(self, x):
        if self._use_recompute and self.training:
            # recompute_optimizer parity: remat the block so XLA recomputes
            # activations during backward; 'selective' granularity saves
            # weight-matmul outputs so only cheap elementwise work reruns
            import jax

            from ..ops._primitive import primitive

            from ..ops.pallas.flash_attention import granularity_policy

            policy = granularity_policy(self._recompute_granularity)

            @primitive
            def _remat(h):
                return jax.checkpoint(self._raw_block, policy=policy)(h)

            return _remat(x)
        return self._block(x)

    def _raw_block(self, arr):
        from ..tensor import Tensor

        out = self._block(Tensor(arr))
        return out._data


class GPTEmbeddings(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.word_embeddings = VocabParallelEmbedding(config.vocab_size, config.hidden_size)
        # rope configs (llama family) carry positions in attention, not here
        self.use_wpe = config.position_embedding == "learned"
        if self.use_wpe:
            self.position_embeddings = Embedding(config.max_position_embeddings, config.hidden_size)
        self.dropout = Dropout(config.hidden_dropout_prob, mode="upscale_in_train")
        self.sequence_parallel = config.sequence_parallel

    def forward(self, input_ids, position_ids=None):
        from ..profiler.scope import scope

        with scope("gpt.embed"):
            return self._embed(input_ids, position_ids)

    def _embed(self, input_ids, position_ids=None):
        if not self.use_wpe:
            return self.dropout(self.word_embeddings(input_ids))
        t = input_ids.shape[-1]
        if position_ids is None:
            if self.sequence_parallel != "none":
                from ..distributed.meta_parallel.sequence_parallel import (
                    SP_AXIS,
                    sp_axis_bound,
                )

                if sp_axis_bound():
                    # input_ids is this shard's sequence slice: positions are
                    # GLOBAL (rank * t_loc + local offset)
                    from ..ops._primitive import primitive

                    @primitive(nondiff=True)
                    def _global_pos(ids):
                        import jax.numpy as jnp
                        from jax import lax

                        base = jnp.arange(t, dtype=jnp.int32) + lax.axis_index(SP_AXIS) * t
                        return jnp.broadcast_to(base, ids.shape)

                    position_ids = _global_pos(input_ids)
            if position_ids is None:
                position_ids = creation.arange(0, t, dtype="int64")
        emb = self.word_embeddings(input_ids) + self.position_embeddings(position_ids)
        return self.dropout(emb)


QKV_LAYOUT_VERSION = 2  # 2 = head-major interleaved [nh, 3, hd] qkv columns


def _migrate_qkv_layout(model: Layer, state_dict, tag_key: str):
    """Permute legacy qkv weights ([3, nh, hd] column layout) to the
    head-major interleaved layout the model now computes with.

    Only dicts that carry an *explicit* old ``qkv_layout`` tag (< current
    version) are auto-migrated. An **untagged** dict is ambiguous — it may
    predate the layout change (column layout) or merely predate the tag
    (already head-major) — so it is loaded as-is with a loud warning; pass
    ``set_flags({"FLAGS_gpt_qkv_assume_legacy": True})`` to opt in to the
    column→head-major permutation for genuinely old checkpoints.
    """
    import warnings

    import numpy as np

    from ..framework.flags import flag

    tag = state_dict.get(tag_key)
    if tag is None:
        if not bool(flag("FLAGS_gpt_qkv_assume_legacy")):
            warnings.warn(
                "state dict has no '%s' version tag; assuming the current "
                "head-major qkv layout and NOT migrating. If this checkpoint "
                "was saved with the pre-head-major column layout, set "
                "FLAGS_gpt_qkv_assume_legacy=True before loading." % tag_key,
                stacklevel=3)
            return state_dict
        warnings.warn(
            "FLAGS_gpt_qkv_assume_legacy=True: migrating untagged state dict "
            "from the legacy [3, nh, hd] column layout to head-major.",
            stacklevel=3)
    elif int(np.asarray(
            tag._data if hasattr(tag, "_data") else tag)) >= QKV_LAYOUT_VERSION:
        return state_dict
    out = dict(state_dict)
    # stamp the migrated dict so the model's version buffer isn't overwritten
    # with the stale tag (a re-save would otherwise double-permute on load)
    out[tag_key] = np.asarray(QKV_LAYOUT_VERSION, np.int32)
    for name, sub in model.named_sublayers(include_self=True):
        if not isinstance(sub, GPTAttention):
            continue
        hd = sub.head_dim
        for suffix, is_bias in ((".qkv_proj.weight", False), (".qkv_proj.bias", True)):
            key = (name + suffix) if name else suffix[1:]
            if key not in out:
                continue
            w = out[key]
            arr = np.asarray(w._data if hasattr(w, "_data") else w)
            cols = arr.shape[-1]
            nh = cols // (3 * hd)
            if is_bias:
                arr = arr.reshape(3, nh, hd).transpose(1, 0, 2).reshape(cols)
            else:
                arr = (arr.reshape(arr.shape[0], 3, nh, hd)
                       .transpose(0, 2, 1, 3).reshape(arr.shape[0], cols))
            out[key] = arr
    return out


class GPTModel(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.embeddings = GPTEmbeddings(config)
        self.h = LayerList([GPTDecoderLayer(config, i) for i in range(config.num_layers)])
        self.ln_f = LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        # layout/version tag saved with every state dict so old-layout qkv
        # checkpoints are detected and permuted on load (see _migrate_qkv_layout)
        import jax.numpy as jnp
        from ..tensor import Tensor as _T

        self.register_buffer("qkv_layout", _T(jnp.asarray(QKV_LAYOUT_VERSION, jnp.int32)))

    def set_state_dict(self, state_dict, use_structured_name: bool = True):
        state_dict = _migrate_qkv_layout(self, state_dict, "qkv_layout")
        return super().set_state_dict(state_dict, use_structured_name)

    load_dict = set_state_dict

    def forward(self, input_ids, position_ids=None):
        x = self.embeddings(input_ids, position_ids)
        for block in self.h:
            x = block(x)
        return self.ln_f(x)

    def set_recompute(self, enabled: bool = True, *,
                      granularity: str = "full", interval: int = 1):
        """Re-flag per-block rematerialization after construction — the
        application hook for a planner-emitted
        :class:`~paddle_tpu.analysis.plan.RematPolicy` (same semantics as
        constructing with ``use_recompute``/``recompute_granularity``/
        ``recompute_interval``; MoE blocks stay un-rematerialized)."""
        interval = int(interval)
        self.config.use_recompute = bool(enabled)
        self.config.recompute_granularity = granularity
        self.config.recompute_interval = interval
        for i, block in enumerate(self.h):
            block._use_recompute = (bool(enabled) and not block.is_moe
                                    and interval >= 1
                                    and i % interval == 0)
            block._recompute_granularity = granularity

    def aux_loss(self):
        """Sum of MoE load-balancing losses from the latest forward (same
        trace), pre-scaled by ``moe_aux_loss_weight``; 0.0 for dense models."""
        total = None
        for block in self.h:
            if getattr(block, "is_moe", False) and block.mlp.l_aux is not None:
                total = block.mlp.l_aux if total is None else total + block.mlp.l_aux
        if total is None:
            return 0.0
        return total * self.config.moe_aux_loss_weight


class GPTForPretraining(Layer):
    """LM head ties the vocab-parallel embedding weight (logits = x @ W^T)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.gpt = GPTModel(config)

    def set_state_dict(self, state_dict, use_structured_name: bool = True):
        state_dict = _migrate_qkv_layout(self, state_dict, "gpt.qkv_layout")
        return Layer.set_state_dict(self, state_dict, use_structured_name)

    load_dict = set_state_dict

    def set_recompute(self, enabled: bool = True, *,
                      granularity: str = "full", interval: int = 1):
        self.gpt.set_recompute(enabled, granularity=granularity,
                               interval=interval)

    @property
    def config(self):
        return self.gpt.config

    def forward(self, input_ids, position_ids=None):
        x = self.gpt(input_ids, position_ids)
        w = self.gpt.embeddings.word_embeddings.weight  # [V, H], vocab on 'mp'
        from ..ops._primitive import primitive
        from ..profiler.scope import scope
        import jax.numpy as jnp

        @primitive
        def _logits(h, w):
            return jnp.matmul(h, w.T)

        with scope("gpt.lm_head"):
            return _logits(x, w)

    def aux_loss(self):
        return self.gpt.aux_loss()


class GPTPretrainingCriterion(Layer):
    """Shifted-LM loss over the vocab-sharded logits."""

    def __init__(self, config: Optional[GPTConfig] = None):
        super().__init__()
        self.ce = ParallelCrossEntropy(ignore_index=-100)

    def forward(self, logits, labels):
        from ..profiler.scope import scope

        # logits [B, T, V]; labels [B, T] — shift happens in data prep
        with scope("gpt.loss"):
            loss = self.ce(logits, labels)  # [B, T, 1]
            return loss.mean()
