"""Autoregressive generation for the GPT family with incremental KV cache.

Parity role: the reference serves generation through its inference stack
(AnalysisPredictor over exported programs plus PaddleNLP's generate);
here generation is first-class on the flagship model: prefill once, then
single-token steps against per-layer K/V caches (the standard
incremental-decoding decomposition — each step is O(T) attention instead of
re-running the O(T^2) full forward).

Sampling: greedy, temperature, top-k and top-p (nucleus), driven by the
framework's seeded PRNG so paddle.seed reproduces generations.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd.tape import no_grad
from ..ops._primitive import unwrap, wrap
from ..random import split_key
from ..tensor import Tensor

__all__ = ["generate"]


def _attn_layers(model):
    from .gpt import GPTAttention

    return [m for m in model.sublayers() if isinstance(m, GPTAttention)]


def _sample(logits, temperature, top_k, top_p):
    """logits (B, V) -> token ids (B,)."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k is not None and top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, -1e9, logits)
    if top_p is not None and top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set with cumulative prob >= top_p; find its cutoff logit
        keep_n = jnp.sum(cum - probs < top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, keep_n - 1, axis=-1)
        logits = jnp.where(logits < cutoff, -1e9, logits)
    return jax.random.categorical(split_key(), logits, axis=-1)


def generate(model, input_ids, max_new_tokens=32, eos_token_id=None,
             temperature: float = 0.0, top_k: Optional[int] = None,
             top_p: Optional[float] = None, use_cache: bool = True):
    """Generate continuations for a batch of prompts.

    model: GPTForPretraining (or GPTModel + tied head via it).
    input_ids: (B, T0) int tensor/array. Returns (B, T0 + n) int64 Tensor
    (n <= max_new_tokens; shorter only when every row hit eos).
    """
    ids = unwrap(input_ids)
    if isinstance(ids, Tensor):
        ids = ids._data
    ids = jnp.asarray(np.asarray(ids)).astype(jnp.int32)
    b, t0 = ids.shape
    was_training = model.training
    model.eval()
    attns = _attn_layers(model) if use_cache else []

    def fwd(tokens, position_ids=None):
        out = model(wrap(tokens) if not isinstance(tokens, Tensor) else tokens,
                    position_ids)
        return unwrap(out)

    try:
        with no_grad():
            if use_cache:
                for a in attns:
                    a._gen_cache = {"k": None, "v": None}
            logits = fwd(ids)  # prefill
            finished = jnp.zeros((b,), bool)
            for step in range(int(max_new_tokens)):
                nxt = _sample(logits[:, -1].astype(jnp.float32),
                              temperature, top_k, top_p).astype(jnp.int32)
                if eos_token_id is not None:
                    nxt = jnp.where(finished, eos_token_id, nxt)
                    finished = finished | (nxt == eos_token_id)
                ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
                if eos_token_id is not None and bool(finished.all()):
                    break
                if step == int(max_new_tokens) - 1:
                    break  # no need to compute logits for an unused step
                if use_cache:
                    pos = wrap(jnp.full((b, 1), ids.shape[1] - 1, jnp.int32))
                    logits = fwd(nxt[:, None], pos)
                else:
                    logits = fwd(ids)
    finally:
        for a in attns:
            if hasattr(a, "_gen_cache"):
                del a._gen_cache
        if was_training:
            model.train()
    return wrap(ids.astype(jnp.int64))
