"""Autoregressive generation for the GPT family with incremental KV cache.

Parity role: the reference serves generation through its inference stack
(AnalysisPredictor over exported programs plus PaddleNLP's generate);
here generation is first-class on the flagship model: prefill once, then
single-token steps against per-layer K/V caches (the standard
incremental-decoding decomposition — each step is O(T) attention instead of
re-running the O(T^2) full forward).

Sampling: greedy, temperature, top-k and top-p (nucleus), driven by the
framework's seeded PRNG so paddle.seed reproduces generations.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd.tape import no_grad
from ..ops._primitive import unwrap, wrap
from ..random import split_key
from ..tensor import Tensor

__all__ = ["generate", "sample_tokens", "fast_forward_key"]


def _attn_layers(model):
    from .gpt import GPTAttention

    return [m for m in model.sublayers() if isinstance(m, GPTAttention)]


def _per_row(value, default, batch, dtype):
    """Broadcast a scalar-or-(B,) sampling param to a (B,) array."""
    if value is None:
        value = default
    arr = jnp.asarray(value, dtype).reshape(-1)
    return jnp.broadcast_to(arr, (batch,))


def _is_key_batch(key, batch):
    """True when ``key`` is a per-row batch of PRNG keys (typed keys of
    shape (B,), or raw uint32 keys of shape (B, 2))."""
    if jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
        return key.ndim == 1 and key.shape[0] == batch
    return key.ndim == 2 and key.shape[0] == batch


def fast_forward_key(key, n):
    """Advance a per-request PRNG key chain by ``n`` draws.

    The serving engine's decode chain is ``key -> split(key)[0]`` once per
    emitted token (the final prefill chunk consumes the first draw, every
    decode step one more — both keep index ``[0]`` as the carried chain and
    spend index ``[1]`` on sampling). After ``n`` emitted tokens the carried
    chain state is therefore ``split`` applied ``n`` times taking ``[0]``,
    which is what this computes — the continuation-join resume point for a
    stream resurrected (or migrated) with ``n`` observed tokens, so the
    continued trajectory samples from exactly the keys the uninterrupted
    run would have drawn. Accepts typed or raw ``uint32[2]`` keys; jittable
    (``n`` is a static python int here — one program per distinct n is
    avoided by the ``fori_loop``).
    """
    n = int(n)
    if n < 0:
        raise ValueError(f"cannot fast-forward a key chain by {n} draws")
    if n == 0:
        return key
    return jax.lax.fori_loop(
        0, n, lambda _, k: jax.random.split(k)[0], key)


def sample_tokens(logits, key, temperature=0.0, top_k=None, top_p=None):
    """Batched, PRNG-key-driven sampling: logits (B, V) -> token ids (B,).

    ``temperature``/``top_k``/``top_p`` each accept a python scalar OR a
    per-row (B,) array, so one compiled program serves a batch that mixes
    greedy and sampled requests with different nucleus settings (the serving
    engine's continuous batches). Per-row semantics:

    - ``temperature <= 0`` → greedy argmax for that row (no RNG consumed by
      the caller's key for greedy-only calls when ``key is None``);
    - ``top_k <= 0`` (or ``None``) → top-k filter disabled for that row;
    - ``top_p >= 1`` (or ``None``) → nucleus filter disabled for that row.

    ``key``: a single jax PRNG key (typed or raw uint32[2]) shared by the
    batch, a per-row batch of keys (typed (B,) or raw (B, 2) — each row draws
    from its own stream, so slot outputs don't depend on who shares the
    batch), or ``None`` (pure greedy — any row with temperature > 0 would
    need randomness, so ``None`` forces argmax everywhere).

    Fully in-graph (jit/vmap-safe, shape-polymorphic over B): filters use a
    full descending sort + per-row rank thresholds instead of the static-k
    ``lax.top_k``.
    """
    logits = logits.astype(jnp.float32)
    b, v = logits.shape
    greedy = jnp.argmax(logits, axis=-1)
    if key is None:
        return greedy
    temp = _per_row(temperature, 0.0, b, jnp.float32)
    kk = _per_row(top_k, 0, b, jnp.int32)
    pp = _per_row(top_p, 1.0, b, jnp.float32)
    scaled = logits / jnp.maximum(temp, 1e-6)[:, None]
    # per-row top-k: kth-largest via full sort + rank gather (k clamps to
    # [1, V]; rows with k<=0 keep everything)
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    kth = jnp.take_along_axis(sorted_desc,
                              (jnp.clip(kk, 1, v) - 1)[:, None], axis=-1)
    use_k = (kk > 0) & (kk < v)
    scaled = jnp.where(use_k[:, None] & (scaled < kth), -1e9, scaled)
    # per-row top-p on the (possibly top-k-filtered) distribution: smallest
    # prefix with cumulative prob >= top_p, per-row cutoff logit
    sorted_p = jnp.sort(scaled, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(sorted_p, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_n = jnp.sum(cum - probs < pp[:, None], axis=-1, keepdims=True)
    cutoff = jnp.take_along_axis(sorted_p, jnp.maximum(keep_n - 1, 0), axis=-1)
    scaled = jnp.where((pp < 1.0)[:, None] & (scaled < cutoff), -1e9, scaled)
    key = jnp.asarray(key) if not isinstance(key, jax.Array) else key
    if _is_key_batch(key, b):
        sampled = jax.vmap(
            lambda k_, l_: jax.random.categorical(k_, l_))(key, scaled)
    else:
        sampled = jax.random.categorical(key, scaled, axis=-1)
    return jnp.where(temp <= 0.0, greedy, sampled)


def _sample(logits, temperature, top_k, top_p):
    """logits (B, V) -> token ids (B,) from the GLOBAL seeded RNG stream
    (scalar-param form used by :func:`generate`; greedy calls draw no key so
    paddle.seed-reproducible programs are unchanged by sampling refactors)."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    if (top_k is None or top_k <= 0) and (top_p is None or top_p >= 1.0):
        # params are concrete scalars here: skip the batched form's sort-
        # based filters entirely on the plain-temperature hot path
        scaled = logits / jnp.maximum(temperature, 1e-6)
        return jax.random.categorical(split_key(), scaled, axis=-1)
    return sample_tokens(logits, split_key(), temperature, top_k, top_p)


def generate(model, input_ids, max_new_tokens=32, eos_token_id=None,
             temperature: float = 0.0, top_k: Optional[int] = None,
             top_p: Optional[float] = None, use_cache: bool = True):
    """Generate continuations for a batch of prompts.

    model: GPTForPretraining (or GPTModel + tied head via it).
    input_ids: (B, T0) int tensor/array. Returns (B, T0 + n) int64 Tensor
    (n <= max_new_tokens; shorter only when every row hit eos).
    """
    ids = unwrap(input_ids)
    if isinstance(ids, Tensor):
        ids = ids._data
    ids = jnp.asarray(np.asarray(ids)).astype(jnp.int32)
    b, t0 = ids.shape
    was_training = model.training
    model.eval()
    attns = _attn_layers(model) if use_cache else []

    def fwd(tokens, position_ids=None):
        out = model(wrap(tokens) if not isinstance(tokens, Tensor) else tokens,
                    position_ids)
        return unwrap(out)

    try:
        with no_grad():
            if use_cache:
                for a in attns:
                    a._gen_cache = {"k": None, "v": None}
            logits = fwd(ids)  # prefill
            finished = jnp.zeros((b,), bool)
            for step in range(int(max_new_tokens)):
                nxt = _sample(logits[:, -1].astype(jnp.float32),
                              temperature, top_k, top_p).astype(jnp.int32)
                if eos_token_id is not None:
                    nxt = jnp.where(finished, eos_token_id, nxt)
                    finished = finished | (nxt == eos_token_id)
                ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
                if eos_token_id is not None and bool(finished.all()):
                    break
                if step == int(max_new_tokens) - 1:
                    break  # no need to compute logits for an unused step
                if use_cache:
                    pos = wrap(jnp.full((b, 1), ids.shape[1] - 1, jnp.int32))
                    logits = fwd(nxt[:, None], pos)
                else:
                    logits = fwd(ids)
    finally:
        for a in attns:
            if hasattr(a, "_gen_cache"):
                del a._gen_cache
        if was_training:
            model.train()
    return wrap(ids.astype(jnp.int64))
