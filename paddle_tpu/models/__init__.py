"""Flagship model families built on the framework's parallel layers.

Parity role: the reference ships its transformer models through PaddleNLP
on top of fleet meta-parallel layers; here the model zoo is in-tree, built
directly on paddle_tpu.distributed.meta_parallel so every parallelism
axis (dp/mp/pp/sharding/sp/ep) applies to each family.
"""
from . import bert, generation, gpt  # noqa: F401
from .generation import generate, sample_tokens  # noqa: F401
from .bert import (  # noqa: F401
    BertConfig,
    BertForPretraining,
    BertModel,
    BertPretrainingCriterion,
    bert_config,
)
from .gpt import (  # noqa: F401
    GPTConfig,
    GPTModel,
    GPTForPretraining,
    GPTPretrainingCriterion,
    gpt_config,
)
