"""BERT encoder family — masked-LM pretraining (BASELINE config #3).

Parity: the reference trains BERT through PaddleNLP modeling on top of
paddle.nn.TransformerEncoder (python/paddle/nn/layer/transformer.py) and the
fused attention path (paddle/fluid/operators/fused/fused_attention_op.cu);
this module rebuilds the same architecture on this framework's TP substrate
(ColumnParallel/RowParallel/VocabParallelEmbedding, mp_layers parity).

TPU-native design mirrors models/gpt.py: weights carry partition_spec
annotations so GSPMD inserts the TP collectives; attention is bidirectional
(is_causal=False) through the shared dispatch in nn.functional_attention;
the MLM head ties the vocab-parallel embedding and the loss is the
vocab-sharded ParallelCrossEntropy.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

from ..distributed.meta_parallel.mp_layers import (
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from ..nn import functional as F
from ..nn.functional_attention import scaled_dot_product_attention
from ..nn.layer import Layer, LayerList
from ..nn.layers.common import Dropout, Embedding, Linear
from ..nn.layers.norm import LayerNorm
from ..ops import creation
from ..ops import manipulation as manip
from ..ops._primitive import primitive

__all__ = [
    "BertConfig",
    "BertModel",
    "BertForPretraining",
    "BertPretrainingCriterion",
    "bert_config",
    "BERT_CONFIGS",
]


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30528  # padded to a 64-multiple for mp divisibility
    hidden_size: int = 768
    num_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: Optional[int] = None
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    attention_dropout_prob: float = 0.1
    layer_norm_epsilon: float = 1e-12
    use_recompute: bool = False

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


BERT_CONFIGS = {
    "bert-base": dict(hidden_size=768, num_layers=12, num_attention_heads=12),
    "bert-large": dict(hidden_size=1024, num_layers=24, num_attention_heads=16),
}


def bert_config(name: str, **overrides) -> BertConfig:
    cfg = dict(BERT_CONFIGS[name])
    cfg.update(overrides)
    return BertConfig(**cfg)


class BertSelfAttention(Layer):
    """Bidirectional self-attention, Megatron TP split (qkv column-parallel,
    output row-parallel — mp_layers.py:97,170 parity)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.num_heads = config.num_attention_heads
        self.head_dim = config.head_dim
        self.dropout_p = config.attention_dropout_prob
        h = config.hidden_size
        self.qkv_proj = ColumnParallelLinear(h, 3 * h, gather_output=False)
        self.out_proj = RowParallelLinear(h, h, input_is_parallel=True)

    def forward(self, x, attn_mask=None):
        b, t = x.shape[0], x.shape[1]
        qkv = self.qkv_proj(x)
        qkv = manip.reshape(qkv, [b, t, 3, self.num_heads, self.head_dim])
        qkv = manip.transpose(qkv, [2, 0, 3, 1, 4])  # [3, B, H, T, D]
        q, k, v = qkv[0], qkv[1], qkv[2]
        out, _ = scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=False,
            dropout_p=self.dropout_p if self.training else 0.0,
        )
        out = manip.transpose(out, [0, 2, 1, 3])
        out = manip.reshape(out, [b, t, self.num_heads * self.head_dim])
        return self.out_proj(out)


class BertLayer(Layer):
    """Post-LN encoder block (original BERT): LN(x + attn(x)); LN(x + ffn)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.attn = BertSelfAttention(config)
        self.ln_1 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.fc_in = ColumnParallelLinear(config.hidden_size, config.intermediate_size,
                                          gather_output=False)
        self.fc_out = RowParallelLinear(config.intermediate_size, config.hidden_size,
                                        input_is_parallel=True)
        self.ln_2 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.dropout1 = Dropout(config.hidden_dropout_prob, mode="upscale_in_train")
        self.dropout2 = Dropout(config.hidden_dropout_prob, mode="upscale_in_train")
        self._use_recompute = config.use_recompute

    def _block(self, x, attn_mask=None):
        x = self.ln_1(x + self.dropout1(self.attn(x, attn_mask)))
        h = self.fc_out(F.gelu(self.fc_in(x), approximate=True))
        return self.ln_2(x + self.dropout2(h))

    def forward(self, x, attn_mask=None):
        if self._use_recompute and self.training:
            import jax

            @primitive
            def _remat(h):
                from ..tensor import Tensor

                def raw(arr):
                    return self._block(Tensor(arr), attn_mask)._data

                return jax.checkpoint(raw)(h)

            return _remat(x)
        return self._block(x, attn_mask)


class BertEmbeddings(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.word_embeddings = VocabParallelEmbedding(config.vocab_size, config.hidden_size)
        self.position_embeddings = Embedding(config.max_position_embeddings, config.hidden_size)
        self.token_type_embeddings = Embedding(config.type_vocab_size, config.hidden_size)
        self.layer_norm = LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.dropout = Dropout(config.hidden_dropout_prob, mode="upscale_in_train")

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        t = input_ids.shape[-1]
        if position_ids is None:
            position_ids = creation.arange(0, t, dtype="int64")
        emb = self.word_embeddings(input_ids) + self.position_embeddings(position_ids)
        if token_type_ids is not None:
            emb = emb + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(emb))


class BertModel(Layer):
    """Returns (sequence_output [B,T,H], pooled_output [B,H])."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.encoder = LayerList([BertLayer(config) for _ in range(config.num_layers)])
        self.pooler = Linear(config.hidden_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                position_ids=None):
        # attention_mask: [B, T] with 1 = attend, 0 = pad -> additive mask
        attn_mask = None
        if attention_mask is not None:
            @primitive(nondiff=True)
            def _additive(m):
                return ((1.0 - m.astype(jnp.float32)) * -1e9)[:, None, None, :]

            attn_mask = _additive(attention_mask)
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        for block in self.encoder:
            x = block(x, attn_mask)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


class BertForPretraining(Layer):
    """MLM head (transform + tied vocab-parallel decoder) and NSP head."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.transform = Linear(config.hidden_size, config.hidden_size)
        self.transform_ln = LayerNorm(config.hidden_size,
                                      epsilon=config.layer_norm_epsilon)
        self.decoder_bias = self.create_parameter(
            [config.vocab_size], is_bias=True)
        self.nsp = Linear(config.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        h = self.transform_ln(F.gelu(self.transform(seq), approximate=True))
        w = self.bert.embeddings.word_embeddings.weight  # [V, H] on 'mp'

        @primitive
        def _logits(h, w, b):
            return jnp.matmul(h, w.T) + b

        prediction_logits = _logits(h, w, self.decoder_bias)
        nsp_logits = self.nsp(pooled)
        return prediction_logits, nsp_logits


class BertPretrainingCriterion(Layer):
    """MLM loss over masked positions (+ NSP loss when labels given).

    masked_lm_labels uses -100 for unmasked positions (ignore_index parity
    with softmax_with_cross_entropy's ignore path)."""

    def __init__(self, config: Optional[BertConfig] = None):
        super().__init__()
        self.ce = ParallelCrossEntropy(ignore_index=-100)

    def forward(self, prediction_logits, masked_lm_labels,
                nsp_logits=None, next_sentence_labels=None):
        mlm = self.ce(prediction_logits, masked_lm_labels)  # [B, T, 1]

        @primitive
        def _masked_mean(losses, labels):
            mask = (labels != -100).astype(losses.dtype)
            return (losses[..., 0] * mask).sum() / jnp.maximum(mask.sum(), 1.0)

        loss = _masked_mean(mlm, masked_lm_labels)
        if nsp_logits is not None and next_sentence_labels is not None:
            nsp = F.softmax_with_cross_entropy(
                nsp_logits, manip.reshape(next_sentence_labels, [-1, 1]))
            loss = loss + nsp.mean()
        return loss
