"""paddle_tpu — a TPU-native deep learning framework.

Capability parity with the reference (SunNy820828449/Paddle, PaddlePaddle
v2.1/2.2-era) re-designed for TPU: jax/XLA is the compute substrate, Pallas
supplies custom kernels, a single jax.sharding.Mesh carries every parallelism
axis. See SURVEY.md for the capability map and ARCHITECTURE notes in README.

Import as a drop-in shape: ``import paddle_tpu as paddle``.
"""
from __future__ import annotations

from . import _jax_compat  # noqa: F401  (must run before any lax.axis_size use)
from . import device as _device_mod
from . import dtype as _dtype_mod
from . import random as _random_mod
from .autograd.tape import (  # noqa: F401
    enable_grad,
    grad,
    is_grad_enabled,
    no_grad,
    set_grad_enabled,
)
from .device import (  # noqa: F401
    CPUPlace,
    CUDAPinnedPlace,
    CUDAPlace,
    NPUPlace,
    Place,
    TPUPlace,
    XPUPlace,
    device_count,
    get_device,
    is_compiled_with_cuda,
    is_compiled_with_npu,
    is_compiled_with_tpu,
    is_compiled_with_xpu,
    set_device,
)
from .dtype import (  # noqa: F401
    bfloat16,
    bool,  # noqa: A004
    complex64,
    complex128,
    dtype,
    finfo,
    float16,
    float32,
    float64,
    iinfo,
    int8,
    int16,
    int32,
    int64,
    uint8,
)
from .random import get_rng_state, seed, set_rng_state  # noqa: F401
from .tensor import Tensor, is_tensor, to_tensor  # noqa: F401

# the whole functional op surface lands at top level (paddle.add, paddle.matmul...)
from .ops import *  # noqa: F401,F403
from . import ops  # noqa: F401

__version__ = "0.1.0"

# ---------------------------------------------------------------------------
# dygraph/static mode toggles (parity: paddle.enable_static/disable_static).
# This framework is always eager-first; "static mode" routes through
# paddle_tpu.static's Program tracer.
# ---------------------------------------------------------------------------
_static_mode = False


def enable_static():
    global _static_mode
    _static_mode = True


def disable_static():
    global _static_mode
    _static_mode = False


def in_dynamic_mode() -> bool:
    return not _static_mode


# lazy submodule surface: these import Layer/ops machinery and would otherwise
# create import cycles at package-load time.
_LAZY_SUBMODULES = (
    "nn",
    "optimizer",
    "amp",
    "jit",
    "io",
    "static",
    "distributed",
    "vision",
    "text",
    "metric",
    "hapi",
    "autograd",
    "incubate",
    "utils",
    "profiler",
    "framework",
    "sysconfig",
    "onnx",
    "inference",
    "fft",
    "signal",
    "quantization",
    "distribution",
    "regularizer",
    "resilience",
    "serving",
    "hub",
    "dataset",
    "reader",
    "compat",
    "linalg",
    "version",
)


def __getattr__(name):
    if name in _LAZY_SUBMODULES:
        import importlib

        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    if name == "save":
        from .framework.io import save

        return save
    if name == "load":
        from .framework.io import load

        return load
    if name == "summary":
        from .hapi.model_summary import summary

        return summary
    if name == "flops":
        from .hapi.dynamic_flops import flops

        return flops
    if name == "Model":
        from .hapi.model import Model

        return Model
    if name == "DataParallel":
        from .distributed.parallel import DataParallel

        return DataParallel
    if name == "ParamAttr":
        from .nn.param_attr import ParamAttr

        return ParamAttr
    if name == "get_flags":
        from .framework.flags import get_flags

        return get_flags
    if name == "set_flags":
        from .framework.flags import set_flags

        return set_flags
    if name == "set_default_dtype":
        from .framework.dtype_default import set_default_dtype

        return set_default_dtype
    if name == "get_default_dtype":
        from .framework.dtype_default import get_default_dtype

        return get_default_dtype
    if name in ("disable_signal_handler", "set_printoptions"):
        from . import framework as _fw

        return getattr(_fw, name)
    if name in ("get_cuda_rng_state", "set_cuda_rng_state"):
        # device-RNG aliases: on TPU the seeded global PRNG plays the role of
        # the per-device curand states (parity: paddle.get/set_cuda_rng_state)
        from .random import get_rng_state, set_rng_state

        return get_rng_state if name == "get_cuda_rng_state" else set_rng_state
    if name == "batch":
        return _batch_reader
    if name == "check_shape":
        return _check_shape
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")


def _batch_reader(reader, batch_size, drop_last=False):
    """Legacy reader decorator: group a sample generator into batches
    (parity: python/paddle/batch.py in the reference)."""

    def batched():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    if batch_size < 1:
        raise ValueError("batch_size should be a positive integer")
    return batched


def _check_shape(shape):
    """Validate a shape argument (parity: paddle.check_shape — list/tuple
    entries must be non-negative ints; a Tensor shape must be integer)."""
    from .tensor import Tensor as _T

    if isinstance(shape, _T):
        if not str(shape.dtype).endswith(("int32", "int64")):
            raise TypeError("shape tensor dtype must be int32 or int64")
        return
    for ele in shape:
        if isinstance(ele, _T):
            continue
        if not isinstance(ele, (int,)):
            raise TypeError("All elements in ``shape`` must be integers")
        if ele < 0:
            raise ValueError("All elements in ``shape`` must be positive")
