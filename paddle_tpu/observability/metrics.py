"""Metrics registry: counters, gauges, log-bucketed histograms, Prometheus.

Parity: the reference exports monitor table/StatValue series ("Monitor"
ops, ``paddle.fluid.monitor``) and Paddle Serving exposes brpc vars; the
cloud-native form of both is Prometheus text exposition. One registry owns
a process's series; each metric supports labels; histograms use
log-spaced (power-of-2) buckets so one layout covers microsecond decode
ticks and minute-long checkpoint saves, with p50/p95/p99 estimated from
the bucket counts (what the JSON snapshot reports).

Exposition (:meth:`MetricsRegistry.prometheus_text`) follows the text
format 0.0.4 rules: ``# HELP``/``# TYPE`` headers, escaped label values,
cumulative ``_bucket{le=...}`` series ending in ``+Inf``, ``_sum`` and
``_count`` — a strict parser (the test ships one) must accept a scrape.

:func:`start_http_exporter` mounts a registry on a tiny HTTP endpoint
(``GET /metrics``) with Accept negotiation — Prometheus text by default,
the JSON dict under ``Accept: application/json`` — the training-side
exporter; the serving server and router reuse the same negotiation with
JSON as *their* default (their JSON bodies predate this module and stay
byte-compatible).
"""
from __future__ import annotations

import json
import math
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "default_registry",
    "log_buckets",
    "prometheus_content_type",
    "wants_prometheus",
    "MetricsHTTPServer",
    "start_http_exporter",
]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: the Prometheus text-format content type served on a negotiated scrape
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def prometheus_content_type() -> str:
    return PROMETHEUS_CONTENT_TYPE


def wants_prometheus(accept: Optional[str]) -> bool:
    """Accept-header negotiation: does the client want text exposition?
    JSON stays the default — existing ``ServingClient``/router consumers
    send no Accept (or ``*/*``) and must keep their byte-compatible body."""
    if not accept:
        return False
    accept = accept.lower()
    return ("text/plain" in accept or "openmetrics" in accept
            or "prometheus" in accept)


def log_buckets(lo: float = 1e-4, hi: float = 64.0,
                factor: float = 2.0) -> List[float]:
    """Log-spaced bucket upper bounds covering [lo, hi] (seconds): 0.1ms
    decode ticks through minute-long saves in ~20 buckets."""
    if not (lo > 0 and hi > lo and factor > 1):
        raise ValueError("need lo > 0, hi > lo, factor > 1")
    out, b = [], float(lo)
    while b < hi:
        out.append(b)
        b *= factor
    out.append(float(hi))
    return out


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return (s.replace("\\", "\\\\").replace("\"", "\\\"")
             .replace("\n", "\\n"))


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if isinstance(v, float) and math.isnan(v):
        return "NaN"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _label_str(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in labels)
    return "{" + inner + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str,
                 labelnames: Sequence[str] = ()):
        if not _NAME_RE.match(name):
            raise ValueError(f"bad metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"bad label name {ln!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != declared "
                f"{sorted(self.labelnames)}")
        return tuple((k, str(labels[k])) for k in self.labelnames)


class Counter(_Metric):
    """Monotonic counter (per label set)."""

    kind = "counter"

    def __init__(self, name, help, labelnames=()):
        super().__init__(name, help, labelnames)
        self._values: Dict[Tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels):
        if amount < 0:
            raise ValueError("counters only go up")
        k = self._key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def _samples(self):
        with self._lock:
            items = sorted(self._values.items())
        return [(self.name, k, v) for k, v in items] or (
            [(self.name, (), 0.0)] if not self.labelnames else [])

    def _to_dict(self):
        with self._lock:
            if not self.labelnames:
                return self._values.get((), 0.0)
            return {json.dumps(dict(k)): v
                    for k, v in sorted(self._values.items())}


class Gauge(_Metric):
    """Set-to-current-value metric (per label set)."""

    kind = "gauge"

    def __init__(self, name, help, labelnames=()):
        super().__init__(name, help, labelnames)
        self._values: Dict[Tuple, float] = {}

    def set(self, value: float, **labels):
        k = self._key(labels)
        with self._lock:
            self._values[k] = float(value)

    def inc(self, amount: float = 1.0, **labels):
        k = self._key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + amount

    def value(self, **labels) -> Optional[float]:
        with self._lock:
            return self._values.get(self._key(labels))

    def remove(self, **labels):
        with self._lock:
            self._values.pop(self._key(labels), None)

    def _samples(self):
        with self._lock:
            items = sorted(self._values.items())
        return [(self.name, k, v) for k, v in items]

    def _to_dict(self):
        with self._lock:
            if not self.labelnames:
                return self._values.get(())
            return {json.dumps(dict(k)): v
                    for k, v in sorted(self._values.items())}


class Histogram(_Metric):
    """Log-bucketed histogram with percentile estimation.

    Buckets are UPPER bounds (``le`` semantics); an implicit ``+Inf``
    bucket catches the tail. Percentiles interpolate linearly inside the
    selected bucket (0 as the floor of the first), which is the usual
    Prometheus ``histogram_quantile`` estimate — good to a bucket width.
    """

    kind = "histogram"

    def __init__(self, name, help, labelnames=(), buckets=None):
        super().__init__(name, help, labelnames)
        bs = sorted(float(b) for b in (buckets or log_buckets()))
        if not bs:
            raise ValueError("need at least one bucket")
        self.buckets = bs
        self._counts: Dict[Tuple, List[int]] = {}   # per-bucket + +Inf
        self._sum: Dict[Tuple, float] = {}

    def observe(self, value: float, **labels):
        k = self._key(labels)
        v = float(value)
        with self._lock:
            counts = self._counts.setdefault(k, [0] * (len(self.buckets) + 1))
            for i, b in enumerate(self.buckets):
                if v <= b:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            self._sum[k] = self._sum.get(k, 0.0) + v

    def count(self, **labels) -> int:
        with self._lock:
            return sum(self._counts.get(self._key(labels), ()))

    def sum(self, **labels) -> float:
        with self._lock:
            return self._sum.get(self._key(labels), 0.0)

    def percentile(self, q: float, **labels) -> Optional[float]:
        """Estimated q-th percentile (q in [0, 100])."""
        k = self._key(labels)
        with self._lock:
            counts = list(self._counts.get(k, ()))
        total = sum(counts)
        if not total:
            return None
        rank = q / 100.0 * total
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= rank:
                lo = self.buckets[i - 1] if 0 < i <= len(self.buckets) else 0.0
                hi = self.buckets[i] if i < len(self.buckets) else lo
                frac = (rank - cum) / c
                return lo + (hi - lo) * max(0.0, min(1.0, frac))
            cum += c
        return self.buckets[-1]

    def _samples(self):
        out = []
        with self._lock:
            items = sorted(self._counts.items())
            sums = dict(self._sum)
        for k, counts in items:
            cum = 0
            for b, c in zip(self.buckets, counts):
                cum += c
                out.append((self.name + "_bucket",
                            k + (("le", _fmt(b)),), cum))
            cum += counts[-1]
            out.append((self.name + "_bucket", k + (("le", "+Inf"),), cum))
            out.append((self.name + "_sum", k, sums.get(k, 0.0)))
            out.append((self.name + "_count", k, cum))
        return out

    def _to_dict(self):
        def one(k):
            with self._lock:
                counts = list(self._counts.get(k, ()))
                s = self._sum.get(k, 0.0)
            n = sum(counts)
            return {
                "count": n,
                "sum": s,
                "p50": self.percentile(50, **dict(k)),
                "p95": self.percentile(95, **dict(k)),
                "p99": self.percentile(99, **dict(k)),
            }
        with self._lock:
            keys = sorted(self._counts)
        if not self.labelnames:
            return one(())
        return {json.dumps(dict(k)): one(k) for k in keys}


class MetricsRegistry:
    """Ordered name → metric registry with get-or-create constructors."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: "Dict[str, _Metric]" = {}

    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) or m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.kind} with labels {m.labelnames}")
                return m
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def clear(self):
        with self._lock:
            self._metrics.clear()

    def prometheus_text(self) -> str:
        """Text exposition format 0.0.4 of every registered series."""
        with self._lock:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in metrics:
            samples = m._samples()
            if not samples:
                continue
            if m.help:
                lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for name, labels, value in samples:
                lines.append(f"{name}{_label_str(labels)} {_fmt(value)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def to_dict(self) -> dict:
        with self._lock:
            metrics = list(self._metrics.values())
        return {m.name: {"type": m.kind, "help": m.help,
                         "values": m._to_dict()} for m in metrics}


_default = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide registry (training-side series land here)."""
    return _default


# -- HTTP exposition ---------------------------------------------------------
class MetricsHTTPServer:
    """Minimal ``GET /metrics`` endpoint with Accept negotiation, on the
    fleet http_server.py idiom (the serving/router planes reuse exactly
    this shape). ``json_fn`` produces the default JSON body; ``prom_fn``
    the Prometheus text body (served when the client asks for text)."""

    def __init__(self, json_fn: Callable[[], dict],
                 prom_fn: Callable[[], str], host: str = "127.0.0.1",
                 port: int = 0, default_prometheus: bool = False):

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path.rstrip("/") != "/metrics":
                    body = b'{"error": "unknown endpoint"}'
                    self.send_response(404)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                accept = self.headers.get("Accept")
                prom = wants_prometheus(accept) or (
                    default_prometheus
                    and "json" not in (accept or "").lower())
                if prom:
                    body = prom_fn().encode()
                    ctype = PROMETHEUS_CONTENT_TYPE
                else:
                    body = json.dumps(json_fn()).encode()
                    ctype = "application/json"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self.host = host
        self.port = self._httpd.server_address[1]
        self.addr = f"{host}:{self.port}"
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MetricsHTTPServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def start_http_exporter(registry: Optional[MetricsRegistry] = None,
                        host: str = "127.0.0.1",
                        port: int = 0) -> MetricsHTTPServer:
    """Training-side exporter: mount ``registry`` (default: the process
    registry) on ``GET /metrics`` — Prometheus text on a negotiated
    scrape, the JSON dict under ``Accept: application/json``."""
    reg = registry or _default
    return MetricsHTTPServer(json_fn=reg.to_dict,
                             prom_fn=reg.prometheus_text,
                             host=host, port=port,
                             default_prometheus=True).start()
