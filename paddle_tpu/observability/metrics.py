"""Metrics registry: counters, gauges, log-bucketed histograms, Prometheus.

Parity: the reference exports monitor table/StatValue series ("Monitor"
ops, ``paddle.fluid.monitor``) and Paddle Serving exposes brpc vars; the
cloud-native form of both is Prometheus text exposition. One registry owns
a process's series; each metric supports labels; histograms use
log-spaced (power-of-2) buckets so one layout covers microsecond decode
ticks and minute-long checkpoint saves, with p50/p95/p99 estimated from
the bucket counts (what the JSON snapshot reports).

Exposition (:meth:`MetricsRegistry.prometheus_text`) follows the text
format 0.0.4 rules: ``# HELP``/``# TYPE`` headers, escaped label values,
cumulative ``_bucket{le=...}`` series ending in ``+Inf``, ``_sum`` and
``_count`` — a strict parser (the test ships one) must accept a scrape.

:func:`start_http_exporter` mounts a registry on a tiny HTTP endpoint
(``GET /metrics``) with Accept negotiation — Prometheus text by default,
the JSON dict under ``Accept: application/json`` — the training-side
exporter; the serving server and router reuse the same negotiation with
JSON as *their* default (their JSON bodies predate this module and stay
byte-compatible).
"""
from __future__ import annotations

import json
import math
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "default_registry",
    "log_buckets",
    "prometheus_content_type",
    "openmetrics_content_type",
    "wants_prometheus",
    "wants_openmetrics",
    "dump_metrics",
    "METRICS_DUMP_SCHEMA_VERSION",
    "MetricsHTTPServer",
    "start_http_exporter",
]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: the Prometheus text-format content type served on a negotiated scrape
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
#: the OpenMetrics content type (exemplar-bearing exposition, r14)
OPENMETRICS_CONTENT_TYPE = \
    "application/openmetrics-text; version=1.0.0; charset=utf-8"

#: version of the JSON metric-dump layout (:func:`dump_metrics`)
METRICS_DUMP_SCHEMA_VERSION = 1


def prometheus_content_type() -> str:
    return PROMETHEUS_CONTENT_TYPE


def openmetrics_content_type() -> str:
    return OPENMETRICS_CONTENT_TYPE


def wants_openmetrics(accept: Optional[str]) -> bool:
    """True when the client explicitly negotiates the OpenMetrics
    exposition (``Accept: application/openmetrics-text``) — the ONLY way
    to receive exemplar syntax. Handlers check this BEFORE
    :func:`wants_prometheus` (which accepts any text-ish Accept), so the
    0.0.4 body stays byte-identical for every pre-r14 client."""
    if not accept:
        return False
    return "application/openmetrics-text" in accept.lower()


def wants_prometheus(accept: Optional[str]) -> bool:
    """Accept-header negotiation: does the client want text exposition?
    JSON stays the default — existing ``ServingClient``/router consumers
    send no Accept (or ``*/*``) and must keep their byte-compatible body."""
    if not accept:
        return False
    accept = accept.lower()
    return ("text/plain" in accept or "openmetrics" in accept
            or "prometheus" in accept)


def log_buckets(lo: float = 1e-4, hi: float = 64.0,
                factor: float = 2.0) -> List[float]:
    """Log-spaced bucket upper bounds covering [lo, hi] (seconds): 0.1ms
    decode ticks through minute-long saves in ~20 buckets."""
    if not (lo > 0 and hi > lo and factor > 1):
        raise ValueError("need lo > 0, hi > lo, factor > 1")
    out, b = [], float(lo)
    while b < hi:
        out.append(b)
        b *= factor
    out.append(float(hi))
    return out


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return (s.replace("\\", "\\\\").replace("\"", "\\\"")
             .replace("\n", "\\n"))


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if isinstance(v, float) and math.isnan(v):
        return "NaN"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _label_str(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in labels)
    return "{" + inner + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str,
                 labelnames: Sequence[str] = ()):
        if not _NAME_RE.match(name):
            raise ValueError(f"bad metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"bad label name {ln!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != declared "
                f"{sorted(self.labelnames)}")
        return tuple((k, str(labels[k])) for k in self.labelnames)


class Counter(_Metric):
    """Monotonic counter (per label set)."""

    kind = "counter"

    def __init__(self, name, help, labelnames=()):
        super().__init__(name, help, labelnames)
        self._values: Dict[Tuple, float] = {}  # guarded-by: self._lock

    def inc(self, amount: float = 1.0, **labels):
        if amount < 0:
            raise ValueError("counters only go up")
        k = self._key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def _samples(self):
        with self._lock:
            items = sorted(self._values.items())
        return [(self.name, k, v) for k, v in items] or (
            [(self.name, (), 0.0)] if not self.labelnames else [])

    def _to_dict(self):
        with self._lock:
            if not self.labelnames:
                return self._values.get((), 0.0)
            return {json.dumps(dict(k)): v
                    for k, v in sorted(self._values.items())}


class Gauge(_Metric):
    """Set-to-current-value metric (per label set)."""

    kind = "gauge"

    def __init__(self, name, help, labelnames=()):
        super().__init__(name, help, labelnames)
        self._values: Dict[Tuple, float] = {}

    def set(self, value: float, **labels):
        k = self._key(labels)
        with self._lock:
            self._values[k] = float(value)

    def inc(self, amount: float = 1.0, **labels):
        k = self._key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + amount

    def value(self, **labels) -> Optional[float]:
        with self._lock:
            return self._values.get(self._key(labels))

    def remove(self, **labels):
        with self._lock:
            self._values.pop(self._key(labels), None)

    def _samples(self):
        with self._lock:
            items = sorted(self._values.items())
        return [(self.name, k, v) for k, v in items]

    def _to_dict(self):
        with self._lock:
            if not self.labelnames:
                return self._values.get(())
            return {json.dumps(dict(k)): v
                    for k, v in sorted(self._values.items())}


class Histogram(_Metric):
    """Log-bucketed histogram with percentile estimation.

    Buckets are UPPER bounds (``le`` semantics); an implicit ``+Inf``
    bucket catches the tail. Percentiles interpolate linearly inside the
    selected bucket (0 as the floor of the first), which is the usual
    Prometheus ``histogram_quantile`` estimate — good to a bucket width.

    Exemplars (r14, opt-in via ``exemplars=True``): each observation that
    carries a trace id (explicit ``trace_id=`` or inherited from the
    ambient :func:`~.trace.current_trace` context) is remembered as the
    bucket's LAST exemplar — bounded at one per bucket per label set, so
    a p99 TTFT bucket always links to a real trace the merge CLI can
    pull. Exemplars surface ONLY in the OpenMetrics exposition
    (``# {trace_id="..."} value ts`` suffix) and in :func:`dump_metrics`;
    the Prometheus 0.0.4 text and the JSON snapshot are byte-identical
    with exemplars on or off.
    """

    kind = "histogram"

    def __init__(self, name, help, labelnames=(), buckets=None,
                 exemplars: bool = False):
        super().__init__(name, help, labelnames)
        bs = sorted(float(b) for b in (buckets or log_buckets()))
        if not bs:
            raise ValueError("need at least one bucket")
        self.buckets = bs
        self.exemplars_enabled = bool(exemplars)
        self._counts: Dict[Tuple, List[int]] = {}   # per-bucket + +Inf
        self._sum: Dict[Tuple, float] = {}
        # label set -> bucket index -> (trace_id, value, unix ts)
        self._exemplars: Dict[Tuple, Dict[int, Tuple[str, float, float]]] = {}

    def observe(self, value: float, trace_id: Optional[str] = None,
                **labels):
        k = self._key(labels)
        v = float(value)
        if self.exemplars_enabled and trace_id is None:
            from .trace import current_trace

            ctx = current_trace()
            if ctx is not None:
                trace_id = ctx[0]
        with self._lock:
            counts = self._counts.setdefault(k, [0] * (len(self.buckets) + 1))
            for i, b in enumerate(self.buckets):
                if v <= b:
                    counts[i] += 1
                    break
            else:
                i = len(self.buckets)
                counts[-1] += 1
            self._sum[k] = self._sum.get(k, 0.0) + v
            if self.exemplars_enabled and trace_id:
                self._exemplars.setdefault(k, {})[i] = (
                    str(trace_id), v, time.time())

    def exemplars(self, **labels) -> Dict[str, dict]:
        """{le: {"trace_id", "value", "ts"}} for one label set — the
        bucket→last-trace join the merge CLI renders."""
        k = self._key(labels)
        with self._lock:
            ex = dict(self._exemplars.get(k, {}))
        les = [_fmt(b) for b in self.buckets] + ["+Inf"]
        return {les[i]: {"trace_id": t, "value": v, "ts": ts}
                for i, (t, v, ts) in sorted(ex.items())}

    def count(self, **labels) -> int:
        with self._lock:
            return sum(self._counts.get(self._key(labels), ()))

    def sum(self, **labels) -> float:
        with self._lock:
            return self._sum.get(self._key(labels), 0.0)

    def percentile(self, q: float, **labels) -> Optional[float]:
        """Estimated q-th percentile (q in [0, 100])."""
        k = self._key(labels)
        with self._lock:
            counts = list(self._counts.get(k, ()))
        total = sum(counts)
        if not total:
            return None
        rank = q / 100.0 * total
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= rank:
                lo = self.buckets[i - 1] if 0 < i <= len(self.buckets) else 0.0
                hi = self.buckets[i] if i < len(self.buckets) else lo
                frac = (rank - cum) / c
                return lo + (hi - lo) * max(0.0, min(1.0, frac))
            cum += c
        return self.buckets[-1]

    def _samples(self):
        out = []
        with self._lock:
            items = sorted(self._counts.items())
            sums = dict(self._sum)
        for k, counts in items:
            cum = 0
            for b, c in zip(self.buckets, counts):
                cum += c
                out.append((self.name + "_bucket",
                            k + (("le", _fmt(b)),), cum))
            cum += counts[-1]
            out.append((self.name + "_bucket", k + (("le", "+Inf"),), cum))
            out.append((self.name + "_sum", k, sums.get(k, 0.0)))
            out.append((self.name + "_count", k, cum))
        return out

    def _samples_om(self):
        """(name, labels, value, exemplar) rows for the OpenMetrics
        exposition — same series as :meth:`_samples`, with each bucket's
        last exemplar attached where one was captured."""
        out = []
        with self._lock:
            items = sorted(self._counts.items())
            sums = dict(self._sum)
            exs = {k: dict(v) for k, v in self._exemplars.items()}
        for k, counts in items:
            ex = exs.get(k, {})
            cum = 0
            for i, (b, c) in enumerate(zip(self.buckets, counts)):
                cum += c
                out.append((self.name + "_bucket",
                            k + (("le", _fmt(b)),), cum, ex.get(i)))
            cum += counts[-1]
            out.append((self.name + "_bucket", k + (("le", "+Inf"),), cum,
                        ex.get(len(self.buckets))))
            out.append((self.name + "_sum", k, sums.get(k, 0.0), None))
            out.append((self.name + "_count", k, cum, None))
        return out

    def _to_dict(self, include_exemplars: bool = False):
        # exemplars ride ONLY when explicitly asked for (dump_metrics /
        # flight dumps): the default JSON snapshot stays byte-identical
        # with exemplars on or off — the same contract as the 0.0.4 text
        def one(k):
            with self._lock:
                counts = list(self._counts.get(k, ()))
                s = self._sum.get(k, 0.0)
            n = sum(counts)
            out = {
                "count": n,
                "sum": s,
                "p50": self.percentile(50, **dict(k)),
                "p95": self.percentile(95, **dict(k)),
                "p99": self.percentile(99, **dict(k)),
            }
            if include_exemplars and self.exemplars_enabled:
                ex = self.exemplars(**dict(k))
                if ex:
                    out["exemplars"] = ex
            return out
        with self._lock:
            keys = sorted(self._counts)
        if not self.labelnames:
            return one(())
        return {json.dumps(dict(k)): one(k) for k in keys}


class MetricsRegistry:
    """Ordered name → metric registry with get-or-create constructors."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: "Dict[str, _Metric]" = {}  # guarded-by: self._lock

    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls) or m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.kind} with labels {m.labelnames}")
                return m
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None,
                  exemplars: bool = False) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets, exemplars=exemplars)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def clear(self):
        with self._lock:
            self._metrics.clear()

    def prometheus_text(self) -> str:
        """Text exposition format 0.0.4 of every registered series."""
        with self._lock:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in metrics:
            samples = m._samples()
            if not samples:
                continue
            if m.help:
                lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for name, labels, value in samples:
                lines.append(f"{name}{_label_str(labels)} {_fmt(value)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def openmetrics_text(self) -> str:
        """OpenMetrics 1.0 exposition of every registered series — the
        ONLY exposition that carries exemplars. Counter families follow
        the spec (``# TYPE x counter`` + ``x_total`` samples); histogram
        ``_bucket`` lines append ``# {trace_id="..."} value ts`` where an
        exemplar was captured; the body ends with ``# EOF``. Served only
        under ``Accept: application/openmetrics-text`` so the 0.0.4 text
        (:meth:`prometheus_text`) stays byte-identical for old scrapers.
        """
        with self._lock:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in metrics:
            if m.kind == "histogram":
                samples = [(n, l, v, e) for n, l, v, e in m._samples_om()]
            else:
                samples = [(n, l, v, None) for n, l, v in m._samples()]
            if not samples:
                continue
            family = m.name
            if m.kind == "counter" and family.endswith("_total"):
                family = family[: -len("_total")]
            lines.append(f"# TYPE {family} {m.kind}")
            if m.help:
                lines.append(f"# HELP {family} {_escape_help(m.help)}")
            for name, labels, value, ex in samples:
                if m.kind == "counter" and not name.endswith("_total"):
                    name += "_total"
                line = f"{name}{_label_str(labels)} {_fmt(value)}"
                if ex is not None:
                    trace_id, exv, exts = ex
                    line += (f' # {{trace_id="{_escape_label(trace_id)}"}} '
                             f"{_fmt(exv)} {exts:.3f}")
                lines.append(line)
        lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def to_dict(self, include_exemplars: bool = False) -> dict:
        """JSON snapshot of every series. Byte-identical with exemplars
        on or off by default (the pre-r14 consumer contract — this body
        is what the training exporter serves as JSON);
        ``include_exemplars=True`` adds each exemplar-enabled histogram's
        bucket exemplars (used by :func:`dump_metrics` and the flight
        recorder, whose dumps feed the merge CLI)."""
        with self._lock:
            metrics = list(self._metrics.values())
        return {m.name: {"type": m.kind, "help": m.help,
                         "values": (m._to_dict(include_exemplars=True)
                                    if include_exemplars
                                    and isinstance(m, Histogram)
                                    else m._to_dict())}
                for m in metrics}


_default = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide registry (training-side series land here)."""
    return _default


# -- HTTP exposition ---------------------------------------------------------
class MetricsHTTPServer:
    """Minimal ``GET /metrics`` endpoint with Accept negotiation, on the
    fleet http_server.py idiom (the serving/router planes reuse exactly
    this shape). ``json_fn`` produces the default JSON body; ``prom_fn``
    the Prometheus text body (served when the client asks for text)."""

    def __init__(self, json_fn: Callable[[], dict],
                 prom_fn: Callable[[], str], host: str = "127.0.0.1",
                 port: int = 0, default_prometheus: bool = False,
                 om_fn: Optional[Callable[[], str]] = None):

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path.rstrip("/") != "/metrics":
                    body = b'{"error": "unknown endpoint"}'
                    self.send_response(404)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                accept = self.headers.get("Accept")
                # OpenMetrics wins when explicitly negotiated (the only
                # exposition carrying exemplars); 0.0.4 and JSON bodies
                # stay byte-compatible for every pre-r14 consumer
                if om_fn is not None and wants_openmetrics(accept):
                    body = om_fn().encode()
                    ctype = OPENMETRICS_CONTENT_TYPE
                elif wants_prometheus(accept) or (
                        default_prometheus
                        and "json" not in (accept or "").lower()):
                    body = prom_fn().encode()
                    ctype = PROMETHEUS_CONTENT_TYPE
                else:
                    body = json.dumps(json_fn()).encode()
                    ctype = "application/json"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self.host = host
        self.port = self._httpd.server_address[1]
        self.addr = f"{host}:{self.port}"
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MetricsHTTPServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def start_http_exporter(registry: Optional[MetricsRegistry] = None,
                        host: str = "127.0.0.1",
                        port: int = 0) -> MetricsHTTPServer:
    """Training-side exporter: mount ``registry`` (default: the process
    registry) on ``GET /metrics`` — Prometheus text on a negotiated
    scrape, the JSON dict under ``Accept: application/json``."""
    reg = registry or _default
    return MetricsHTTPServer(json_fn=reg.to_dict,
                             prom_fn=reg.prometheus_text,
                             om_fn=reg.openmetrics_text,
                             host=host, port=port,
                             default_prometheus=True).start()


def dump_metrics(registry: Optional[MetricsRegistry] = None,
                 path: Optional[str] = None,
                 process: Optional[str] = None) -> dict:
    """Versioned JSON dump of a registry's series (exemplars included for
    exemplar-enabled histograms) — the metric-side sibling of
    :func:`~.trace.dump_trace`; ``python -m paddle_tpu.observability
    merge`` accepts these alongside span dumps and renders each exemplar
    as an instant event linking to its trace."""
    reg = registry or _default
    doc = {
        "schema_version": METRICS_DUMP_SCHEMA_VERSION,
        "process": process or f"pid-{os.getpid()}",
        "pid": os.getpid(),
        "wall_time": time.time(),
        "metrics": reg.to_dict(include_exemplars=True),
    }
    if path is not None:
        with open(path, "w") as f:
            json.dump(doc, f, indent=1)
    return doc
