"""Crash flight recorder: "what was the system doing when it died".

The trace ring (:mod:`.trace`) always holds the last N spans; this module
freezes it — plus the process's metric series and a small set of pinned
notes (current step, replica address, ...) — into ONE versioned JSON
snapshot at the moments that matter:

* anomaly-sentinel halt / rollback (:class:`~..resilience.sentinel
  .SentinelMonitor`),
* :class:`~..resilience.preemption.PreemptionGuard` SIGTERM / deadline,
* a serving engine tick failing (requests failed, loop survives),
* a router-CONFIRMED replica death (probe agreed the replica is gone).

Contract: dumping must never make the crash worse. Every ``dump`` is
exception-contained (a full disk loses the dump, not the exit protocol),
and the recorder holds the snapshot in memory (``last``) even when no
directory is configured, so tests and post-mortem debuggers can read it
without touching the filesystem. ``PADDLE_TPU_FLIGHT_DIR`` arms file
output process-wide.

Disabled-mode guarantee: notes/dumps are pure host bookkeeping — nothing
here touches a jax trace, so the r6/r7 jaxpr-identity bar is unaffected.
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
import warnings
import weakref
from typing import Dict, Optional

from . import trace as _trace

__all__ = [
    "FLIGHT_SCHEMA_VERSION",
    "FLIGHT_DIR_ENV",
    "FlightRecorder",
    "flight_recorder",
    "configure_flight",
    "register_metrics_registry",
]

#: version of the flight-dump JSON layout (bumped like the analysis JSONs)
FLIGHT_SCHEMA_VERSION = 1

#: set this to a directory to arm file dumps process-wide
FLIGHT_DIR_ENV = "PADDLE_TPU_FLIGHT_DIR"

_MAX_NOTES = 64

# per-instance metric registries (serving engines, routers) attached so a
# crash dump freezes THEIR series too, not just the process registry.
# Weak values: a retired engine's registry drops out with the engine.
_EXTRA_REGISTRIES: "weakref.WeakValueDictionary[str, object]" = \
    weakref.WeakValueDictionary()
_registry_seq = itertools.count(1)


def register_metrics_registry(label: str, registry) -> str:
    """Attach ``registry`` to every future flight dump under a unique
    ``label-N`` section (the serving plane registers its per-instance
    registries here). Returns the section name."""
    name = f"{label}-{next(_registry_seq)}"
    _EXTRA_REGISTRIES[name] = registry
    return name


class FlightRecorder:
    """Bounded notes + dump-on-crash over the shared span ring."""

    def __init__(self, directory: Optional[str] = None,
                 max_spans: int = 256, process: Optional[str] = None):
        self._lock = threading.Lock()
        self.directory = directory or os.environ.get(FLIGHT_DIR_ENV) or None
        self.max_spans = int(max_spans)
        self.process = process
        self._notes: Dict[str, object] = {}  # guarded-by: self._lock
        self._seq = 0                        # guarded-by: self._lock
        self.last: Optional[dict] = None      # newest dump (in-memory)
        self.last_path: Optional[str] = None  # where it landed, if on disk

    @property
    def armed(self) -> bool:
        """True when dumps land on disk (a directory is configured)."""
        return self.directory is not None

    def configure(self, directory: Optional[str] = None,
                  max_spans: Optional[int] = None,
                  process: Optional[str] = None) -> "FlightRecorder":
        with self._lock:
            if directory is not None:
                self.directory = directory
            if max_spans is not None:
                self.max_spans = int(max_spans)
            if process is not None:
                self.process = process
        return self

    def note(self, **kv):
        """Pin small context values (step=..., replica=...) into every
        future dump. Bounded: past :data:`_MAX_NOTES` keys new ones are
        dropped (existing keys always update — the hot path is step=N)."""
        with self._lock:
            for k, v in kv.items():
                if k in self._notes or len(self._notes) < _MAX_NOTES:
                    self._notes[k] = v

    def notes(self) -> Dict[str, object]:
        with self._lock:
            return dict(self._notes)

    def dump(self, reason: str, extra: Optional[dict] = None,
             directory: Optional[str] = None) -> Optional[dict]:
        """Freeze the flight snapshot. Returns the document (also kept in
        ``self.last``); writes ``flight_<reason>_<pid>_<seq>.json`` when a
        directory is configured (argument overrides the instance/env one).
        NEVER raises — a recorder failure must not mask the crash."""
        try:
            from .metrics import default_registry

            with self._lock:
                notes = dict(self._notes)
                self._seq += 1
                seq = self._seq
            # exemplars included: flight dumps feed the merge CLI, which
            # renders each bucket's last trace_id beside the span tree
            metrics = {"process": default_registry().to_dict(
                include_exemplars=True)}
            for name, reg in sorted(_EXTRA_REGISTRIES.items()):
                try:
                    metrics[name] = reg.to_dict(include_exemplars=True)
                except Exception:
                    metrics[name] = "failed"
            doc = {
                "schema_version": FLIGHT_SCHEMA_VERSION,
                "reason": str(reason),
                "wall_time": time.time(),
                "pid": os.getpid(),
                "process": self.process or f"pid-{os.getpid()}",
                "step": notes.get("step"),
                "notes": notes,
                "spans": [s.to_dict()
                          for s in _trace.snapshot_spans(self.max_spans)],
                "dropped_spans": _trace.span_ring().dropped,
                "metrics": metrics,
            }
            if extra:
                doc["extra"] = extra
            self.last, self.last_path = doc, None
            out_dir = directory or self.directory
            if out_dir:
                os.makedirs(out_dir, exist_ok=True)
                safe = "".join(c if c.isalnum() or c in "-_" else "_"
                               for c in str(reason))[:64]
                path = os.path.join(
                    out_dir, f"flight_{safe}_{os.getpid()}_{seq}.json")
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(doc, f, indent=1)
                os.replace(tmp, path)
                self.last_path = path
            return doc
        except Exception as e:  # the crash path must survive the recorder
            try:
                warnings.warn(
                    f"flight recorder dump failed ({type(e).__name__}: {e})",
                    RuntimeWarning)
            except Exception:
                pass
            return None


_default = FlightRecorder()


def flight_recorder() -> FlightRecorder:
    """The process-wide recorder (what the built-in crash hooks use)."""
    return _default


def configure_flight(directory: Optional[str] = None,
                     max_spans: Optional[int] = None,
                     process: Optional[str] = None) -> FlightRecorder:
    """Arm the default recorder (file output lands in ``directory``)."""
    return _default.configure(directory=directory, max_spans=max_spans,
                              process=process)
