"""CLI: ``python -m paddle_tpu.observability <cmd> ...``.

Subcommands:

* ``merge -o OUT [--trace-id ID] DUMP [DUMP ...]`` — stitch per-process
  trace/flight/metric dumps into one chrome-trace JSON (open in
  ``ui.perfetto.dev`` or ``chrome://tracing``); histogram exemplars
  render as instant events linking buckets to trace ids.
* ``perf [-o benchmarks/perf_attribution.json]`` — run the trainer step
  and the warmed serving decode on this host and write the scope-level
  roofline attribution artifact (the Pallas target list, ISSUE 9).
* ``bench-diff BENCH_new.json [--baseline PATH]`` — compare one bench
  payload against the committed lineage baseline; exit 1 naming every
  regressed metric (CI gate).
* ``baseline --rebuild [FILES...]`` — regenerate
  ``benchmarks/bench_baseline.json`` from the BENCH_* lineage.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from .merge import merge_files


def _add_merge(sub):
    m = sub.add_parser(
        "merge", help="stitch per-process dumps into one chrome-trace")
    m.add_argument("dumps", nargs="+",
                   help="trace/flight/metrics dump JSON files")
    m.add_argument("-o", "--out", default=None,
                   help="output path (default: stdout)")
    m.add_argument("--trace-id", default=None,
                   help="keep only spans/exemplars of this trace id")


def _add_perf(sub):
    p = sub.add_parser(
        "perf", help="scope-level roofline attribution of the hot paths")
    p.add_argument("-o", "--out", default=None,
                   help="artifact path (default: "
                        "<repo>/benchmarks/perf_attribution.json)")
    p.add_argument("--steps", type=int, default=8,
                   help="timed trainer steps (default 8)")
    p.add_argument("--ticks", type=int, default=16,
                   help="timed decode ticks (default 16)")
    p.add_argument("--top", type=int, default=5,
                   help="ranked rows to print per entry (default 5)")


def _add_bench_diff(sub):
    d = sub.add_parser(
        "bench-diff",
        help="gate one bench payload against the lineage baseline")
    d.add_argument("payload", help="bench JSON (BENCH_rXX.json or raw)")
    d.add_argument("--baseline", default=None,
                   help="baseline path (default: "
                        "benchmarks/bench_baseline.json)")
    d.add_argument("--json", action="store_true",
                   help="print the full verdict as JSON")


def _add_baseline(sub):
    b = sub.add_parser(
        "baseline", help="rebuild the bench baseline from the lineage")
    b.add_argument("--rebuild", action="store_true")
    b.add_argument("files", nargs="*")
    b.add_argument("-o", "--out", default=None)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability",
        description="telemetry-plane tooling")
    sub = parser.add_subparsers(dest="cmd", required=True)
    _add_merge(sub)
    _add_perf(sub)
    _add_bench_diff(sub)
    _add_baseline(sub)
    args = parser.parse_args(argv)

    if args.cmd == "merge":
        try:
            doc = merge_files(args.dumps, out_path=args.out,
                              trace_id=args.trace_id)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        if args.out is None:
            json.dump(doc, sys.stdout, indent=1)
            print()
        else:
            meta = doc.get("metadata", {})
            print(f"wrote {args.out}: {meta.get('n_spans')} spans + "
                  f"{meta.get('n_exemplars', 0)} exemplars from "
                  f"{meta.get('merged_dumps')} dump(s)")
        return 0

    if args.cmd == "perf":
        from .baseline import _repo_root
        from .perf import build_perf_report

        out = args.out or os.path.join(_repo_root(), "benchmarks",
                                       "perf_attribution.json")
        doc = build_perf_report(out_path=out, steps=args.steps,
                                ticks=args.ticks)
        for name, entry in doc["entries"].items():
            rec = entry["reconciliation"]
            print(f"{name}: measured {entry['measured_total_s']:.6f}s, "
                  f"roofline floor {entry['roofline_total_s']:.6f}s, "
                  f"mfu {entry['mfu']}, reconciliation "
                  f"{'OK' if rec['ok'] else 'FAILED'}")
            for row in entry["rows"][:max(args.top, 0)]:
                print(f"  {row['scope']:45s} measured {row['measured_s']:.6f}s"
                      f" roofline {row['roofline_min_s']:.2e}s "
                      f"[{row['bound']}, {row['dominant_prim']}]")
        print(f"wrote {out}")
        return 0 if all(e["reconciliation"]["ok"]
                        for e in doc["entries"].values()) else 1

    if args.cmd == "bench-diff":
        from .baseline import compare, load_baseline

        try:
            with open(args.payload) as f:
                payload = json.load(f)
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        verdict = compare(payload, baseline)
        if args.json:
            json.dump(verdict, sys.stdout, indent=1)
            print()
        if verdict["ok"]:
            print(f"bench-diff OK: {verdict['compared']} metrics within "
                  f"band", file=sys.stderr)
            return 0
        for r in verdict["regressions"]:
            print(f"REGRESSION {r['describe']}", file=sys.stderr)
        return 1

    if args.cmd == "baseline":
        from .baseline import main as baseline_main

        argv2 = (["--rebuild"] if args.rebuild else []) + list(args.files)
        if args.out:
            argv2 += ["-o", args.out]
        return baseline_main(argv2)
    return 2


if __name__ == "__main__":
    sys.exit(main())
