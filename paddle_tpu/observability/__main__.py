"""CLI: ``python -m paddle_tpu.observability merge ...``.

Subcommands:

* ``merge -o OUT [--trace-id ID] DUMP [DUMP ...]`` — stitch per-process
  trace/flight dumps into one chrome-trace JSON (open in
  ``ui.perfetto.dev`` or ``chrome://tracing``).
"""
from __future__ import annotations

import argparse
import json
import sys

from .merge import merge_files


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability",
        description="telemetry-plane tooling")
    sub = parser.add_subparsers(dest="cmd", required=True)
    m = sub.add_parser(
        "merge", help="stitch per-process dumps into one chrome-trace")
    m.add_argument("dumps", nargs="+", help="trace/flight dump JSON files")
    m.add_argument("-o", "--out", default=None,
                   help="output path (default: stdout)")
    m.add_argument("--trace-id", default=None,
                   help="keep only spans of this trace id")
    args = parser.parse_args(argv)

    if args.cmd == "merge":
        try:
            doc = merge_files(args.dumps, out_path=args.out,
                              trace_id=args.trace_id)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        if args.out is None:
            json.dump(doc, sys.stdout, indent=1)
            print()
        else:
            meta = doc.get("metadata", {})
            print(f"wrote {args.out}: {meta.get('n_spans')} spans from "
                  f"{meta.get('merged_dumps')} dump(s)")
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
