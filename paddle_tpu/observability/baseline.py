"""Bench regression watchdog: BENCH_* lineage → baselines → bench-diff.

The ``BENCH_r01..r05`` trajectory (and every future round artifact) is a
machine-readable record of what this repo could do on real hardware — but
until r14 it was curated by hand: nothing CHECKED that a PR regressed
``pipeline_step_ratio`` or serving TTFT. This module closes the loop
(VisualDL's run-over-run comparison, done natively):

* :func:`rebuild` parses the committed ``BENCH_*.json`` lineage into
  per-metric baselines: median over the observed samples plus a noise
  band — ``median ± tolerance`` per metric class, WIDENED to cover the
  observed lineage spread (every historical payload passes its own
  baseline by construction; only genuinely-worse-than-ever results gate).
* :func:`compare` diffs one new bench payload against the baseline and
  names every primary/secondary metric that regressed beyond its band.
* ``python -m paddle_tpu.observability bench-diff BENCH_new.json`` exits 1
  on any regression (CI-runnable); ``bench.py`` runs the same compare as a
  trailing self-check and reports it in the round artifact.

Metric classes (by name pattern, first match wins):

* ``higher`` — throughput-like (tokens/sec, speedup, MFU, goodput,
  pipeline ratio): regress = new below the band floor.
* ``lower`` — latency-like (TTFT, overhead, recovery): regress = new
  above the band ceiling.
* ``magnitude`` — signed zero-is-ideal metrics (drift fractions, est-vs-
  measured deltas): banded on ``abs(value)``, so an improvement TOWARD
  zero from a negative lineage never gates.
* ``count_max`` — must-stay-zero-ish counters (silent drops, dropped
  requests): regress = new exceeds the lineage maximum.
* ``flag`` — booleans (``*_ok``, ``*_within_3x``): regress = was always
  true in the lineage, now false.
* ``info`` — tracked for the record, never gates (configs, wall times of
  box-dependent tooling, byte counts).
"""
from __future__ import annotations

import dataclasses
import glob as _glob
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "BASELINE_SCHEMA_VERSION",
    "DEFAULT_TOLERANCES",
    "flatten_payload",
    "payload_arm",
    "classify_metric",
    "rebuild",
    "load_baseline",
    "compare",
    "default_bench_glob",
    "default_baseline_path",
    "main",
]

BASELINE_SCHEMA_VERSION = 1

#: per-class relative noise tolerance around the lineage median
DEFAULT_TOLERANCES = {"higher": 0.10, "lower": 0.35}
#: extra pad past the observed lineage extreme (measurement noise floor)
LINEAGE_PAD = 0.05

_HIGHER = ("tokens_per_sec", "speedup", "mfu", "goodput", "vs_baseline",
           "attributed_fraction", "pipeline_step_ratio", "_hits",
           "efficiency")
_LOWER = ("overhead", "ttft", "latency", "_ms", "recovery_s",
          "step_seconds", "gap_s")
# signed, zero-is-ideal: banded on |value| (a negative-lineage drift must
# not flag a later PERFECT 0.0 as "above the band ceiling")
_MAGNITUDE = ("drift", "est_vs_measured")
_COUNT_MAX = ("silent_drops", "dropped_requests", "inflight_failures",
              "admitted_killed", "writes_lost",
              # zero-loss streams (r21): a resurrection or migration that
              # duplicates or drops even one token breaks the continuation
              # contract — must stay zero
              "duplicate_tokens", "dropped_tokens",
              # replicated checkpoint plane (r19): a manifest-committed
              # snapshot that cannot be reassembled after disk loss is a
              # durability-contract violation — must stay zero
              "snapshots_lost",
              # concurrency-doctor finding counts (r18): a PR that
              # re-introduces a HIGH/MEDIUM host-race finding regresses
              # past the lineage maximum and gates
              "host_findings_high", "host_findings_medium",
              # determinism-doctor counts (ISSUE 19): a re-introduced
              # HIGH/MEDIUM nondeterminism hazard, or an inject seam left
              # without its two-run replay certificate, gates the same way
              "det_findings_high", "det_findings_medium",
              "det_seams_uncovered",
              # Pallas kernel-doctor counts (ISSUE 20): a broken BlockSpec
              # coverage proof, a dropped f32-accumulator cast, or a
              # registry model past drift tolerance gates identically
              "kernel_findings_high", "kernel_findings_medium")


def classify_metric(name: str, value) -> str:
    if isinstance(value, bool):
        return "flag"
    if not isinstance(value, (int, float)):
        return "info"
    for pat in _COUNT_MAX:
        if pat in name:
            return "count_max"
    for pat in _MAGNITUDE:
        if pat in name:
            return "magnitude"
    for pat in _HIGHER:
        if pat in name:
            return "higher"
    for pat in _LOWER:
        if pat in name:
            return "lower"
    return "info"


def _parsed(doc: dict) -> dict:
    """Accept a raw bench payload OR the round-artifact wrapper that the
    BENCH_rXX.json files use ({"parsed": {...}, "tail": ...})."""
    if "parsed" in doc and isinstance(doc["parsed"], dict):
        return doc["parsed"]
    return doc


def payload_arm(doc: dict) -> str:
    """Which bench arm produced a payload: ``"cpu"`` or ``"tpu"``.
    Metric NAMES are shared across arms but their values are not
    comparable (a CPU smoke run must never band a TPU lineage metric, and
    vice versa), so baselines and compares are arm-segregated. Pre-r15
    payloads carry no ``arm`` key — the historical lineage is on-chip."""
    return str(_parsed(doc).get("arm", "tpu")).lower()


def flatten_payload(doc: dict) -> Dict[str, object]:
    """One flat {metric: value} view of a bench payload: the primary
    metric under its own name, ``vs_baseline``, and every numeric/boolean
    secondary (nested dicts dotted)."""
    p = _parsed(doc)
    flat: Dict[str, object] = {}
    if "metric" in p and isinstance(p.get("value"), (int, float)):
        flat[str(p["metric"])] = p["value"]
    if isinstance(p.get("vs_baseline"), (int, float)):
        flat["vs_baseline"] = p["vs_baseline"]

    def rec(prefix: str, d: dict):
        for k, v in d.items():
            if isinstance(v, dict):
                rec(f"{prefix}{k}.", v)
            elif isinstance(v, (bool, int, float)):
                flat[f"{prefix}{k}"] = v

    sec = p.get("secondary")
    if isinstance(sec, dict):
        rec("", sec)
    return flat


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def default_bench_glob() -> List[str]:
    """The lineage: on-chip round artifacts at the repo root plus any
    CPU-arm artifacts committed under ``benchmarks/`` (arm-tagged payloads
    are segregated by :func:`payload_arm` at rebuild time)."""
    root = _repo_root()
    return sorted(_glob.glob(os.path.join(root, "BENCH_*.json"))) + sorted(
        _glob.glob(os.path.join(root, "benchmarks", "BENCH_cpu_*.json")))


def default_baseline_path() -> str:
    return os.path.join(_repo_root(), "benchmarks", "bench_baseline.json")


def rebuild(paths: Optional[Sequence[str]] = None,
            tolerances: Optional[Dict[str, float]] = None,
            out_path: Optional[str] = None) -> dict:
    """Parse the BENCH lineage into the versioned baseline document."""
    paths = list(paths) if paths else default_bench_glob()
    if not paths:
        raise ValueError("no BENCH_*.json lineage files found")
    tol = dict(DEFAULT_TOLERANCES)
    tol.update(tolerances or {})
    # arm-segregated: CPU smoke payloads share metric NAMES with the
    # on-chip lineage but not comparable values — each arm gets its own
    # band set ("metrics" = tpu, the historical default; "metrics_cpu")
    samples_by_arm: Dict[str, Dict[str, List]] = {}
    primaries_by_arm: Dict[str, set] = {}
    for path in paths:
        with open(path) as f:
            doc = json.load(f)
        p = _parsed(doc)
        arm = payload_arm(doc)
        samples = samples_by_arm.setdefault(arm, {})
        primaries = primaries_by_arm.setdefault(arm, set())
        if "metric" in p:
            primaries.add(str(p["metric"]))
        for name, value in flatten_payload(doc).items():
            samples.setdefault(name, []).append(value)
    doc = {
        "schema_version": BASELINE_SCHEMA_VERSION,
        "generated_by": "paddle_tpu.observability.baseline --rebuild",
        "source_files": [os.path.basename(p) for p in paths],
        "tolerances": tol,
        "lineage_pad": LINEAGE_PAD,
        "metrics": _build_metrics(samples_by_arm.get("tpu", {}),
                                  primaries_by_arm.get("tpu", set()), tol),
    }
    if "cpu" in samples_by_arm:
        doc["metrics_cpu"] = _build_metrics(
            samples_by_arm["cpu"], primaries_by_arm.get("cpu", set()), tol)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
    return doc


def _build_metrics(samples: Dict[str, List], primaries: set,
                   tol: Dict[str, float]) -> dict:
    metrics = {}
    for name, values in sorted(samples.items()):
        cls = classify_metric(name, values[0])
        entry = {"class": cls, "n": len(values), "values": values,
                 "primary": name in primaries}
        if cls == "flag":
            entry["expect_true"] = all(bool(v) for v in values)
        elif cls == "count_max":
            entry["max"] = max(float(v) for v in values)
        elif cls == "magnitude":
            # banded on |value| with the lower-class tolerance: only a
            # magnitude GROWTH past the band gates; sign and direction
            # toward zero are always improvements
            vs = sorted(abs(float(v)) for v in values)
            median = vs[len(vs) // 2]
            entry["median"] = median
            entry["tolerance"] = tol["lower"]
            entry["band_hi"] = max(median * (1 + tol["lower"]),
                                   vs[-1] * (1 + LINEAGE_PAD))
        elif cls in ("higher", "lower"):
            vs = sorted(float(v) for v in values)
            median = vs[len(vs) // 2]
            entry["median"] = median
            entry["tolerance"] = tol[cls]
            # sign-aware widening: subtract/add |v|*frac instead of
            # multiplying (a negative extreme times 1+pad moves the bound
            # the WRONG way — e.g. a drift lineage of [-0.05, -0.01]
            # would band its own best sample out)
            if cls == "higher":
                # band floor: median - tol, widened past the worst sample
                # so the lineage itself always passes
                entry["band_lo"] = min(
                    median - abs(median) * tol[cls],
                    vs[0] - abs(vs[0]) * LINEAGE_PAD)
            else:
                entry["band_hi"] = max(
                    median + abs(median) * tol[cls],
                    vs[-1] + abs(vs[-1]) * LINEAGE_PAD)
        metrics[name] = entry
    return metrics


def load_baseline(path: Optional[str] = None) -> dict:
    with open(path or default_baseline_path()) as f:
        doc = json.load(f)
    if doc.get("schema_version") != BASELINE_SCHEMA_VERSION:
        raise ValueError(
            f"unsupported baseline schema {doc.get('schema_version')!r} "
            f"(want {BASELINE_SCHEMA_VERSION})")
    return doc


@dataclasses.dataclass
class Regression:
    metric: str
    cls: str
    value: float
    bound: float
    median: Optional[float] = None
    primary: bool = False

    def describe(self) -> str:
        arrow = {"higher": "<", "lower": ">", "count_max": ">",
                 "magnitude": "|value| >", "flag": "!="}.get(self.cls, "?")
        tag = "PRIMARY " if self.primary else ""
        med = f" (lineage median {self.median:g})" if self.median else ""
        return (f"{tag}{self.metric}: {self.value:g} {arrow} "
                f"band {self.bound:g}{med}")


def compare(payload: dict, baseline: dict) -> dict:
    """Diff one bench payload against the baseline. Returns a JSON-ready
    verdict: regressed metrics (most severe first: primaries lead),
    how many metrics were compared, and which baseline metrics the
    payload no longer reports (informational — a renamed metric must not
    silently drop out of the watchdog)."""
    flat = flatten_payload(payload)
    # arm-matched bands: a CPU payload is judged only against CPU-arm
    # baselines (empty verdict when the lineage has none yet)
    if payload_arm(payload) == "cpu":
        metrics = baseline.get("metrics_cpu", {})
    else:
        metrics = baseline.get("metrics", {})
    regressions: List[Regression] = []
    compared = 0
    type_changed: List[str] = []
    for name, entry in metrics.items():
        if name not in flat:
            continue
        value = flat[name]
        cls = entry.get("class", "info")
        if cls == "info":
            continue
        primary = bool(entry.get("primary"))
        if cls == "flag":
            compared += 1
            if entry.get("expect_true") and not bool(value):
                regressions.append(Regression(
                    metric=name, cls=cls, value=float(bool(value)),
                    bound=1.0, primary=primary))
            continue
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            # a metric whose TYPE changed is NOT compared — surface it
            # with the missing ones rather than counting it as checked
            type_changed.append(name)
            continue
        compared += 1
        value = float(value)
        if cls == "count_max" and value > entry["max"]:
            regressions.append(Regression(
                metric=name, cls=cls, value=value, bound=entry["max"],
                primary=primary))
        elif cls == "magnitude" and abs(value) > entry["band_hi"]:
            regressions.append(Regression(
                metric=name, cls=cls, value=value, bound=entry["band_hi"],
                median=entry.get("median"), primary=primary))
        elif cls == "higher" and value < entry["band_lo"]:
            regressions.append(Regression(
                metric=name, cls=cls, value=value, bound=entry["band_lo"],
                median=entry.get("median"), primary=primary))
        elif cls == "lower" and value > entry["band_hi"]:
            regressions.append(Regression(
                metric=name, cls=cls, value=value, bound=entry["band_hi"],
                median=entry.get("median"), primary=primary))
    regressions.sort(key=lambda r: (not r.primary, r.metric))
    missing = sorted(set(
        n for n, e in metrics.items()
        if e.get("class") != "info" and n not in flat) | set(type_changed))
    return {
        "ok": not regressions,
        "compared": compared,
        "regressions": [dataclasses.asdict(r) | {"describe": r.describe()}
                        for r in regressions],
        "missing_metrics": missing,
    }


# ---------------------------------------------------------------------------
def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python -m paddle_tpu.observability.baseline --rebuild [FILES...]``
    (also mounted as the ``baseline`` / ``bench-diff`` subcommands of
    ``python -m paddle_tpu.observability``)."""
    import argparse
    import sys

    parser = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.baseline",
        description="bench lineage baselines")
    parser.add_argument("--rebuild", action="store_true",
                        help="regenerate the baseline from the lineage")
    parser.add_argument("files", nargs="*",
                        help="BENCH_*.json lineage (default: repo root)")
    parser.add_argument("-o", "--out", default=None,
                        help=f"output path (default: "
                             f"{default_baseline_path()})")
    args = parser.parse_args(argv)
    if not args.rebuild:
        parser.error("nothing to do (pass --rebuild)")
    out = args.out or default_baseline_path()
    doc = rebuild(args.files or None, out_path=out)
    print(f"wrote {out}: {len(doc['metrics'])} metrics from "
          f"{len(doc['source_files'])} lineage files", file=sys.stderr)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
