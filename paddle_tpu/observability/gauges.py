"""Predicted-vs-actual gauges: the r9/r10 analyzer as a RUNTIME component.

Two live series per trainer, both cheap enough for the hot loop:

* **MFU** — the r10 cost model (:func:`analysis.cost.graph_cost`) prices
  the trainer's jitted step ONCE (flops per step, per device); dividing by
  the measured step wall time and the device's peak bf16 flops gives a
  live model-flops-utilization gauge — the same accounting bench.py pins,
  but continuously, from the real program instead of the 6N formula.
* **HBM drift** — the r10 liveness estimator's peak/resident prediction
  sits next to a ``jax.live_arrays()`` census as ``predicted``/``actual``
  gauges plus a drift fraction: the estimator's 15% acceptance bar,
  watchable in production instead of only in the bench artifact.

:class:`TrainerTelemetry` wraps a :class:`~..distributed.parallel_trainer
.ParallelTrainer`; ``prime()`` runs the static analysis (trace-time cost,
once), ``step()`` times the hot path (host wall time between dispatches —
back-to-back dispatch converges to device step time under XLA's async
queue), ``refresh_hbm()`` reads the census. All series land in a
:class:`~.metrics.MetricsRegistry` (default: the process registry), so the
training-side exporter serves them to Prometheus unchanged.
"""
from __future__ import annotations

import time
from typing import Dict, Optional

__all__ = ["device_peak_flops_bf16", "TrainerTelemetry"]

#: peak bf16 FLOP/s per chip by device generation (bench.py's table)
_PEAK_FLOPS_BF16 = {
    "v6e": 918e12, "v6": 918e12,
    "v5e": 197e12, "v5litepod": 197e12, "v5 lite": 197e12,
    "v5p": 459e12,
    "v4": 275e12,
    "v3": 123e12,
    "v2": 45e12,
}


def device_peak_flops_bf16(device=None) -> float:
    """Peak bf16 FLOP/s of ``device`` (default: jax.devices()[0]); assumes
    v5e-class when the kind is unknown (CPU arms report MFU against it so
    the gauge is populated, not meaningful — same convention as bench)."""
    import jax

    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, val in _PEAK_FLOPS_BF16.items():
        if key in kind:
            return val
    return 197e12


class TrainerTelemetry:
    """Live MFU + predicted-vs-actual HBM gauges for one trainer."""

    def __init__(self, trainer, registry=None, peak_flops: Optional[float]
                 = None, name: str = "trainer"):
        from .metrics import default_registry, log_buckets

        self.trainer = trainer
        self.name = name
        self.registry = registry or default_registry()
        self.peak_flops = (float(peak_flops) if peak_flops
                           else device_peak_flops_bf16())
        self.flops_per_step: Optional[float] = None
        self.predicted_peak_bytes: Optional[int] = None
        self.predicted_resident_bytes: Optional[int] = None
        self._last_return: Optional[float] = None
        self._steps = 0
        self._guard = None          # TraceGuard on the priced jit step
        self._priced_shapes = None  # (x.shape, y.shape) the flops price
        self.reprices = 0
        self.reprice_errors = 0
        r = self.registry
        self._g_mfu = r.gauge(
            "train_mfu", "model flops utilization (cost-model flops / "
            "measured step time / device peak)", ("trainer",))
        self._g_flops = r.gauge(
            "train_step_flops", "static cost-model flops per train step "
            "per device", ("trainer",))
        self._h_step = r.histogram(
            "train_step_seconds", "train step wall time",
            ("trainer",), buckets=log_buckets(1e-4, 128.0))
        self._c_steps = r.counter(
            "train_steps_total", "train steps dispatched", ("trainer",))
        self._g_hbm_pred = r.gauge(
            "train_hbm_predicted_peak_bytes",
            "liveness-estimator predicted per-device peak HBM", ("trainer",))
        self._g_hbm_live = r.gauge(
            "train_hbm_live_bytes",
            "jax.live_arrays() census at last refresh", ("trainer",))
        self._g_hbm_drift = r.gauge(
            "train_hbm_drift_frac",
            "live census / predicted steady-state residency - 1",
            ("trainer",))
        self._c_reprices = r.counter(
            "train_telemetry_reprices_total",
            "MFU re-pricings after an observed step recompile", ("trainer",))

    # -- static side (once) --------------------------------------------
    def prime(self, x, y) -> "TrainerTelemetry":
        """Price the jitted step with the r10 analyzers: flops per step
        (MFU numerator) and predicted peak/resident HBM. ``x``/``y`` are
        one representative batch (shapes only — nothing is executed)."""
        import jax.numpy as jnp

        from ..analysis.cost import graph_cost
        from ..analysis.graph import AnalysisTarget
        from ..analysis.memory import estimate_memory
        from ..random import split_key
        from ..tensor import Tensor

        tr = self.trainer
        if tr._jit_step is None:
            tr._build()
        xb = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        yb = y._data if isinstance(y, Tensor) else jnp.asarray(y)
        self._priced_shapes = (tuple(xb.shape), tuple(yb.shape))
        lr = jnp.asarray(float(tr.optimizer.get_lr()), jnp.float32)
        args = (tr.params, tr.opt_state, tr.buffers, xb, yb, split_key(),
                tr.scale_state, tr.sentinel_state, lr)
        mesh_axes = {str(k): int(v) for k, v in tr.mesh.shape.items()}
        target = AnalysisTarget(f"telemetry_{self.name}", tr._jit_step,
                                args, mesh_axes=mesh_axes)
        cost = graph_cost(target.graph(), mesh_axes)
        self.flops_per_step = float(cost.flops)
        self._g_flops.set(self.flops_per_step, trainer=self.name)
        est = estimate_memory(target)
        self.predicted_peak_bytes = int(est.peak_bytes)
        self.predicted_resident_bytes = int(est.resident_bytes)
        self._g_hbm_pred.set(self.predicted_peak_bytes, trainer=self.name)
        # arm the recompile hook: the r9 TraceGuard's cache probe tells us
        # when the jit compiles a NEW program (reshaped batch, rebuild) —
        # the priced flops would silently go stale otherwise (r14 fix)
        from ..analysis.traceguard import TraceGuard

        if self._guard is None or self._guard._fn is not tr._jit_step:
            self._guard = TraceGuard(tr._jit_step,
                                     name=f"telemetry_{self.name}")
        self._guard.poll()  # absorb the current cache size — not a miss
        return self

    # -- hot path -------------------------------------------------------
    def step(self, x, y):
        """``trainer.step`` with step-time + MFU observation. Wall time is
        measured return-to-return: with async dispatch the host is back-
        pressured by the device queue, so the steady-state gap IS the
        device step time (the first gap is dispatch-only and skipped).

        Recompile invalidation (r14): after every step the r9 TraceGuard
        cache probe is polled; when the jit compiled a new program (a
        reshaped batch re-traces), the step is RE-PRICED with this batch's
        shapes instead of reporting MFU against stale flops, and the
        recompiled step's wall time (trace + compile, not execution) is
        excluded from the step histogram."""
        t0 = time.perf_counter()
        loss = self.trainer.step(x, y)
        now = time.perf_counter()
        prev = self._last_return
        self._last_return = now
        self._steps += 1
        self._c_steps.inc(trainer=self.name)
        recompiled = self._poll_recompile(x, y)
        dt = now - (prev if prev is not None and prev > t0 - 120.0 else t0)
        if self._steps > 1 and not recompiled:
            self.observe_step(dt)
        if recompiled:
            # the reprice itself (re-trace + liveness estimate) ran AFTER
            # `now` was stamped — re-stamp so the NEXT step's
            # return-to-return gap doesn't absorb the pricing wall time
            self._last_return = time.perf_counter()
        return loss

    def _poll_recompile(self, x, y) -> bool:
        """True when the observed jit step compiled a new program this
        call. Re-prices when the compile changes the priced shapes (a
        reshaped batch); the PRIMING compile itself — the first executed
        step, whose shapes the price already covers — only skips the
        timing observation (trace + compile wall time is not a step)."""
        fn = getattr(self.trainer, "_jit_step", None)
        if fn is None or self._guard is None:
            return False
        rebuilt = self._guard._fn is not fn
        if not rebuilt and not self._guard.poll():
            return False
        shapes = (tuple(getattr(x, "shape", ())),
                  tuple(getattr(y, "shape", ())))
        if rebuilt or shapes != self._priced_shapes:
            try:
                self.prime(x, y)
                self.reprices += 1
                self._c_reprices.inc(trainer=self.name)
            except Exception:  # pricing must never break the train loop
                self.reprice_errors += 1
                # re-arm the probe on the CURRENT jit even though pricing
                # failed: without this, a rebuilt trainer whose pricing
                # raises would re-run the full-trace prime on EVERY step
                # and suppress step observation forever — stale-but-live
                # gauges plus one counted error beat a retry storm
                from ..analysis.traceguard import TraceGuard

                if self._guard._fn is not fn:
                    self._guard = TraceGuard(fn,
                                             name=f"telemetry_{self.name}")
                self._guard.poll()
        return True

    def observe_step(self, seconds: float):
        """Record one measured step time and refresh the MFU gauge (use
        directly when the loop times itself)."""
        self._h_step.observe(float(seconds), trainer=self.name)
        if self.flops_per_step and seconds > 0:
            self._g_mfu.set(
                self.flops_per_step / (float(seconds) * self.peak_flops),
                trainer=self.name)

    # -- census side -----------------------------------------------------
    def refresh_hbm(self) -> Dict[str, float]:
        """``jax.live_arrays()`` census next to the prediction: sets the
        live gauge and the drift fraction (census / predicted residency -
        1; the estimator's steady-state number is the comparable one —
        the transient peak exists only inside a step)."""
        import jax

        live = sum(int(a.nbytes) for a in jax.live_arrays())
        self._g_hbm_live.set(live, trainer=self.name)
        out = {"live_bytes": float(live)}
        if self.predicted_resident_bytes:
            drift = live / self.predicted_resident_bytes - 1.0
            self._g_hbm_drift.set(drift, trainer=self.name)
            out["predicted_resident_bytes"] = float(
                self.predicted_resident_bytes)
            out["predicted_peak_bytes"] = float(
                self.predicted_peak_bytes or 0)
            out["drift_frac"] = drift
        return out

    def report(self) -> Dict:
        """Host-side summary of the live gauges (JSON-ready)."""
        return {
            "mfu": self._g_mfu.value(trainer=self.name),
            "flops_per_step": self.flops_per_step,
            "step_seconds_p50": self._h_step.percentile(
                50, trainer=self.name),
            "step_seconds_p95": self._h_step.percentile(
                95, trainer=self.name),
            "steps": self._steps,
            "hbm_predicted_peak_bytes": self.predicted_peak_bytes,
            "hbm_predicted_resident_bytes": self.predicted_resident_bytes,
            "hbm_live_bytes": self._g_hbm_live.value(trainer=self.name),
            "hbm_drift_frac": self._g_hbm_drift.value(trainer=self.name),
            "reprices": self.reprices,
            "reprice_errors": self.reprice_errors,
        }
