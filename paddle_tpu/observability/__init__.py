"""Unified telemetry plane (ISSUE 7).

One coherent observability surface over the islands earlier rounds built
(r6 profiler scopes, r8 ServingMetrics, r10 analysis JSONs, r11 router
probes):

* :mod:`.trace` — distributed request tracing: trace IDs minted at the
  router, propagated via HTTP headers, spans in a bounded ring buffer,
  chrome-trace export;
* :mod:`.metrics` — counters / gauges / log-bucketed histograms with
  Prometheus text exposition and a training-side HTTP exporter;
* :mod:`.gauges` — predicted-vs-actual: live MFU (cost-model flops over
  measured step time) and HBM drift (liveness estimate vs
  ``jax.live_arrays()``) — the analyzer as a runtime component;
* :mod:`.flight` — crash flight recorder: the span ring + metrics frozen
  to a versioned JSON snapshot on sentinel halt, SIGTERM, engine tick
  failure, and router-confirmed replica death;
* :mod:`.merge` — ``python -m paddle_tpu.observability merge`` stitches
  multi-process dumps into one timeline by trace ID;
* :mod:`.perf` — the perf doctor (r14): scope-level roofline attribution
  fusing the r6 scopes, r10 cost model, and measured wall time into the
  ranked MFU-gap table (``python -m paddle_tpu.observability perf``);
* :mod:`.baseline` — bench regression watchdog (r14): BENCH_* lineage →
  per-metric noise-banded baselines → ``bench-diff`` CI gate.

Parity: ``paddle.profiler`` / VisualDL timelines / monitor StatValue
series / the platform profiler from PAPER.md's L0 row (PARITY.md maps the
rows).
"""
from .baseline import compare as bench_compare
from .baseline import load_baseline
from .baseline import rebuild as rebuild_baseline
from .flight import (
    FLIGHT_SCHEMA_VERSION,
    FlightRecorder,
    configure_flight,
    flight_recorder,
)
from .gauges import TrainerTelemetry, device_peak_flops_bf16
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsHTTPServer,
    MetricsRegistry,
    default_registry,
    dump_metrics,
    log_buckets,
    start_http_exporter,
    wants_openmetrics,
    wants_prometheus,
)
from .perf import (
    PERF_SCHEMA_VERSION,
    PerfAttribution,
    attribute,
    build_perf_report,
    device_peak_hbm_bw,
)
from .trace import (
    PARENT_HEADER,
    TRACE_HEADER,
    Span,
    disable_tracing,
    dump_trace,
    enable_tracing,
    event,
    new_trace_id,
    record_span,
    snapshot_spans,
    span,
    to_chrome_trace,
    trace_context,
    tracing_enabled,
)

__all__ = [
    "TRACE_HEADER",
    "PARENT_HEADER",
    "Span",
    "span",
    "event",
    "record_span",
    "trace_context",
    "new_trace_id",
    "enable_tracing",
    "disable_tracing",
    "tracing_enabled",
    "snapshot_spans",
    "dump_trace",
    "to_chrome_trace",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "MetricsHTTPServer",
    "default_registry",
    "log_buckets",
    "start_http_exporter",
    "wants_prometheus",
    "wants_openmetrics",
    "dump_metrics",
    "TrainerTelemetry",
    "device_peak_flops_bf16",
    "FLIGHT_SCHEMA_VERSION",
    "FlightRecorder",
    "flight_recorder",
    "configure_flight",
    "PERF_SCHEMA_VERSION",
    "PerfAttribution",
    "attribute",
    "build_perf_report",
    "device_peak_hbm_bw",
    "bench_compare",
    "load_baseline",
    "rebuild_baseline",
]
