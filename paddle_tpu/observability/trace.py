"""Distributed request tracing: trace IDs, spans, and a bounded ring buffer.

Parity: the reference's observability surface (``paddle.profiler`` +
VisualDL timelines) answers "where did this step spend its time" for ONE
process; production serving needs the cross-process form — "where did this
REQUEST spend its time" as it crosses the router, a replica's admission
queue, the prefill program, and every decode tick. This module is the wire
format for that question:

* **Trace IDs** are minted at the request's entry point (the serving
  router) and propagated through HTTP headers (:data:`TRACE_HEADER` /
  :data:`PARENT_HEADER`) into the replica's scheduler and engine; training
  loops mint one per run.
* **Spans** are host-side wall-clock intervals (name, trace/span/parent
  ids, attrs) recorded into a bounded in-process ring buffer — old spans
  fall off, so a long-running server never grows without bound and the
  flight recorder always has "the last N things that happened".
* **Export** is Perfetto/chrome-trace JSON (``chrome://tracing`` /
  ``ui.perfetto.dev``); :mod:`.merge` stitches dumps from multiple
  processes into one timeline keyed by trace ID.

Zero-perturbation guarantee (the r6/r7 bar, extended to tracing): spans are
PURE HOST bookkeeping. ``span()`` never calls ``jax.named_scope`` and
records NOTHING while jax is tracing a program, so a jitted step compiles
to the identical jaxpr whether tracing is enabled or not (tests pin this
for the trainer and pipeline steps). Disabled (the default), ``span()`` is
one module-flag read.
"""
from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "TRACE_HEADER",
    "PARENT_HEADER",
    "DEADLINE_HEADER",
    "TRACE_SCHEMA_VERSION",
    "Span",
    "SpanRing",
    "span",
    "event",
    "record_span",
    "trace_context",
    "current_trace",
    "new_trace_id",
    "new_span_id",
    "enable_tracing",
    "disable_tracing",
    "tracing_enabled",
    "span_ring",
    "snapshot_spans",
    "spans_for_trace",
    "reset_spans",
    "to_chrome_trace",
    "dump_trace",
]

#: HTTP headers carrying the trace context between router and replicas
TRACE_HEADER = "X-Trace-Id"
PARENT_HEADER = "X-Parent-Span"
#: the client deadline rides the same header family as the trace context:
#: REMAINING seconds at send time (relative — immune to clock skew), so
#: each hop re-derives its local absolute deadline and re-stamps the
#: remainder when it forwards
DEADLINE_HEADER = "X-Deadline-S"

#: version of the trace-dump JSON layout (``dump_trace`` / flight spans)
TRACE_SCHEMA_VERSION = 1

_enabled = False


def new_trace_id() -> str:
    """128-bit random id, 16 hex chars (w3c-traceparent-ish, short form)."""
    # det-ok: trace ids are telemetry-only (w3c semantics want global
    # uniqueness); nothing ordered or replayed keys off them
    return uuid.uuid4().hex[:16]


def new_span_id() -> str:
    # det-ok: span ids are telemetry-only, same contract as trace ids
    return uuid.uuid4().hex[:16]


@dataclasses.dataclass
class Span:
    """One host-side wall-clock interval. ``ts`` is epoch seconds (spans
    from different processes merge on the shared wall clock), ``dur`` is a
    monotonic-clock duration."""

    name: str
    trace_id: Optional[str]
    span_id: str
    parent_id: Optional[str]
    ts: float
    dur: float
    pid: int = dataclasses.field(default_factory=os.getpid)
    tid: str = ""
    attrs: Dict = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "ts": self.ts,
            "dur": self.dur,
            "pid": self.pid,
            "tid": self.tid,
            "attrs": dict(self.attrs),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Span":
        return cls(name=d["name"], trace_id=d.get("trace_id"),
                   span_id=d.get("span_id", ""),
                   parent_id=d.get("parent_id"), ts=float(d["ts"]),
                   dur=float(d.get("dur", 0.0)), pid=int(d.get("pid", 0)),
                   tid=str(d.get("tid", "")), attrs=dict(d.get("attrs", {})))


class SpanRing:
    """Thread-safe bounded span buffer (oldest spans fall off)."""

    def __init__(self, max_spans: int = 8192):
        self._lock = threading.Lock()
        # guarded-by: self._lock
        self._ring: "deque[Span]" = deque(maxlen=int(max_spans))
        # spans that fell off the ring (bounded-loss gauge)
        self.dropped = 0  # guarded-by: self._lock

    @property
    def max_spans(self) -> int:
        # maxlen is immutable after construction — safe bare read
        # hostrace: ok(host-guarded-by)
        return self._ring.maxlen or 0

    def record(self, s: Span):
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(s)

    def snapshot(self, last: Optional[int] = None) -> List[Span]:
        with self._lock:
            spans = list(self._ring)
        return spans if last is None else spans[-int(last):]

    def clear(self):
        with self._lock:
            self._ring.clear()
            self.dropped = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)


_ring = SpanRing()

#: (trace_id, span_id) of the innermost open span in this task/thread
_ctx: "contextvars.ContextVar[Optional[Tuple[str, Optional[str]]]]" = \
    contextvars.ContextVar("paddle_tpu_trace_ctx", default=None)


def span_ring() -> SpanRing:
    return _ring


def enable_tracing(max_spans: Optional[int] = None):
    """Arm span collection. ``max_spans`` resizes the ring (and clears it)."""
    global _enabled, _ring
    if max_spans is not None and int(max_spans) != _ring.max_spans:
        _ring = SpanRing(int(max_spans))
    _enabled = True


def disable_tracing():
    global _enabled
    _enabled = False


def tracing_enabled() -> bool:
    return _enabled


def snapshot_spans(last: Optional[int] = None) -> List[Span]:
    return _ring.snapshot(last)


def spans_for_trace(trace_id: str) -> List[Span]:
    """All ring spans belonging to one trace — the span tree an exemplar's
    ``trace_id`` points at (the pull side of the r14 exemplar join)."""
    return [s for s in _ring.snapshot() if s.trace_id == trace_id]


def reset_spans():
    _ring.clear()


def _in_jax_trace() -> bool:
    """True while jax is tracing a program — spans must record nothing
    there (the jaxpr-identity guarantee); reuses the r6 probe."""
    from ..profiler.scope import _tracing

    return _tracing()


def current_trace() -> Optional[Tuple[str, Optional[str]]]:
    """(trace_id, span_id) of the innermost open span, or None."""
    return _ctx.get()


@contextlib.contextmanager
def trace_context(trace_id: str, parent_id: Optional[str] = None):
    """Install a trace context for the current thread/task — spans opened
    inside inherit ``trace_id`` and parent onto ``parent_id`` (the receive
    side of header propagation)."""
    token = _ctx.set((trace_id, parent_id))
    try:
        yield
    finally:
        _ctx.reset(token)


@contextlib.contextmanager
def span(name: str, *, trace_id: Optional[str] = None,
         parent_id: Optional[str] = None, **attrs):
    """``with span("serving.route", replica=addr) as sp:`` — time a region
    into the ring. Yields the :class:`Span` (its ``span_id`` is the parent
    handle for child spans / header propagation; ``attrs`` may be added to
    while open). Inherits trace/parent from the ambient context when not
    given. No-op (yields None) when tracing is disabled or jax is tracing.
    """
    if not _enabled or _in_jax_trace():
        yield None
        return
    inherited = _ctx.get()
    if trace_id is None and inherited is not None:
        trace_id = inherited[0]
        if parent_id is None:
            parent_id = inherited[1]
    s = Span(name=name, trace_id=trace_id, span_id=new_span_id(),
             parent_id=parent_id, ts=time.time(), dur=0.0,
             tid=threading.current_thread().name, attrs=dict(attrs))
    # trace-less spans still nest (parent via context) — a training loop
    # without a minted trace id keeps its step ⊃ checkpoint_save tree
    token = _ctx.set((trace_id, s.span_id))
    t0 = time.perf_counter()
    try:
        yield s
    finally:
        s.dur = time.perf_counter() - t0
        _ctx.reset(token)
        _ring.record(s)


def record_span(name: str, *, ts: float, dur: float,
                trace_id: Optional[str] = None,
                parent_id: Optional[str] = None,
                attrs: Optional[Dict] = None) -> Optional[Span]:
    """Record a retrospective span with explicit timing (e.g. queue wait:
    the interval is only known once the request leaves the queue). Inherits
    the ambient trace context when no explicit ids are given (profiler
    ``scope`` regions nest under the enclosing request/step span). Returns
    the span (None when disabled / inside a jax trace)."""
    if not _enabled or _in_jax_trace():
        return None
    if trace_id is None:
        inherited = _ctx.get()
        if inherited is not None:
            trace_id = inherited[0]
            if parent_id is None:
                parent_id = inherited[1]
    s = Span(name=name, trace_id=trace_id, span_id=new_span_id(),
             parent_id=parent_id, ts=float(ts), dur=float(dur),
             tid=threading.current_thread().name, attrs=dict(attrs or {}))
    _ring.record(s)
    return s


def event(name: str, *, trace_id: Optional[str] = None,
          parent_id: Optional[str] = None, **attrs) -> Optional[Span]:
    """Zero-duration marker span (rank failure, breaker flip, ...)."""
    if not _enabled or _in_jax_trace():
        return None
    inherited = _ctx.get()
    if trace_id is None and inherited is not None:
        trace_id = inherited[0]
        if parent_id is None:
            parent_id = inherited[1]
    s = Span(name=name, trace_id=trace_id, span_id=new_span_id(),
             parent_id=parent_id, ts=time.time(), dur=0.0,
             tid=threading.current_thread().name, attrs=dict(attrs))
    _ring.record(s)
    return s


# -- export -----------------------------------------------------------------
def to_chrome_trace(spans: Sequence, process_names: Optional[Dict[int, str]]
                    = None) -> dict:
    """Chrome-trace/Perfetto JSON from spans (:class:`Span` or their
    dicts): complete ("X") events in microseconds, pid/tid preserved so a
    merged multi-process dump renders as parallel tracks."""
    events = []
    tids: Dict[Tuple[int, str], int] = {}
    for s in spans:
        d = s.to_dict() if isinstance(s, Span) else dict(s)
        key = (int(d.get("pid", 0)), str(d.get("tid", "")))
        tid = tids.setdefault(key, len(tids) + 1)
        args = {k: v for k, v in (d.get("attrs") or {}).items()}
        if d.get("trace_id"):
            args["trace_id"] = d["trace_id"]
        if d.get("span_id"):
            args["span_id"] = d["span_id"]
        if d.get("parent_id"):
            args["parent_id"] = d["parent_id"]
        events.append({
            "name": d["name"],
            "ph": "X",
            "ts": float(d["ts"]) * 1e6,
            "dur": float(d.get("dur", 0.0)) * 1e6,
            "pid": int(d.get("pid", 0)),
            "tid": tid,
            "args": args,
        })
    events.sort(key=lambda e: e["ts"])
    meta = []
    for (pid, tname), tid in sorted(tids.items(), key=lambda kv: kv[1]):
        if tname:
            meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tid, "args": {"name": tname}})
    for pid, pname in sorted((process_names or {}).items()):
        meta.append({"name": "process_name", "ph": "M", "pid": int(pid),
                     "tid": 0, "args": {"name": pname}})
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def dump_trace(path: Optional[str] = None, process: Optional[str] = None,
               last: Optional[int] = None) -> dict:
    """Versioned JSON dump of the current ring (one process's record; feed
    several to ``python -m paddle_tpu.observability merge``)."""
    doc = {
        "schema_version": TRACE_SCHEMA_VERSION,
        "process": process or f"pid-{os.getpid()}",
        "pid": os.getpid(),
        "wall_time": time.time(),
        "dropped_spans": _ring.dropped,
        "spans": [s.to_dict() for s in _ring.snapshot(last)],
    }
    if path is not None:
        with open(path, "w") as f:
            json.dump(doc, f, indent=1)
    return doc
