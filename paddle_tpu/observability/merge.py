"""Multi-process trace stitching: N dumps → one chrome-trace timeline.

``python -m paddle_tpu.observability merge -o out.json a.json b.json ...``

Inputs are the versioned JSON dumps this package writes — trace dumps
(:func:`.trace.dump_trace`) AND flight-recorder dumps (both carry a
``spans`` list + ``pid``/``process``). Spans ride wall-clock timestamps,
so records from a router process and its replica processes line up on the
shared clock; ``--trace-id`` filters to one request's spans across every
process (the "where did this request spend its time" view).
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence

from .trace import to_chrome_trace

__all__ = ["load_dump", "merge_dumps", "merge_files"]


def load_dump(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "spans" not in doc:
        raise ValueError(f"{path}: not a paddle_tpu trace/flight dump "
                         f"(no 'spans' list)")
    return doc


def merge_dumps(dumps: Sequence[dict],
                trace_id: Optional[str] = None) -> dict:
    """One chrome-trace document from several process dumps. Span pids
    default to the dump's pid (older spans carry their own); process
    names become chrome metadata so tracks are labelled."""
    spans: List[dict] = []
    process_names: Dict[int, str] = {}
    n_dropped = 0
    for doc in dumps:
        pid = int(doc.get("pid", 0))
        name = str(doc.get("process", "") or f"pid-{pid}")
        process_names[pid] = name
        n_dropped += int(doc.get("dropped_spans", 0) or 0)
        for s in doc.get("spans", ()):
            d = dict(s)
            d.setdefault("pid", pid)
            if trace_id is not None and d.get("trace_id") != trace_id:
                continue
            spans.append(d)
    out = to_chrome_trace(spans, process_names=process_names)
    out["metadata"] = {
        "merged_dumps": len(dumps),
        "n_spans": len(spans),
        "dropped_spans_total": n_dropped,
        "trace_id_filter": trace_id,
    }
    return out


def merge_files(paths: Sequence[str], out_path: Optional[str] = None,
                trace_id: Optional[str] = None) -> dict:
    doc = merge_dumps([load_dump(p) for p in paths], trace_id=trace_id)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1)
    return doc
