"""Multi-process trace stitching: N dumps → one chrome-trace timeline.

``python -m paddle_tpu.observability merge -o out.json a.json b.json ...``

Inputs are the versioned JSON dumps this package writes — trace dumps
(:func:`.trace.dump_trace`), flight-recorder dumps (``spans`` +
``metrics``), and metric dumps (:func:`.metrics.dump_metrics`). Spans ride
wall-clock timestamps, so records from a router process and its replica
processes line up on the shared clock; exemplar-bearing histograms (r14)
render each bucket's last exemplar as an instant event carrying its
``trace_id``, so a p99 TTFT bucket points INTO the span tree next to it.
``--trace-id`` filters both spans and exemplars to one request across
every process. A dump with neither ``spans`` nor ``metrics`` is an error
(never silently skipped).
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence

from .trace import to_chrome_trace

__all__ = ["load_dump", "merge_dumps", "merge_files", "exemplar_events"]


def load_dump(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or (
            "spans" not in doc and "metrics" not in doc):
        raise ValueError(f"{path}: not a paddle_tpu trace/flight/metrics "
                         f"dump (no 'spans' or 'metrics' section)")
    return doc


def _iter_metric_sections(doc: dict):
    """Metric registries in a dump: a metrics dump has ONE under
    ``metrics``; a flight dump may carry several labelled sections."""
    m = doc.get("metrics")
    if not isinstance(m, dict):
        return
    # either {metric_name: {type, values}} directly, or {section: {...}}
    if all(isinstance(v, dict) and "type" in v for v in m.values()):
        yield "", m
        return
    for section, series in m.items():
        if isinstance(series, dict):
            yield str(section), series


def exemplar_events(doc: dict, pid: int,
                    trace_id: Optional[str] = None) -> List[dict]:
    """Chrome-trace instant events for every histogram exemplar in a
    metric dump — each links a bucket (``le``) to the last ``trace_id``
    observed into it."""
    events: List[dict] = []
    for section, series in _iter_metric_sections(doc):
        for mname, m in series.items():
            if not isinstance(m, dict) or m.get("type") != "histogram":
                continue
            values = m.get("values")
            if not isinstance(values, dict):
                continue
            # unlabelled histograms carry exemplars at top level;
            # labelled ones nest one dict per label set
            sets = ([("", values)] if "exemplars" in values or "count"
                    in values else list(values.items()))
            for labelstr, v in sets:
                for le, ex in (v.get("exemplars") or {}).items():
                    if trace_id is not None and \
                            ex.get("trace_id") != trace_id:
                        continue
                    name = f"{mname}_bucket[le={le}]"
                    if section:
                        name = f"{section}/{name}"
                    events.append({
                        "name": name,
                        "ph": "i",
                        "s": "p",
                        "ts": float(ex.get("ts", 0.0)) * 1e6,
                        "pid": pid,
                        "tid": 0,
                        "args": {"trace_id": ex.get("trace_id"),
                                 "value": ex.get("value"),
                                 "labels": labelstr},
                    })
    return events


def merge_dumps(dumps: Sequence[dict],
                trace_id: Optional[str] = None) -> dict:
    """One chrome-trace document from several process dumps. Span pids
    default to the dump's pid (older spans carry their own); process
    names become chrome metadata so tracks are labelled; histogram
    exemplars become instant events on their process's track."""
    spans: List[dict] = []
    process_names: Dict[int, str] = {}
    n_dropped = 0
    extra_events: List[dict] = []
    n_exemplars = 0
    for doc in dumps:
        pid = int(doc.get("pid", 0))
        name = str(doc.get("process", "") or f"pid-{pid}")
        process_names[pid] = name
        n_dropped += int(doc.get("dropped_spans", 0) or 0)
        for s in doc.get("spans", ()):
            d = dict(s)
            d.setdefault("pid", pid)
            if trace_id is not None and d.get("trace_id") != trace_id:
                continue
            spans.append(d)
        ex = exemplar_events(doc, pid, trace_id=trace_id)
        n_exemplars += len(ex)
        extra_events.extend(ex)
    out = to_chrome_trace(spans, process_names=process_names)
    if extra_events:
        out["traceEvents"] = out["traceEvents"] + sorted(
            extra_events, key=lambda e: e["ts"])
    out["metadata"] = {
        "merged_dumps": len(dumps),
        "n_spans": len(spans),
        "n_exemplars": n_exemplars,
        "dropped_spans_total": n_dropped,
        "trace_id_filter": trace_id,
    }
    return out


def merge_files(paths: Sequence[str], out_path: Optional[str] = None,
                trace_id: Optional[str] = None) -> dict:
    doc = merge_dumps([load_dump(p) for p in paths], trace_id=trace_id)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1)
    return doc
