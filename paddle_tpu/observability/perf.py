"""Perf doctor: scope-level roofline attribution (ISSUE 9 tentpole).

The r12 telemetry plane answers *that* a step took 12 ms and the r10 cost
model answers *how much work* the whole program does; neither says WHICH
region eats the MFU gap. This module fuses three earlier layers into one
attribution table:

* the r6 ``profiler.scope`` names embedded in eqn ``name_stack`` metadata
  (normalized by :func:`analysis.graph.scope_components` so forward and
  backward halves of a region share one row),
* the r10 per-eqn roofline cost model, sliced per scope by
  :func:`analysis.cost.scope_costs`,
* measured wall time — host spans from the r6 :class:`TimerRegistry` /
  r12 trace ring where a scope is host-visible, the measured whole-step
  time apportioned by roofline share where it is not (in-graph scopes
  execute inside one compiled program; the device does not expose their
  individual times, so apportioned rows are explicitly tagged
  ``measured_source`` and never pretend to be direct measurements).

Per scope the report carries: measured time, roofline-minimum time
(``max(flops/peak_flops, bytes/peak_bw)``), efficiency (roofline / measured
— the scope's share of the achievable), a memory- vs compute-bound verdict,
and the dominant primitive. Ranked by absolute MFU-gap seconds, the table
is the canonical target list for the planned Pallas-kernel round (ROADMAP
item 2): the top rows name exactly the scopes a fused kernel must move.

``python -m paddle_tpu.observability perf`` runs the trainer step and the
warmed serving decode tick on this host and writes
``benchmarks/perf_attribution.json`` (``schema_version`` 1). The
scope-summed flops/bytes reconcile with the whole-graph
:func:`~paddle_tpu.analysis.cost.graph_cost` totals exactly (pinned within
1% by the acceptance test — same walk, same multipliers).
"""
from __future__ import annotations

import dataclasses
import json
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "PERF_SCHEMA_VERSION",
    "device_peak_hbm_bw",
    "ScopeRow",
    "PerfAttribution",
    "attribute",
    "measured_from_timers",
    "measured_from_ring",
    "build_perf_report",
]

#: version of the ``perf_attribution.json`` layout
PERF_SCHEMA_VERSION = 1

#: peak HBM bandwidth (bytes/s) per chip by device generation — the
#: roofline's memory leg (same table family as the bf16 flops in .gauges)
_PEAK_HBM_BW = {
    "v6e": 1.64e12, "v6": 1.64e12,
    "v5e": 8.19e11, "v5litepod": 8.19e11, "v5 lite": 8.19e11,
    "v5p": 2.765e12,
    "v4": 1.2288e12,
    "v3": 9.0e11,
    "v2": 7.0e11,
}


def device_peak_hbm_bw(device=None) -> float:
    """Peak HBM bytes/s of ``device`` (default: jax.devices()[0]); assumes
    v5e-class when unknown — the CPU arm's convention, matching
    :func:`~.gauges.device_peak_flops_bf16` so CPU-arm efficiencies are
    populated (comparable round-over-round) rather than meaningful."""
    import jax

    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, val in _PEAK_HBM_BW.items():
        if key in kind:
            return val
    return 8.19e11


@dataclasses.dataclass
class ScopeRow:
    """One ranked row of the attribution table (JSON-ready via to_dict)."""

    scope: str
    flops: float
    bytes_accessed: float
    comm_bytes: float
    n_eqns: int
    intensity: float
    bound: str                      # memory-bound | compute-bound
    dominant_prim: Optional[str]
    compute_s: float                # flops / peak_flops
    memory_s: float                 # bytes / peak_bw
    roofline_min_s: float           # max(compute_s, memory_s)
    measured_s: Optional[float] = None
    measured_source: Optional[str] = None
    efficiency: Optional[float] = None   # roofline_min_s / measured_s
    gap_s: Optional[float] = None        # measured_s - roofline_min_s
    estimated: bool = False

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        for k in ("flops", "bytes_accessed", "comm_bytes"):
            d[k] = float(d[k])
        d["intensity"] = round(self.intensity, 3)
        return d


@dataclasses.dataclass
class PerfAttribution:
    """Scope rows + whole-graph totals + the reconciliation check."""

    rows: List[ScopeRow]
    peak_flops: float
    peak_bw: float
    measured_total_s: Optional[float]
    graph_cost: dict                 # whole-graph GraphCost.to_dict()
    reconciliation: dict             # scope-sum vs graph totals

    @property
    def roofline_total_s(self) -> float:
        return sum(r.roofline_min_s for r in self.rows)

    @property
    def mfu(self) -> Optional[float]:
        """Whole-entry model-flops-utilization over the measured time."""
        if not self.measured_total_s or self.measured_total_s <= 0:
            return None
        flops = sum(r.flops for r in self.rows)
        return flops / (self.measured_total_s * self.peak_flops)

    def top(self, n: int = 5) -> List[ScopeRow]:
        return self.rows[:n]

    def to_dict(self, max_rows: Optional[int] = None) -> dict:
        rows = self.rows if max_rows is None else self.rows[:max_rows]
        return {
            "peak_flops": self.peak_flops,
            "peak_hbm_bw": self.peak_bw,
            "measured_total_s": self.measured_total_s,
            "roofline_total_s": self.roofline_total_s,
            "mfu": (round(self.mfu, 6) if self.mfu is not None else None),
            "graph_cost": self.graph_cost,
            "reconciliation": self.reconciliation,
            "rows": [r.to_dict() for r in rows],
        }


def measured_from_timers(prefix: str = "") -> Dict[str, float]:
    """Measured per-scope seconds from the r6 host TimerRegistry: name →
    mean seconds per recorded span (scopes that bracket a dispatch on the
    host side — ``serving.prefill``, ``serving.decode_step``, ...)."""
    from ..profiler.scope import timer_registry

    return timer_registry.averages(prefix)


def measured_from_ring(names: Optional[Sequence[str]] = None,
                       ) -> Dict[str, float]:
    """Measured per-scope seconds from the r12 trace ring: span name →
    mean duration over the ring's current contents (optionally filtered to
    ``names``). The ring sees the same host intervals as the timers when
    tracing is armed, plus request spans (``serving.route`` trees)."""
    from .trace import snapshot_spans

    want = set(names) if names is not None else None
    total: Dict[str, float] = {}
    count: Dict[str, int] = {}
    for s in snapshot_spans():
        if want is not None and s.name not in want:
            continue
        total[s.name] = total.get(s.name, 0.0) + float(s.dur)
        count[s.name] = count.get(s.name, 0) + 1
    return {n: total[n] / count[n] for n in total}


def _match_measured(scope: Tuple[str, ...],
                    measured: Dict[str, float]) -> Optional[str]:
    """Deepest scope-path component with a direct measurement, or None."""
    for comp in reversed(scope):
        if comp in measured:
            return comp
    return None


def attribute(target_or_graph, *, mesh_axes: Optional[Dict[str, int]] = None,
              peak_flops: Optional[float] = None,
              peak_bw: Optional[float] = None,
              ridge: Optional[float] = None,
              measured: Optional[Dict[str, float]] = None,
              measured_total_s: Optional[float] = None) -> PerfAttribution:
    """Build the ranked scope-attribution table for one entry point.

    ``target_or_graph`` is an :class:`~paddle_tpu.analysis.graph
    .AnalysisTarget` or a built :class:`DefUseGraph`. ``measured`` maps
    host-visible scope names to measured seconds per execution
    (:func:`measured_from_timers` / :func:`measured_from_ring`);
    ``measured_total_s`` is the whole-entry measured wall time (one step /
    one decode tick). Join semantics:

    * a row whose path contains a measured scope name takes its share of
      that scope's measured budget, split by roofline-minimum share among
      the rows under the same name (``measured_source`` =
      ``"scope-timer"``);
    * remaining rows split the RESIDUAL of ``measured_total_s`` (whole
      minus directly-measured scopes) the same way (``"step-apportioned"``
      — per-scope efficiency then inherits the entry-level gap, which is
      exactly what a host without per-op device timing can honestly say);
    * with no measurement at all, ``measured_s`` stays None and the table
      still ranks by roofline share.
    """
    from ..analysis.cost import (
        DEFAULT_RIDGE_FLOPS_PER_BYTE,
        graph_cost,
        scope_costs,
    )
    from .gauges import device_peak_flops_bf16

    graph = (target_or_graph.graph()
             if hasattr(target_or_graph, "graph") else target_or_graph)
    if mesh_axes is None and hasattr(target_or_graph, "mesh_axes"):
        mesh_axes = target_or_graph.mesh_axes or None
    peak_flops = float(peak_flops) if peak_flops else device_peak_flops_bf16()
    peak_bw = float(peak_bw) if peak_bw else device_peak_hbm_bw()
    ridge = float(ridge) if ridge else DEFAULT_RIDGE_FLOPS_PER_BYTE
    measured = dict(measured or {})

    table = scope_costs(graph, mesh_axes)
    gc = graph_cost(graph, mesh_axes)

    rows: List[ScopeRow] = []
    for sc in table.values():
        compute_s = sc.flops / peak_flops
        memory_s = sc.bytes_accessed / peak_bw
        rows.append(ScopeRow(
            scope=sc.name, flops=sc.flops,
            bytes_accessed=sc.bytes_accessed, comm_bytes=sc.comm_bytes,
            n_eqns=sc.n_eqns, intensity=sc.intensity, bound=sc.bound(ridge),
            dominant_prim=sc.dominant_prim, compute_s=compute_s,
            memory_s=memory_s, roofline_min_s=max(compute_s, memory_s),
            estimated=sc.estimated))

    # --- measured join -----------------------------------------------------
    groups: Dict[Optional[str], List[ScopeRow]] = {}
    for row, sc in zip(rows, table.values()):
        groups.setdefault(_match_measured(sc.scope, measured), []).append(row)

    def _apportion(group: List[ScopeRow], budget: float, source: str):
        share_total = sum(r.roofline_min_s for r in group)
        for r in group:
            share = (r.roofline_min_s / share_total if share_total > 0
                     else 1.0 / len(group))
            r.measured_s = budget * share
            r.measured_source = source

    direct_total = 0.0
    for key, group in groups.items():
        if key is None:
            continue
        budget = float(measured[key])
        direct_total += budget
        _apportion(group, budget, "scope-timer")
    unmatched = groups.get(None, [])
    if unmatched and measured_total_s is not None:
        residual = max(float(measured_total_s) - direct_total, 0.0)
        _apportion(unmatched, residual, "step-apportioned")
    for r in rows:
        if r.measured_s is not None:
            r.gap_s = r.measured_s - r.roofline_min_s
            r.efficiency = (r.roofline_min_s / r.measured_s
                            if r.measured_s > 0 else None)

    rows.sort(key=lambda r: (-(r.gap_s if r.gap_s is not None else -1.0),
                             -r.roofline_min_s))

    # --- reconciliation: rows must SUM to the whole-graph totals -----------
    sflops = sum(r.flops for r in rows)
    sbytes = sum(r.bytes_accessed for r in rows)
    flops_frac = abs(sflops - gc.flops) / gc.flops if gc.flops else 0.0
    bytes_frac = (abs(sbytes - gc.bytes_accessed) / gc.bytes_accessed
                  if gc.bytes_accessed else 0.0)
    reconciliation = {
        "scope_flops": sflops, "graph_flops": gc.flops,
        "flops_frac": round(flops_frac, 6),
        "scope_bytes": sbytes, "graph_bytes": gc.bytes_accessed,
        "bytes_frac": round(bytes_frac, 6),
        "ok": bool(flops_frac <= 0.01 and bytes_frac <= 0.01),
    }
    return PerfAttribution(
        rows=rows, peak_flops=peak_flops, peak_bw=peak_bw,
        measured_total_s=measured_total_s, graph_cost=gc.to_dict(),
        reconciliation=reconciliation)


# ===========================================================================
# the CLI workhorse: run both shipped hot paths on THIS host and attribute
# ===========================================================================
def _median(xs: Sequence[float]) -> float:
    s = sorted(xs)
    return s[len(s) // 2]


def _trainer_entry(on_tpu: bool, steps: int, peak_flops: float,
                   peak_bw: float) -> dict:
    """Measure + attribute the eager ParallelTrainer step (bench configs:
    gpt3-350m on TPU, the tiny gpt2-small smoke shapes on CPU)."""
    import gc

    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as paddle
    from ..analysis.graph import AnalysisTarget
    from ..distributed.env import clear_mesh, init_mesh
    from ..distributed.parallel_trainer import ParallelTrainer
    from ..models.gpt import (
        GPTForPretraining,
        GPTPretrainingCriterion,
        gpt_config,
    )
    from ..optimizer.optimizers import AdamW
    from ..random import split_key

    if on_tpu:
        name, batch, seq, warmup = "gpt3-350m", 8, 1024, 3
        overrides = {}
    else:
        name, batch, seq, warmup = "gpt2-small", 4, 32, 2
        overrides = dict(vocab_size=256, hidden_size=64, num_layers=2,
                         num_attention_heads=4, max_position_embeddings=64)
    cfg = gpt_config(name, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0, **overrides)
    paddle.seed(0)
    clear_mesh()
    gc.collect()
    init_mesh({"dp": 1})
    model = GPTForPretraining(cfg)
    crit = GPTPretrainingCriterion(cfg)
    opt = AdamW(learning_rate=1e-4, parameters=model.parameters(),
                moment_dtype="bfloat16")
    trainer = ParallelTrainer(model, lambda out, y: crit(out, y), opt,
                              dp_axis=None,
                              compute_dtype="bfloat16" if on_tpu else None)
    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(
        rng.integers(0, cfg.vocab_size, (batch, seq)).astype("int32"))
    for _ in range(warmup):
        loss = trainer.step(ids, ids)
    float(np.asarray(loss._data))
    per_step = []
    for _ in range(max(steps, 1)):
        t0 = time.perf_counter()
        loss = trainer.step(ids, ids)
        float(np.asarray(loss._data))  # block: measured = full step wall
        per_step.append(time.perf_counter() - t0)
    measured_s = _median(per_step)

    args = (trainer.params, trainer.opt_state, trainer.buffers,
            ids._data, ids._data, split_key(), trainer.scale_state,
            trainer.sentinel_state, jnp.asarray(1e-4, jnp.float32))
    target = AnalysisTarget("trainer_step", trainer._jit_step, args,
                            mesh_axes={"dp": 1})
    att = attribute(target, mesh_axes={"dp": 1}, peak_flops=peak_flops,
                    peak_bw=peak_bw, measured=measured_from_timers("trainer."),
                    measured_total_s=measured_s)
    entry = att.to_dict()
    entry["config"] = {"model": name, "batch": batch, "seq": seq,
                       "steps_timed": len(per_step)}
    entry["per_step_s"] = [round(t, 6) for t in per_step]
    del trainer, model
    gc.collect()
    return entry


def _serving_entry(on_tpu: bool, ticks: int, peak_flops: float,
                   peak_bw: float, attn_impl: str = "xla") -> dict:
    """Measure + attribute ONE warmed decode tick of the continuous-
    batching engine (all slots active — the serving hot path)."""
    import gc

    import numpy as np

    import paddle_tpu as paddle
    from ..analysis.graph import AnalysisTarget
    from ..distributed.env import clear_mesh, init_mesh
    from ..models.gpt import GPTForPretraining, gpt_config
    from ..serving import ContinuousBatchingEngine, Request

    if on_tpu:
        name, s_len, n_slots, buckets = "gpt3-350m", 512, 8, [64, 128]
        lo, hi = 16, 120
        overrides = {}
    else:
        name, s_len, n_slots, buckets = "gpt2-small", 64, 4, [8, 16]
        lo, hi = 3, 8
        overrides = dict(vocab_size=256, hidden_size=64, num_layers=2,
                         num_attention_heads=4, max_position_embeddings=64)
    cfg = gpt_config(name, hidden_dropout_prob=0.0,
                     attention_dropout_prob=0.0, **overrides)
    paddle.seed(0)
    clear_mesh()
    gc.collect()
    init_mesh({"dp": 1})
    model = GPTForPretraining(cfg)
    model.eval()
    rng = np.random.default_rng(0)
    eng = ContinuousBatchingEngine(model, max_seq_len=s_len, n_slots=n_slots,
                                   prefill_buckets=buckets,
                                   max_queue=4 * n_slots,
                                   attn_impl=attn_impl)
    prompts = [rng.integers(0, cfg.vocab_size, (int(l),)).astype("int32")
               for l in rng.integers(lo, hi, size=2 * n_slots)]
    # warm every bucket + the decode step (compiles out of the timed ticks)
    eng.generate_batch([Request(p, max_new_tokens=4) for p in prompts])

    # fill every slot, absorb admissions, then time ticks individually:
    # each timed tick is one batched decode step over n_slots active slots
    reqs = [eng.submit(p, max_new_tokens=ticks + 8)
            for p in prompts[:n_slots]]
    eng.step_once()  # admissions + prefills + first decode
    per_tick = []
    for _ in range(max(ticks, 1)):
        t0 = time.perf_counter()
        eng.step_once()
        per_tick.append(time.perf_counter() - t0)
    measured_s = _median(per_tick)
    for r in reqs:  # drain: bounded by max_new_tokens
        while not r.done:
            if not eng.step_once():
                break

    # layout-agnostic: the engine hands back args matching its compiled
    # step (paged default since ISSUE 11 — the attribution table ranks
    # the serving.paged_attn gather row)
    target = AnalysisTarget("serving_decode", eng._step_jit,
                            eng._step_args_example())
    att = attribute(target, peak_flops=peak_flops, peak_bw=peak_bw,
                    measured=measured_from_timers("serving.decode"),
                    measured_total_s=measured_s)
    entry = att.to_dict()
    entry["config"] = {"model": name, "n_slots": n_slots,
                       "max_seq_len": s_len, "buckets": list(buckets),
                       "ticks_timed": len(per_tick),
                       "attn_impl": attn_impl}
    entry["per_tick_s"] = [round(t, 6) for t in per_tick]
    entry["host_timers"] = {
        k: round(v, 6) for k, v in measured_from_timers("serving.").items()}
    del eng, model
    gc.collect()
    return entry


def build_perf_report(out_path: Optional[str] = None, steps: int = 8,
                      ticks: int = 16) -> dict:
    """Run both shipped hot paths (trainer step, warmed serving decode) on
    this host, attribute each, and return/write the versioned artifact.

    The mesh and profiler-timer state are restored afterwards so the
    report can run inside a live process (tests call it in-process)."""
    import jax

    from ..distributed.env import get_mesh, set_mesh
    from ..profiler.scope import (
        disable_timers,
        enable_timers,
        timer_registry,
        timers_enabled,
    )
    from .gauges import device_peak_flops_bf16

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    peak_flops = device_peak_flops_bf16(dev)
    peak_bw = device_peak_hbm_bw(dev)
    from ..random import (
        get_rng_state,
        get_rng_state_tracker,
        set_rng_state,
    )

    prev_mesh = get_mesh()
    had_timers = timers_enabled()
    # borrow the shared registry: start clean so the measured join sees
    # only THIS report's spans, and hand the caller's accumulated state
    # back afterwards (a live serving/training process must not lose its
    # measurements to a diagnostic run). The global RNG is restored the
    # same way — the entry builders paddle.seed(0) for reproducible
    # artifacts, which must not replay a live run's dropout/sampling
    # streams from seed 0 afterwards.
    saved_timers = timer_registry.save_state()
    saved_rng = get_rng_state()
    saved_tracker = get_rng_state_tracker().get_states_tracker()
    timer_registry.reset()
    enable_timers()  # host-visible scopes land in the TimerRegistry join
    entries = {}
    try:
        entries["trainer_step"] = _trainer_entry(on_tpu, steps, peak_flops,
                                                 peak_bw)
        entries["serving_decode"] = _serving_entry(on_tpu, ticks, peak_flops,
                                                   peak_bw)
        # r20 kernel-on arm: the paged flash-decode Pallas kernel in place
        # of the XLA gather; the committed artifact keeps both rows so the
        # serving.paged_attn roofline verdict is comparable within one file.
        # Fresh timers so the arm's measured join sees only its own spans
        # (both arms record under the same serving.* scope names).
        timer_registry.reset()
        entries["serving_decode_pallas"] = _serving_entry(
            on_tpu, ticks, peak_flops, peak_bw, attn_impl="pallas")
    finally:
        if not had_timers:
            disable_timers()
        timer_registry.restore_state(saved_timers)
        set_rng_state(saved_rng)
        get_rng_state_tracker().set_states_tracker(saved_tracker)
        set_mesh(prev_mesh)
    doc = {
        "schema_version": PERF_SCHEMA_VERSION,
        "generated_by": "python -m paddle_tpu.observability perf",
        "device": {"platform": dev.platform,
                   "kind": getattr(dev, "device_kind", "")},
        "peak_flops": peak_flops,
        "peak_hbm_bw": peak_bw,
        "entries": entries,
    }
    if out_path:
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1)
    return doc
