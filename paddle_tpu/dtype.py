"""Dtype model for paddle_tpu.

Mirrors the reference's ``proto::VarType`` dtype surface
(/root/reference/paddle/fluid/framework/framework.proto:92-120) but is a thin
mapping onto numpy/jax dtypes — on TPU there is no separate typed-tensor IR;
XLA carries dtype through the HLO.
"""
from __future__ import annotations

import builtins

import jax.numpy as jnp
import numpy as np

__all__ = [
    "dtype",
    "uint8",
    "int8",
    "int16",
    "int32",
    "int64",
    "float16",
    "bfloat16",
    "float32",
    "float64",
    "complex64",
    "complex128",
    "bool",
    "convert_dtype",
    "to_jax_dtype",
    "is_floating_point_dtype",
    "is_integer_dtype",
    "iinfo",
    "finfo",
]


class dtype:
    """A framework dtype: a named wrapper around a numpy/jax dtype.

    Compares equal to its string name, to numpy dtypes and to other ``dtype``
    instances so user code can say ``x.dtype == 'float32'`` like the reference
    API allows.
    """

    __slots__ = ("name", "np_dtype")

    _registry: dict = {}

    def __init__(self, name: str, np_dtype):
        self.name = name
        self.np_dtype = np.dtype(np_dtype)
        dtype._registry[name] = self

    def __repr__(self):
        return f"paddle_tpu.{self.name}"

    def __hash__(self):
        return hash(self.name)

    def __eq__(self, other):
        if isinstance(other, dtype):
            return self.name == other.name
        if isinstance(other, str):
            return other in (self.name, f"paddle.{self.name}", f"paddle_tpu.{self.name}")
        try:
            return np.dtype(other) == self.np_dtype and _np_name(other) == self.name
        except TypeError:
            return NotImplemented

    def __ne__(self, other):
        eq = self.__eq__(other)
        return NotImplemented if eq is NotImplemented else not eq


def _np_name(other) -> str:
    # bfloat16 is not a numpy builtin; ml_dtypes gives it name 'bfloat16'
    return np.dtype(other).name


uint8 = dtype("uint8", np.uint8)
int8 = dtype("int8", np.int8)
int16 = dtype("int16", np.int16)
int32 = dtype("int32", np.int32)
int64 = dtype("int64", np.int64)
float16 = dtype("float16", np.float16)
bfloat16 = dtype("bfloat16", jnp.bfloat16)
float32 = dtype("float32", np.float32)
float64 = dtype("float64", np.float64)
complex64 = dtype("complex64", np.complex64)
complex128 = dtype("complex128", np.complex128)
bool = dtype("bool", np.bool_)  # noqa: A001 - mirrors paddle.bool

_ALIASES = {
    "float": "float32",
    "double": "float64",
    "half": "float16",
    "int": "int32",
    "long": "int64",
    "bool_": "bool",
    "bfloat16": "bfloat16",
    "uint16": "bfloat16",  # the reference stores bf16 as VarType.BF16/uint16
}


def convert_dtype(d) -> str:
    """Normalize any dtype spec (str / numpy / jax / framework dtype) to its
    canonical string name. Parity: python/paddle/fluid/data_feeder.py convert_dtype."""
    if isinstance(d, dtype):
        return d.name
    if isinstance(d, str):
        name = d.split(".")[-1]
        name = _ALIASES.get(name, name)
        if name in dtype._registry:
            return name
        raise ValueError(f"Unknown dtype string: {d!r}")
    if d is float:
        return "float32"
    if d is int:
        return "int64"
    if d is builtins.bool:
        return "bool"
    try:
        name = np.dtype(d).name
    except TypeError as e:
        raise ValueError(f"Cannot convert {d!r} to a dtype") from e
    name = _ALIASES.get(name, name)
    if name in dtype._registry:
        return name
    raise ValueError(f"Unsupported dtype: {d!r}")


def to_paddle_dtype(d) -> dtype:
    return dtype._registry[convert_dtype(d)]


def to_jax_dtype(d):
    """Resolve any dtype spec to the jnp dtype used on device."""
    return dtype._registry[convert_dtype(d)].np_dtype


def is_floating_point_dtype(d) -> builtins.bool:
    name = convert_dtype(d)
    return name in ("float16", "bfloat16", "float32", "float64")


def is_integer_dtype(d):
    name = convert_dtype(d)
    return name in ("uint8", "int8", "int16", "int32", "int64")


def iinfo(d):
    return np.iinfo(to_jax_dtype(d))


def finfo(d):
    return jnp.finfo(to_jax_dtype(d))
