"""Admission control for the continuous-batching engine.

Parity: Paddle Serving's front-end batches requests FCFS into a bounded
task queue (its ``BatchTasks``/dag scheduler) and rejects on overflow; the
TPU-native twist is the **compile-cache bound**: prompts are padded to
power-of-2 length buckets, so over any workload the engine traces at most
``len(buckets)`` prefill programs plus ONE decode-step program — iteration-
level (Orca-style) slot scheduling with a provably bounded program cache
instead of a paged-KV GPU kernel zoo.

Pieces:

* :class:`Request` — one generation request: prompt + per-request sampling
  params + a thread-safe incremental token log (the streaming front-end
  tails it).
* :class:`FCFSScheduler` — bounded FIFO admission queue (reject-with-429
  semantics via :class:`QueueFullError` when full, :class:`SchedulerClosed`
  after drain starts), power-of-2 prefill buckets, and the prefill/decode
  interleave knob ``max_prefills_per_tick`` (how many waiting requests may
  prefill between two decode steps — prefills are the expensive programs,
  so unbounded admission would starve in-flight decodes).
"""
from __future__ import annotations

import itertools
import math
import threading
import time
from collections import deque
from typing import List, Optional, Sequence

import numpy as np

__all__ = [
    "Request",
    "FCFSScheduler",
    "QueueFullError",
    "SchedulerClosed",
    "power_of_two_buckets",
]


class QueueFullError(RuntimeError):
    """Admission queue is at capacity — HTTP 429 Too Many Requests.

    ``retry_after`` (seconds, optional) is the backpressure hint the server
    derives from current throughput and queue depth
    (``ServingMetrics.retry_after_hint``) and ships in the ``Retry-After``
    header; the client re-attaches it here."""

    http_status = 429

    def __init__(self, msg: str = "queue full", retry_after=None):
        super().__init__(msg)
        self.retry_after = None if retry_after is None else float(retry_after)


class SchedulerClosed(RuntimeError):
    """Drain has started; no new admissions — HTTP 503 Service Unavailable."""

    http_status = 503


def power_of_two_buckets(max_prompt_len: int, min_bucket: int = 16) -> List[int]:
    """Power-of-2 prefill buckets covering [1, max_prompt_len]: the compile
    cache holds at most ``len(buckets)`` prefill programs + 1 decode step."""
    if max_prompt_len < 1:
        raise ValueError("max_prompt_len must be >= 1")
    buckets = []
    b = max(1, int(min_bucket))
    while b < max_prompt_len:
        buckets.append(b)
        b *= 2
    buckets.append(int(max_prompt_len))
    return buckets


_req_ids = itertools.count(1)


class Request:
    """One in-flight generation: immutable inputs + a growing token log.

    ``tokens`` holds GENERATED ids only (including the eos token when hit —
    mirroring ``models.generate`` which appends eos before stopping);
    ``result()`` returns prompt + generated. The condition variable makes
    ``wait()``/``iter_tokens()`` safe to call from server threads while the
    engine appends from its loop thread.
    """

    PENDING, RUNNING, DONE, FAILED = "pending", "running", "done", "failed"

    def __init__(self, prompt, max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None, seed: Optional[int] = None,
                 request_id: Optional[str] = None,
                 trace_id: Optional[str] = None,
                 parent_span_id: Optional[str] = None,
                 deadline_s: Optional[float] = None,
                 observed_tokens: Optional[Sequence[int]] = None):
        self.prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.eos_token_id = None if eos_token_id is None else int(eos_token_id)
        self.temperature = float(temperature)
        self.top_k = None if top_k is None else int(top_k)
        self.top_p = None if top_p is None else float(top_p)
        self.seed = seed
        # continuation join (stream resurrection / live migration): tokens
        # this stream ALREADY generated elsewhere. The engine prefills
        # prompt+observed[:-1], fast-forwards the PRNG key chain by
        # len(observed) draws, and resumes decode — bit-identical to the
        # uninterrupted run, so the transcript log starts pre-populated
        self.observed: List[int] = (
            [] if observed_tokens is None
            else [int(t) for t in observed_tokens])
        if len(self.observed) > self.max_new_tokens:
            raise ValueError(
                f"continuation carries {len(self.observed)} observed tokens, "
                f"past its generation limit max_new_tokens="
                f"{self.max_new_tokens}")
        if self.observed and self.temperature > 0.0 and seed is None:
            # without the original seed the key chain cannot be
            # reconstructed — a resumed sampled stream would silently
            # diverge from the uninterrupted trajectory
            raise ValueError(
                "sampled continuation requires an explicit seed (the PRNG "
                "key chain cannot be fast-forwarded without it)")
        self.request_id = request_id or f"req-{next(_req_ids)}"
        # distributed-tracing context: the router mints the trace id and
        # ships it via HTTP headers; a direct submit with tracing armed
        # mints locally so engine-only runs still get request span trees
        if trace_id is None:
            from ..observability import trace as _obs

            if _obs.tracing_enabled():
                trace_id = _obs.new_trace_id()
        self.trace_id = trace_id
        self.parent_span_id = parent_span_id
        self._decode_span_parent: Optional[str] = None  # engine-owned
        # pre-populated with the observed prefix for continuations: eos /
        # max_new_tokens checks, result() and stream replay all see ONE
        # transcript regardless of which replica generated which token
        self.tokens: List[int] = list(self.observed)  # guarded-by: self._cond
        self.state = Request.PENDING     # guarded-by: self._cond
        self.error: Optional[str] = None  # guarded-by: self._cond
        # typed discriminator for failures ("DeadlineExceededError",
        # "ShedError", ...) — clients switch on this, not message prose
        self.error_type: Optional[str] = None  # guarded-by: self._cond
        self.bucket: Optional[int] = None
        self.submitted_at = time.perf_counter()
        # client deadline (propagated as REMAINING seconds via the
        # X-Deadline-S header): absolute on the local monotonic clock —
        # work that cannot start before it is shed from the queue. NaN
        # would compare False against every expiry check and silently
        # disable the deadline the client believes is set — reject it
        if deadline_s is not None and not math.isfinite(float(deadline_s)):
            raise ValueError(f"deadline_s must be finite, got {deadline_s}")
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        self.deadline_at = (None if deadline_s is None
                            else self.submitted_at + float(deadline_s))
        self.submitted_wall = time.time()  # span timestamps are wall-clock
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self._cond = threading.Condition()

    # -- engine side --------------------------------------------------------
    def _append(self, token: int):
        with self._cond:
            if self.first_token_at is None:
                self.first_token_at = time.perf_counter()
            self.tokens.append(int(token))
            self._cond.notify_all()

    def _finish(self, state: str = DONE, error: Optional[str] = None,
                error_type: Optional[str] = None):
        with self._cond:
            self.state = state
            self.error = error
            self.error_type = error_type
            self.finished_at = time.perf_counter()
            self._cond.notify_all()

    # -- continuation join --------------------------------------------------
    @property
    def prefill_len(self) -> int:
        """Tokens the engine must prefill before decode can resume: the
        whole prompt, plus — for a continuation — every observed token but
        the last (whose KV the first resumed decode step writes, exactly
        as the uninterrupted run's step did)."""
        return self.prompt.size + max(len(self.observed) - 1, 0)

    def prefill_ids(self) -> np.ndarray:
        """The continuation-join prefill sequence: ``prompt`` for a fresh
        request, ``prompt + observed[:-1]`` for a continuation (int32 —
        what the chunk programs, radix matching and page tables key on)."""
        if not self.observed:
            return self.prompt
        return np.concatenate(
            [self.prompt,
             np.asarray(self.observed[:-1], dtype=np.int32)])

    @property
    def observed_terminal(self) -> bool:
        """True when the observed transcript already finished generation
        (hit max_new_tokens or eos) — nothing to prefill or decode; the
        engine completes the request at admission."""
        if not self.observed:
            return False
        if len(self.observed) >= self.max_new_tokens:
            return True
        return (self.eos_token_id is not None
                and self.observed[-1] == self.eos_token_id)

    # -- deadline -----------------------------------------------------------
    def deadline_remaining(self) -> Optional[float]:
        if self.deadline_at is None:
            return None
        return self.deadline_at - time.perf_counter()

    def deadline_expired(self) -> bool:
        rem = self.deadline_remaining()
        return rem is not None and rem <= 0

    # -- client side --------------------------------------------------------
    @property
    def done(self) -> bool:
        # a bare read of the state REFERENCE is the documented contract:
        # transitions are monotonic (PENDING->RUNNING->DONE/FAILED) and a
        # stale read only delays the observer one poll
        # hostrace: ok(host-guarded-by)
        return self.state in (Request.DONE, Request.FAILED)

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the request finishes; True when done."""
        deadline = None if timeout is None else time.perf_counter() + timeout
        with self._cond:
            while not self.done:
                rem = None if deadline is None else deadline - time.perf_counter()
                if rem is not None and rem <= 0:
                    return False
                self._cond.wait(rem)
        return True

    def iter_tokens(self, timeout: Optional[float] = None):
        """Yield generated tokens incrementally (the streaming endpoint's
        source); returns when the request finishes."""
        idx = 0
        deadline = None if timeout is None else time.perf_counter() + timeout
        while True:
            with self._cond:
                while idx >= len(self.tokens) and not self.done:
                    rem = (None if deadline is None
                           else deadline - time.perf_counter())
                    if rem is not None and rem <= 0:
                        return
                    self._cond.wait(rem)
                chunk = self.tokens[idx:]
                finished = self.done
                total = len(self.tokens)  # consistent with chunk/finished
            for t in chunk:
                yield t
            idx += len(chunk)
            if finished and idx >= total:
                return

    def result(self) -> np.ndarray:
        """prompt + generated tokens as int64 (models.generate's shape).
        Read-after-done by contract: callers wait() first, and _finish
        publishes under the condition this read pairs with."""
        return np.concatenate(
            [self.prompt.astype(np.int64),
             # hostrace: ok(host-guarded-by)
             np.asarray(self.tokens, dtype=np.int64)])

    def ttft(self) -> Optional[float]:
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.submitted_at


class FCFSScheduler:
    """Bounded FIFO admission queue with bucketed prefill lengths."""

    def __init__(self, buckets: Sequence[int], max_queue: int = 64,
                 max_prefills_per_tick: int = 2):
        if not buckets:
            raise ValueError("need at least one prefill bucket")
        self.buckets = sorted(int(b) for b in buckets)
        self.max_queue = int(max_queue)
        self.max_prefills_per_tick = max(1, int(max_prefills_per_tick))
        # chunked-prefill engines (paged KV layout) admit prompts LONGER
        # than the largest bucket: each chunk is bucketed, not the whole
        # prompt. None = whole-prompt bucketing (the r8 behavior).
        self.bucket_cap: Optional[int] = None
        self._q: deque = deque()  # guarded-by: self._cond
        self._cond = threading.Condition()
        self._closed = False      # guarded-by: self._cond
        # popped by take_admissions but not yet settled into a slot (or
        # retired/failed) by the engine: during a prefill compile these
        # requests are in NEITHER the queue nor a slot, and a drain that
        # trusts depth()+active alone would declare the engine empty
        # mid-prefill and orphan them
        self._in_admission = 0    # guarded-by: self._cond
        # queued requests that CARRY a deadline: lets the per-tick expiry
        # sweep skip the O(queue) walk entirely for deployments that
        # never set deadlines
        self._deadlined = 0       # guarded-by: self._cond

    # -- admission ----------------------------------------------------------
    def bucket_for(self, prompt_len: int) -> int:
        if self.bucket_cap is not None:
            # chunked prefill: only the (capped) first chunk is bucketed;
            # the engine validates total capacity against max_seq_len
            prompt_len = min(int(prompt_len), self.bucket_cap,
                             self.buckets[-1])
        for b in self.buckets:
            if prompt_len <= b:
                return b
        raise ValueError(
            f"prompt length {prompt_len} exceeds the largest prefill bucket "
            f"{self.buckets[-1]}")

    def submit(self, req: Request) -> Request:
        """FCFS enqueue. Raises :class:`SchedulerClosed` after drain started
        and :class:`QueueFullError` at capacity (the server maps these to
        503/429)."""
        # continuations bucket the JOIN length (prompt + observed[:-1]) —
        # that is what the prefill programs will actually run over
        req.bucket = self.bucket_for(req.prefill_len)  # validate first
        with self._cond:
            if self._closed:
                raise SchedulerClosed("scheduler is draining; not admitting")
            if len(self._q) >= self.max_queue:
                raise QueueFullError(
                    f"admission queue full ({self.max_queue})")
            self._q.append(req)
            if req.deadline_at is not None:
                self._deadlined += 1
            self._cond.notify_all()
        return req

    # -- engine side --------------------------------------------------------
    def take_admissions(self, free_slots: int) -> List[Request]:
        """Pop up to min(free_slots, max_prefills_per_tick) requests FCFS —
        the prefill/decode interleaving policy: at most this many prefill
        programs run between two decode steps."""
        out: List[Request] = []
        n = min(int(free_slots), self.max_prefills_per_tick)
        with self._cond:
            while self._q and len(out) < n:
                out.append(self._q.popleft())
            # counted under the SAME lock as the pop: a concurrent
            # metrics read sees each request as queued or in-admission,
            # never neither
            self._in_admission += len(out)
            self._deadlined -= sum(1 for r in out
                                   if r.deadline_at is not None)
        return out

    def shed_oldest(self, n: int) -> List[Request]:
        """Pop up to ``n`` requests OLDEST-first for load shedding (the
        overload policy's mechanism — popped requests are no longer
        queued; the engine fails them visibly). Oldest-first preserves
        goodput under FCFS + deadlines: the head of the queue has burned
        the most of its deadline and is the likeliest to be abandoned or
        already retried by its client."""
        out: List[Request] = []
        with self._cond:
            while self._q and len(out) < int(n):
                out.append(self._q.popleft())
            self._deadlined -= sum(1 for r in out
                                   if r.deadline_at is not None)
        return out

    def sweep_expired(self) -> List[Request]:
        """Remove every queued request whose deadline already elapsed
        (they can never start in time — shedding them early frees queue
        budget for work that can still meet its deadline). O(1) when no
        queued request carries a deadline — the engine calls this every
        tick."""
        out: List[Request] = []
        with self._cond:
            if not self._q or self._deadlined <= 0:
                return out
            keep = deque()
            for req in self._q:
                (out if req.deadline_expired() else keep).append(req)
            if out:
                self._q = keep
                self._deadlined -= len(out)
        return out

    def admission_settled(self, n: int = 1):
        """The engine finished placing ``n`` taken requests (active slot,
        retired at prefill, or failed)."""
        with self._cond:
            self._in_admission = max(0, self._in_admission - int(n))

    def in_admission(self) -> int:
        with self._cond:
            return self._in_admission

    def depth(self) -> int:
        with self._cond:
            return len(self._q)

    def wait_for_work(self, timeout: float = 0.05) -> bool:
        """Engine idle-wait: True when the queue is non-empty."""
        with self._cond:
            if not self._q:
                self._cond.wait(timeout)
            return bool(self._q)

    # -- drain --------------------------------------------------------------
    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed

    def close(self):
        """Stop admitting (graceful drain step 1); queued requests still
        run to completion."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
