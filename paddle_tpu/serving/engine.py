"""Continuous-batching engine — iteration-level scheduling on TPU.

Parity: the reference serves production decoding through AnalysisPredictor's
ZeroCopyRun over exported programs and batches requests in Paddle Serving's
front-end; the scale story ("millions of users") on TPU is **continuous
batching** (Orca, OSDI'22; popularized by vLLM): requests join and leave a
shared decode batch *between* iterations instead of waiting for a full batch
to finish.

TPU-native design — fixed shapes, bounded compile cache, no dynamic kernels.
Two KV layouts, selected by ``kv_layout``:

* ``"paged"`` (default, ISSUE 11): a block-paged KV pool — one fixed
  ``[L, n_pages, H, page_size, D]`` array pair plus a per-slot page table
  padded to ``max_pages_per_slot`` (attention gathers the table's pages
  back into position order and masks past the live length, so the step
  stays ONE jitted program). Pages are allocated lazily (prompt pages at
  admission, decode pages on demand), refcounted, and shared across
  requests through a host-side radix tree over prompt prefixes
  (``serving/paged.py``): a request whose prompt prefix is already
  resident skips that part of prefill entirely, with copy-on-write of the
  final page when the WHOLE prompt is resident. Long prompts prefill in
  page-aligned **chunks** (``prefill_chunk``) interleaved with decode
  ticks, so a 4k-token prompt no longer stalls every in-flight stream.
  Compile cache: at most ``len(chunk_buckets)`` prefill programs + 1
  decode step (asserted by ``trace_count``).
* ``"slot"`` (the r8 fallback, kept for bit-comparison): a monolithic
  ``[L, n_slots, H, S, D]`` cache where every slot pays max-seq-len HBM.

Greedy decoding through either layout is token-for-token identical to
sequential ``models.generate`` (tested), which is what makes continuous
batching — and paging — a pure throughput/memory win, not a quality trade.
"""
from __future__ import annotations

import contextlib
import threading
import time
import weakref
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..observability import trace as obstrace
from .metrics import ServingMetrics
from .paged import TRASH_PAGE, PagePool, PagesExhaustedError, RadixCache
from .scheduler import FCFSScheduler, Request, power_of_two_buckets

__all__ = ["ContinuousBatchingEngine", "MIGRATED_ERROR_TYPE",
           "make_continuation_record", "verify_continuation_record"]

#: ``error_type`` stamped on a request whose stream was exported to another
#: replica (live migration): the id is retired HERE but the stream lives on
#: — routers treat this as "moved", never as a request-level failure
MIGRATED_ERROR_TYPE = "MigratedError"


def _record_crc(record: Dict) -> int:
    import json
    import zlib

    payload = {k: v for k, v in record.items() if k != "crc"}
    blob = json.dumps(payload, sort_keys=True,
                      separators=(",", ":")).encode()
    return zlib.crc32(blob) & 0xFFFFFFFF


def make_continuation_record(req: Request, deadline_remaining=None) -> Dict:
    """CRC-stamped continuation record for one in-flight stream: the full
    transcript + sampling params + key-chain position (= len(tokens)).
    Everything a peer needs to continuation-join the stream bit-identically;
    the CRC covers the canonical JSON so a torn transfer is detected at
    import, mirroring the r19 blob plane's integrity discipline."""
    record = {
        "v": 1,
        "kind": "continuation",
        "request_id": req.request_id,
        "prompt": [int(t) for t in req.prompt],
        "tokens": [int(t) for t in req.tokens],
        "max_new_tokens": int(req.max_new_tokens),
        "eos_token_id": req.eos_token_id,
        "temperature": float(req.temperature),
        "top_k": req.top_k,
        "top_p": req.top_p,
        # the seed the engine ACTUALLY keyed this stream's chain with —
        # a sampled request submitted without one still resumes exactly
        "seed": int(getattr(req, "effective_seed", req.seed or 0)),
        "deadline_remaining": (None if deadline_remaining is None
                               else float(deadline_remaining)),
    }
    record["crc"] = _record_crc(record)
    return record


def verify_continuation_record(record: Dict) -> Dict:
    """Validate a continuation record's shape + CRC; raises ValueError on
    a torn/corrupt/alien payload (the import endpoint maps this to 400)."""
    if not isinstance(record, dict) or record.get("kind") != "continuation":
        raise ValueError("not a continuation record")
    if "crc" not in record or "prompt" not in record or "tokens" not in record:
        raise ValueError("continuation record missing required fields")
    if int(record["crc"]) != _record_crc(record):
        raise ValueError(
            "continuation record CRC mismatch (torn or corrupted transfer)")
    if not record["tokens"]:
        raise ValueError("continuation record carries no observed tokens")
    return record

# Tracing prefill_fn/step_fn temporarily hangs `_gen_cache` off the model's
# attention layers; two engines sharing one model object (multi-replica
# tests, A/B harnesses) must not trace concurrently or the attrs race —
# one trace reads the other's tracers and the tick dies. One lock per
# model, held only while a call may trace (first use of a bucket / step).
_MODEL_TRACE_LOCKS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_MODEL_TRACE_LOCKS_GUARD = threading.Lock()


def _model_trace_lock(model) -> threading.RLock:
    with _MODEL_TRACE_LOCKS_GUARD:
        lock = _MODEL_TRACE_LOCKS.get(model)
        if lock is None:
            lock = _MODEL_TRACE_LOCKS[model] = threading.RLock()
        return lock


class ContinuousBatchingEngine:
    """Request-level serving engine over a fixed-capacity batched KV cache.

    ``model``: an eval-mode learned-position GPTForPretraining (rope needs
    per-slot rotary offsets in buffer mode — not wired, same restriction as
    ``inference.save_for_generation``). ``max_seq_len``: per-slot KV capacity
    S (prompt + generated must fit). ``prefill_buckets``: padded prompt
    lengths; defaults to power-of-2 buckets up to S.

    Paged-layout knobs: ``page_size`` (tokens per KV page), ``n_pages``
    (pool capacity; default fully provisions ``n_slots`` slots — set it
    lower to overcommit and let prefix sharing make up the difference),
    ``prefill_chunk`` (max tokens prefilled per tick for one request; None
    = whole prompt in one program), ``prefix_sharing`` (radix-tree prompt
    reuse on/off).
    """

    def __init__(self, model, max_seq_len: int, n_slots: int = 8,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 scheduler: Optional[FCFSScheduler] = None,
                 metrics: Optional[ServingMetrics] = None,
                 max_queue: int = 64, max_prefills_per_tick: int = 2,
                 cache_dtype: str = "float32",
                 hbm_budget_bytes: Optional[int] = None,
                 admission_gate=None, shed_policy=None,
                 kv_layout: str = "paged", page_size: int = 16,
                 n_pages: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 prefix_sharing: bool = True,
                 attn_impl: str = "xla",
                 kv_dtype: Optional[str] = None,
                 weight_dtype: Optional[str] = None,
                 spec_decode=None):
        import jax.numpy as jnp

        from ..models.gpt import GPTForPretraining

        if not isinstance(model, GPTForPretraining):
            raise TypeError("ContinuousBatchingEngine expects GPTForPretraining")
        cfg = model.gpt.config
        if cfg.position_embedding == "rope":
            raise NotImplementedError(
                "buffer-mode KV cache with rope is not wired "
                "(learned-position configs only)")
        from ..models.generation import _attn_layers

        if kv_layout not in ("paged", "slot"):
            raise ValueError("kv_layout must be 'paged' or 'slot'")
        if attn_impl not in ("xla", "pallas"):
            raise ValueError("attn_impl must be 'xla' or 'pallas'")
        if attn_impl == "pallas" and kv_layout != "paged":
            raise ValueError(
                "attn_impl='pallas' is the paged flash-decode kernel; it "
                "requires kv_layout='paged'")
        self.attn_impl = attn_impl
        model.eval()
        self.model = model
        self.n_slots = int(n_slots)
        self.max_seq_len = int(max_seq_len)
        self.kv_layout = kv_layout
        self._paged = kv_layout == "paged"
        self._layers = cfg.num_layers
        self._heads = cfg.num_attention_heads
        self._head_dim = cfg.head_dim
        self._attns = _attn_layers(model)
        buckets = (list(prefill_buckets) if prefill_buckets is not None
                   else power_of_two_buckets(self.max_seq_len))
        if max(buckets) > self.max_seq_len:
            raise ValueError("prefill bucket exceeds max_seq_len")
        self._cache_dtype = jnp.dtype(cache_dtype)

        # -- quantized inference plane (ISSUE 18) -----------------------
        # kv_dtype="int8": the paged pool stores int8 K/V with per-token
        # f32 absmax scales riding alongside ([L, n_pages, page_size] per
        # half) — quant on scatter-in, dequant on gather/flash read.
        # weight_dtype="int8": the model's Linear weights are loaded as a
        # per-out-channel int8 tree (quantization/ptq.py), dequantized
        # INSIDE the dot (scale-fused int8 dot_general, never an f32
        # weight copy — the extended dtype-promotion rule lints this).
        if kv_dtype is not None and str(kv_dtype) != "int8":
            raise ValueError("kv_dtype must be None (= cache_dtype) or 'int8'")
        self._kv_quant = kv_dtype == "int8"
        if self._kv_quant and kv_layout != "paged":
            raise ValueError("kv_dtype='int8' requires kv_layout='paged'")
        self.kv_dtype = (jnp.dtype(np.int8) if self._kv_quant
                         else self._cache_dtype)
        if weight_dtype is not None and str(weight_dtype) != "int8":
            raise ValueError("weight_dtype must be None or 'int8'")
        self.weight_dtype = weight_dtype
        if weight_dtype == "int8":
            from ..quantization.ptq import quantize_model_weights_

            # idempotent: an already-PTQ'd model (load_quantized) is left
            # untouched; a fresh fp model is weight-quantized in place
            quantize_model_weights_(model)

        # -- paged-layout state (ISSUE 11) ------------------------------
        if self._paged:
            self.page_size = int(page_size)
            if self.page_size < 1:
                raise ValueError("page_size must be >= 1")
            self.max_pages_per_slot = -(-self.max_seq_len // self.page_size)
            per_el = np.dtype(self.kv_dtype).itemsize
            # one page's K+V bytes across all layers — the allocation unit
            self.page_bytes = (2 * self._layers * self._heads
                               * self.page_size * self._head_dim * per_el)
            if self._kv_quant:
                # the per-token f32 scales are part of the layout's cost
                self.page_bytes += 2 * self._layers * self.page_size * 4
            if n_pages is None:
                n_pages = 1 + self.n_slots * self.max_pages_per_slot
            self.n_pages = int(n_pages)
            if self.n_pages < 2:
                raise ValueError("n_pages must be >= 2 (trash + 1)")
            self._pool = PagePool(self.n_pages, page_bytes=self.page_bytes)
            self._radix = (RadixCache(self._pool, self.page_size)
                           if prefix_sharing else None)
            if prefill_chunk is not None:
                prefill_chunk = int(prefill_chunk)
                if prefill_chunk < 1:
                    raise ValueError("prefill_chunk must be >= 1")
            self.prefill_chunk = prefill_chunk
            limit = (prefill_chunk if prefill_chunk is not None
                     else max(buckets))
            self.chunk_buckets = sorted(
                {b for b in buckets if b <= limit} | {limit})
            self._chunk_limit = limit
            self._pool_shape = (self._layers, self.n_pages, self._heads,
                                self.page_size, self._head_dim)
            self._pool_k = jnp.zeros(self._pool_shape, self.kv_dtype)
            self._pool_v = jnp.zeros(self._pool_shape, self.kv_dtype)
            self._scale_shape = (self._layers, self.n_pages, self.page_size)
            if self._kv_quant:
                self._scale_k = jnp.zeros(self._scale_shape, jnp.float32)
                self._scale_v = jnp.zeros(self._scale_shape, jnp.float32)
            self._page_tables = np.zeros(
                (self.n_slots, self.max_pages_per_slot), np.int32)
            # slot -> chunked-prefill progress ({"req", "next", "key",
            # "cow", "t0_span" ...}); a slot here is occupied but not yet
            # decoding
            self._prefill_slots: Dict[int, dict] = {}
            self.cow_pages = 0  # copy-on-write events (metrics)
        else:
            self.page_size = None
            self.prefill_chunk = None
            self.chunk_buckets = list(buckets)
            self._pool = None
            self._radix = None
            self._prefill_slots = {}
            self._cache_shape = (self._layers, self.n_slots, self._heads,
                                 self.max_seq_len, self._head_dim)
            self._kc = jnp.zeros(self._cache_shape, self._cache_dtype)
            self._vc = jnp.zeros(self._cache_shape, self._cache_dtype)

        self.scheduler = scheduler or FCFSScheduler(
            buckets, max_queue=max_queue,
            max_prefills_per_tick=max_prefills_per_tick)
        if self._paged:
            # chunked prefill admits sequences longer than the largest
            # bucket (they split; the paged path ALWAYS runs the chunk
            # loop, capped at max(buckets) without prefill_chunk), so the
            # scheduler buckets only the chunk — this is what lets a
            # continuation join (prompt + observed transcript) re-home
            # onto a replica whose buckets the bare prompt was sized for
            self.scheduler.bucket_cap = self._chunk_limit
        self.metrics = metrics or ServingMetrics()
        self.metrics.n_slots = self.n_slots

        # parameters are frozen for serving: snapshot once
        self._params = {n: p._data for n, p in model.named_parameters()}
        self._buffers = {n: b._data for n, b in model.named_buffers()}

        # per-slot decode-state (host mirrors, shipped to device each tick)
        self._tok = np.zeros((self.n_slots,), np.int32)
        self._pos = np.zeros((self.n_slots,), np.int32)
        self._active = np.zeros((self.n_slots,), bool)
        self._temp = np.zeros((self.n_slots,), np.float32)
        self._topk = np.zeros((self.n_slots,), np.int32)
        self._topp = np.ones((self.n_slots,), np.float32)
        self._keys = np.zeros((self.n_slots, 2), np.uint32)
        self._slots: List[Optional[Request]] = [None] * self.n_slots
        self._seed_counter = 0
        # trace counters: the jitted bodies below run ONLY when jax traces a
        # new program, so these count compiles — the bounded-compile-cache
        # acceptance gauge (len(chunk_buckets) prefills + 1 step)
        self.trace_counts: Dict[str, int] = {"prefill": 0, "step": 0}
        self._step_jit = None
        self._prefill_jit = None
        # intentionally holds across traces (that is its whole job)
        self._trace_lock = _model_trace_lock(model)  # hostrace: blocking-ok
        self._traced_buckets: set = set()  # prefill avals already compiled
        # engine tick mutual exclusion: one tick = compile-if-needed +
        # device step + slot bookkeeping, serialized BY DESIGN — waiters
        # are other tick callers, never request threads
        self._lock = threading.Lock()  # hostrace: blocking-ok
        self._abort = threading.Event()  # crash simulation: loop exits, NO drain
        self._build_programs()
        # speculative decoding (ISSUE 18): a draft model proposes k tokens
        # per tick, the target verifies them in ONE batched step — greedy
        # output is token-for-token identical to the plain engine
        self._spec = None
        if spec_decode is not None:
            if not self._paged:
                raise ValueError(
                    "speculative decoding requires kv_layout='paged'")
            from .spec_decode import SpecDecodeState

            self._spec = SpecDecodeState(self, spec_decode)
        # overload protection (serving/admission.py), both opt-in: the
        # gate prices each request's prefill against an HBM budget with
        # the r10 liveness estimator and (paged) the predicted page-pool
        # watermark; the shed policy bounds queue wait under sustained
        # overload by failing the oldest queued work
        if admission_gate is None and hbm_budget_bytes is not None:
            from .admission import AdmissionGate

            admission_gate = AdmissionGate(self, hbm_budget_bytes)
        self.admission_gate = admission_gate
        self.shed_policy = shed_policy.bind(self) if shed_policy else None

    # -- traced programs ----------------------------------------------------
    def _build_programs(self):
        if self._paged:
            self._build_programs_paged()
        else:
            self._build_programs_slot()

    def _build_programs_slot(self):
        import jax
        import jax.numpy as jnp

        from ..autograd.tape import no_grad
        from ..models.generation import sample_tokens
        from ..ops._primitive import unwrap, wrap
        from ..profiler.scope import scope

        model, attns = self.model, self._attns
        heads, hd, s = self._heads, self._head_dim, self.max_seq_len

        def _forward(params, buffers, ids_t, position_ids_t):
            out, _ = model.functional_call_with_state(
                params, buffers, ids_t, position_ids_t)
            return unwrap(out)

        def prefill_fn(params, buffers, ids, length, slot, key, temp,
                       topk, topp, kc, vc):
            # ids [1, Tb] bucket-padded; length = real prompt length; the
            # causal mask keeps pad positions out of row length-1's logits
            self.trace_counts["prefill"] += 1
            zeros = jnp.zeros((1, heads, s, hd), kc.dtype)
            pos0 = jnp.zeros((1,), jnp.int32)
            for a in attns:
                a._gen_cache = {"mode": "buffer", "k": zeros, "v": zeros,
                                "pos": pos0}
            try:
                with no_grad():
                    logits = _forward(params, buffers, wrap(ids), None)
                ks = jnp.stack([unwrap(a._gen_cache["k"]) for a in attns])
                vs = jnp.stack([unwrap(a._gen_cache["v"]) for a in attns])
            finally:
                for a in attns:
                    if hasattr(a, "_gen_cache"):
                        del a._gen_cache
            z = jnp.zeros((), jnp.int32)
            slot = slot.astype(jnp.int32)
            # the slot row is REPLACED wholesale (pad rows beyond the prompt
            # are zeros, overwritten again as decode advances), so freed
            # slots can't leak K/V into their successors
            kc = jax.lax.dynamic_update_slice(kc, ks.astype(kc.dtype),
                                              (z, slot, z, z, z))
            vc = jax.lax.dynamic_update_slice(vc, vs.astype(vc.dtype),
                                              (z, slot, z, z, z))
            last = jax.lax.dynamic_slice(
                logits, (jnp.zeros((), jnp.int32), length - 1,
                         jnp.zeros((), jnp.int32)),
                (1, 1, logits.shape[-1]))[:, 0]
            key, sub = jax.random.split(key)
            # named region (r6 scope, r14 perf-doctor row): the sampling
            # machinery is real per-token work, not model compute — it
            # must be attributable, not "(unscoped)"
            with scope("serving.sample"):
                first = sample_tokens(last.astype(jnp.float32), sub,
                                      temp, topk, topp)[0]
            return first.astype(jnp.int32), key, kc, vc

        def step_fn(params, buffers, tok, pos, active, temp, topk, topp,
                    keys, kc, vc):
            # tok [n,1] last sampled token per slot; pos [n] its position
            self.trace_counts["step"] += 1
            posj = pos.astype(jnp.int32)
            for li, a in enumerate(attns):
                a._gen_cache = {"mode": "buffer", "k": kc[li], "v": vc[li],
                                "pos": posj}
            try:
                with no_grad():
                    logits = _forward(params, buffers, wrap(tok),
                                      wrap(posj[:, None]))
                ks = jnp.stack([unwrap(a._gen_cache["k"]) for a in attns])
                vs = jnp.stack([unwrap(a._gen_cache["v"]) for a in attns])
            finally:
                for a in attns:
                    if hasattr(a, "_gen_cache"):
                        del a._gen_cache
            pair = jax.vmap(lambda k_: jax.random.split(k_))(keys)
            with scope("serving.sample"):
                nxt = sample_tokens(
                    logits[:, -1].astype(jnp.float32),
                    pair[:, 1], temp, topk, topp).astype(jnp.int32)
            nxt = jnp.where(active, nxt, 0)
            new_tok = jnp.where(active, nxt, tok[:, 0])[:, None]
            new_pos = jnp.where(active, posj + 1, posj)
            new_keys = jnp.where(active[:, None], pair[:, 0], keys)
            return nxt, new_tok, new_pos, new_keys, ks.astype(kc.dtype), \
                vs.astype(vc.dtype)

        # donate the K/V caches and the PRNG key chains: the engine replaces
        # them with the returned buffers every call, so XLA can update in
        # place instead of copying the full [L, n_slots, H, S, D] pair per
        # token.  The intended donation is recorded unconditionally (the
        # analysis donation-miss rule lints against it — the TPU deployment
        # contract) but applied only off-CPU, where XLA honors aliasing
        # (donating on CPU just warns per program).
        self._donate_prefill = (5, 9, 10)   # key, kc, vc
        self._donate_step = (8, 9, 10)      # keys, kc, vc
        on_cpu = jax.default_backend() == "cpu"
        self._prefill_jit = jax.jit(
            prefill_fn, donate_argnums=() if on_cpu else self._donate_prefill)
        self._step_jit = jax.jit(
            step_fn, donate_argnums=() if on_cpu else self._donate_step)

    def _build_programs_paged(self):
        import jax
        import jax.numpy as jnp

        from ..autograd.tape import no_grad
        from ..models.generation import sample_tokens
        from ..ops._primitive import unwrap, wrap
        from ..profiler.scope import scope

        model, attns = self.model, self._attns
        ps = self.page_size
        quant = self._kv_quant

        def _forward(params, buffers, ids_t, position_ids_t):
            out, _ = model.functional_call_with_state(
                params, buffers, ids_t, position_ids_t)
            return unwrap(out)

        def _set_caches(pk, pv, pages, pos, scales=()):
            for li, a in enumerate(attns):
                c = {"mode": "paged", "k": pk[li], "v": pv[li],
                     "pages": pages, "pos": pos,
                     "page_size": ps,
                     "attn_impl": self.attn_impl}
                if scales:
                    # int8 KV layout: per-token f32 absmax scales ride
                    # alongside the pool halves (quant on scatter-in,
                    # dequant on gather — models/gpt.py's _paged_attn)
                    c["k_scale"] = scales[0][li]
                    c["v_scale"] = scales[1][li]
                a._gen_cache = c

        def _collect_caches():
            pk = jnp.stack([unwrap(a._gen_cache["k"]) for a in attns])
            pv = jnp.stack([unwrap(a._gen_cache["v"]) for a in attns])
            if not quant:
                return pk, pv, ()
            sk = jnp.stack([unwrap(a._gen_cache["k_scale"]) for a in attns])
            sv = jnp.stack([unwrap(a._gen_cache["v_scale"]) for a in attns])
            return pk, pv, (sk, sv)

        def _clear_caches():
            for a in attns:
                if hasattr(a, "_gen_cache"):
                    del a._gen_cache

        def prefill_fn(params, buffers, ids, start, rlen, is_final, pages,
                       key, temp, topk, topp, cow_src, cow_dst, pk, pv,
                       *scales):
            # ONE page-aligned-or-COW chunk of a prompt: ids [1, Tc]
            # chunk-bucket-padded, start = absolute position of ids[0,0],
            # rlen = real tokens in this chunk. The chunk attends to the
            # slot's resident pages (shared prefix + earlier chunks)
            # through `pages` and writes its own K/V into them. Sampling
            # happens every call (one program per chunk LENGTH only) but
            # the key advances — and the token matters — only when
            # is_final is set.
            self.trace_counts["prefill"] += 1
            # copy-on-write BEFORE any write lands: duplicate one page
            # (src==dst==0 is the trash-page no-op) so a whole-prompt
            # prefix hit can recompute its final token into a private
            # copy without mutating the shared page
            pk = pk.at[:, cow_dst].set(jnp.take(pk, cow_src, axis=1))
            pv = pv.at[:, cow_dst].set(jnp.take(pv, cow_src, axis=1))
            if scales:
                sk, sv = scales
                scales = (
                    sk.at[:, cow_dst].set(jnp.take(sk, cow_src, axis=1)),
                    sv.at[:, cow_dst].set(jnp.take(sv, cow_src, axis=1)))
            start = start.astype(jnp.int32)
            tc = ids.shape[1]
            pos_ids = (start + jnp.arange(tc, dtype=jnp.int32))[None, :]
            _set_caches(pk, pv, pages[None, :], start[None], scales)
            try:
                with no_grad():
                    logits = _forward(params, buffers, wrap(ids),
                                      wrap(pos_ids))
                pk, pv, scales = _collect_caches()
            finally:
                _clear_caches()
            last = jax.lax.dynamic_slice(
                logits, (jnp.zeros((), jnp.int32), rlen - 1,
                         jnp.zeros((), jnp.int32)),
                (1, 1, logits.shape[-1]))[:, 0]
            key2, sub = jax.random.split(key)
            with scope("serving.sample"):
                tok = sample_tokens(last.astype(jnp.float32), sub,
                                    temp, topk, topp)[0]
            first = jnp.where(is_final, tok.astype(jnp.int32),
                              jnp.zeros((), jnp.int32))
            new_key = jnp.where(is_final, key2, key)
            return (first, new_key, pk, pv) + tuple(scales)

        def step_fn(params, buffers, tok, pos, active, temp, topk, topp,
                    keys, tables, pk, pv, *scales):
            # one decode token for every active slot, through the pool:
            # writes scatter into (tables[slot, pos//ps], pos%ps); reads
            # gather the tables' pages back into position order
            self.trace_counts["step"] += 1
            posj = pos.astype(jnp.int32)
            _set_caches(pk, pv, tables, posj, scales)
            try:
                with no_grad():
                    logits = _forward(params, buffers, wrap(tok),
                                      wrap(posj[:, None]))
                pk, pv, scales = _collect_caches()
            finally:
                _clear_caches()
            pair = jax.vmap(lambda k_: jax.random.split(k_))(keys)
            with scope("serving.sample"):
                nxt = sample_tokens(
                    logits[:, -1].astype(jnp.float32),
                    pair[:, 1], temp, topk, topp).astype(jnp.int32)
            nxt = jnp.where(active, nxt, 0)
            new_tok = jnp.where(active, nxt, tok[:, 0])[:, None]
            new_pos = jnp.where(active, posj + 1, posj)
            new_keys = jnp.where(active[:, None], pair[:, 0], keys)
            return (nxt, new_tok, new_pos, new_keys, pk, pv) \
                + tuple(scales)

        # donate the page pool and PRNG key chains: the pool is the ONLY
        # large mutable state, threaded through every call — donation
        # makes each tick an in-place update instead of a full-pool copy
        # (recorded unconditionally for the analysis donation lint — the
        # TPU deployment contract — applied off-CPU where XLA honors it)
        self._donate_prefill = (7, 13, 14)  # key, pool_k, pool_v
        self._donate_step = (8, 10, 11)     # keys, pool_k, pool_v
        if quant:
            # the scale planes are donated state exactly like the pool
            self._donate_prefill += (15, 16)
            self._donate_step += (12, 13)
        on_cpu = jax.default_backend() == "cpu"
        self._prefill_jit = jax.jit(
            prefill_fn, donate_argnums=() if on_cpu else self._donate_prefill)
        self._step_jit = jax.jit(
            step_fn, donate_argnums=() if on_cpu else self._donate_step)

    # -- program arg specs (admission pricing, analysis, perf doctor) ------
    def _prefill_arg_specs(self, bucket: int):
        """ShapeDtypeStruct tuple matching ``_prefill_jit`` at ``bucket``
        (the admission gate prices this without compiling)."""
        import jax

        sds = jax.ShapeDtypeStruct
        i32, f32, u32 = np.int32, np.float32, np.uint32
        params = {n: sds(p.shape, p.dtype) for n, p in self._params.items()}
        buffers = {n: sds(b.shape, b.dtype) for n, b in self._buffers.items()}
        if self._paged:
            args = (params, buffers, sds((1, int(bucket)), i32),
                    sds((), i32), sds((), i32), sds((), np.bool_),
                    sds((self.max_pages_per_slot,), i32), sds((2,), u32),
                    sds((), f32), sds((), i32), sds((), f32),
                    sds((), i32), sds((), i32),
                    sds(self._pool_shape, self.kv_dtype),
                    sds(self._pool_shape, self.kv_dtype))
            if self._kv_quant:
                args += (sds(self._scale_shape, f32),
                         sds(self._scale_shape, f32))
            return args
        return (params, buffers, sds((1, int(bucket)), i32), sds((), i32),
                sds((), i32), sds((2,), u32), sds((), f32), sds((), i32),
                sds((), f32),
                sds(self._cache_shape, self._cache_dtype),
                sds(self._cache_shape, self._cache_dtype))

    def _step_args_example(self):
        """Concrete arrays matching ``_step_jit`` (analysis entry points,
        perf doctor) — every slot marked active."""
        import jax.numpy as jnp

        n = self.n_slots
        common = (self._params, self._buffers,
                  jnp.zeros((n, 1), jnp.int32), jnp.zeros((n,), jnp.int32),
                  jnp.ones((n,), bool), jnp.zeros((n,), jnp.float32),
                  jnp.full((n,), -1, jnp.int32), jnp.ones((n,), jnp.float32),
                  jnp.zeros((n, 2), jnp.uint32))
        if self._paged:
            args = common + (jnp.asarray(self._page_tables),
                             self._pool_k, self._pool_v)
            if self._kv_quant:
                args += (self._scale_k, self._scale_v)
            return args
        return common + (self._kc, self._vc)

    # -- public API ---------------------------------------------------------
    @property
    def trace_count(self) -> int:
        """Total compiled programs (prefill chunk buckets used + decode
        step)."""
        return self.trace_counts["prefill"] + self.trace_counts["step"]

    def free_slots(self) -> int:
        return sum(1 for r in self._slots if r is None)

    def active_slots(self) -> int:
        """Occupied slots: decoding OR mid-chunked-prefill (both hold
        pages and both must block a drain)."""
        return self.n_slots - self.free_slots()

    def _busy(self) -> bool:
        return bool(self._active.any()) or bool(self._prefill_slots)

    # -- page accounting (paged layout) -------------------------------------
    def pages_needed(self, req: Request) -> int:
        """Worst-case NEW pages this request will allocate over its
        lifetime, net of the prefix pages currently resident in the radix
        tree — the admission gate's per-request watermark increment."""
        if not self._paged:
            return 0
        total = -(-(req.prompt.size + req.max_new_tokens) // self.page_size)
        # continuation joins price against the JOIN sequence (prompt +
        # observed[:-1]): that is what prefill writes and what the radix
        # tree can discount — a mass resurrection after a replica death is
        # gated on what it will truly allocate, not the raw prompt
        seq = req.prefill_ids()
        shared = self._radix.peek(seq) if self._radix else 0
        # a whole-prefix hit still copies one page (copy-on-write)
        if shared * self.page_size >= seq.size and shared > 0:
            shared -= 1
        return max(total - shared, 1)

    def page_state(self) -> Dict[str, int]:
        """Live pool occupancy (free/used/shared/capacity/page_bytes) plus
        prefix-sharing counters; empty dict for the slot layout."""
        if not self._paged:
            return {}
        st = self._pool.state()
        st["cow_pages"] = self.cow_pages
        if self._radix is not None:
            st["prefix_queries"] = self._radix.queries
            st["prefix_hits"] = self._radix.hits
            st["prefix_hit_tokens"] = self._radix.hit_tokens
        return st

    def kv_bytes_per_stream(self) -> Optional[float]:
        """Measured KV HBM per occupied stream: allocated pages × page
        bytes / occupied slots (None when idle). The paged win over the
        slot layout's ``2·L·H·S·D`` per slot, as a live gauge."""
        if not self._paged:
            return None
        occupied = self.active_slots()
        if not occupied:
            return None
        return self._pool.used_count() * self.page_bytes / occupied

    def submit(self, prompt, **kwargs) -> Request:
        """Admit one request (FCFS). Raises QueueFullError / SchedulerClosed
        on backpressure/drain and ValueError on capacity violations."""
        req = prompt if isinstance(prompt, Request) else Request(prompt, **kwargs)
        if req.prompt.size + req.max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt ({req.prompt.size}) + max_new_tokens "
                f"({req.max_new_tokens}) exceeds KV capacity "
                f"max_seq_len={self.max_seq_len}")
        if req.deadline_expired():
            # dead on arrival: refuse up front (503) — queueing it would
            # only burn a prefill the client has already given up on
            from .admission import DeadlineExceededError

            self.metrics.on_reject()
            raise DeadlineExceededError(
                f"request {req.request_id} arrived with its deadline "
                f"already elapsed (deadline_s={req.deadline_s})")
        if req.observed_terminal:
            # the observed transcript already finished (max_new_tokens or
            # eos) on its previous home: nothing to prefill or decode —
            # complete immediately so poll/stream replay the full log
            self.metrics.on_submit()
            req.state = Request.RUNNING
            req._finish(Request.DONE)
            self.metrics.on_complete()
            return req
        if self.admission_gate is not None:
            try:
                self.admission_gate.check(req)
            except Exception:
                self.metrics.on_reject()
                raise
        try:
            self.scheduler.submit(req)
        except Exception:
            self.metrics.on_reject()
            self._settle_gate(req)
            raise
        self.metrics.on_submit()
        return req

    def export_stream(self, request_id: str) -> Dict:
        """Live-migration source half: drain ONE active stream between
        ticks — build its CRC-stamped continuation record, free its slot
        and pages, and retire the local id with the typed
        :data:`MIGRATED_ERROR_TYPE` (routers read that as "moved", not
        failed). Raises KeyError for an id this engine is not decoding
        (unknown, queued, finished) and ValueError for a mid-prefill slot
        (its KV is incomplete — nothing coherent to export yet)."""
        with self._lock:
            slot_idx = next(
                (i for i in range(self.n_slots)
                 if self._slots[i] is not None
                 and self._slots[i].request_id == request_id), None)
            if slot_idx is None:
                raise KeyError(
                    f"request {request_id!r} holds no slot on this replica "
                    f"(unknown, still queued, or already finished)")
            req = self._slots[slot_idx]
            if not self._active[slot_idx]:
                raise ValueError(
                    f"request {request_id!r} is mid-prefill; only actively "
                    f"decoding streams are exportable")
            record = make_continuation_record(
                req, deadline_remaining=req.deadline_remaining())
            if self._paged:
                self._free_paged_slot(slot_idx, req)
            else:
                self._slots[slot_idx] = None
                self._active[slot_idx] = False
            req._finish(
                Request.FAILED,
                f"{MIGRATED_ERROR_TYPE}: stream exported off this replica "
                f"after {len(req.tokens)} tokens",
                error_type=MIGRATED_ERROR_TYPE)
            self.metrics.on_export()
            self.metrics.set_gauges(self.scheduler.depth(),
                                    self.active_slots(), self.n_slots)
        return record

    def _settle_gate(self, req: Request):
        """Release the admission gate's page-watermark reservation for a
        request that left the queue (allocated its pages, or failed)."""
        gate = self.admission_gate
        if gate is not None:
            try:
                gate.settle(req)
            except Exception:
                pass

    # -- engine ticks -------------------------------------------------------
    def _admit_one(self, req: Request, slot_idx: int) -> bool:
        if self._paged:
            return self._admit_one_paged(req, slot_idx)
        return self._admit_one_slot(req, slot_idx)

    def _record_queue_span(self, req: Request):
        if obstrace.tracing_enabled() and req.trace_id is not None:
            return obstrace.record_span(
                "serving.queue_wait", ts=req.submitted_wall,
                dur=time.perf_counter() - req.submitted_at,
                trace_id=req.trace_id, parent_id=req.parent_span_id,
                attrs={"request_id": req.request_id})
        return None

    def _admit_one_slot(self, req: Request, slot_idx: int) -> bool:
        """Prefill ``req`` into ``slot_idx``; False when the request finished
        at prefill (slot stays free)."""
        import jax
        import jax.numpy as jnp

        from ..profiler.scope import scope

        seq = req.prefill_ids()
        t0 = seq.size
        bucket = req.bucket or self.scheduler.bucket_for(t0)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :t0] = seq
        seed = self._seed_for(req)
        key = jax.random.PRNGKey(seed)
        before = self.trace_counts["prefill"]
        # request-scoped spans: queue wait is recorded retrospectively
        # (submit → this admission), and the prefill span parents the
        # per-token decode spans — route ⊃ queue ⊃ prefill ⊃ decode
        queue_span = self._record_queue_span(req)
        t_prefill_wall, t_prefill = time.time(), time.perf_counter()
        # first use of a bucket traces, and tracing mutates the SHARED
        # model's attention layers — exclude other engines on this model
        guard = (contextlib.nullcontext() if bucket in self._traced_buckets
                 else self._trace_lock)
        with scope("serving.prefill"), guard:
            first, key, self._kc, self._vc = self._prefill_jit(
                self._params, self._buffers, jnp.asarray(ids),
                jnp.asarray(np.int32(t0)), jnp.asarray(np.int32(slot_idx)),
                key, jnp.float32(req.temperature),
                jnp.int32(-1 if req.top_k is None else req.top_k),
                jnp.float32(1.0 if req.top_p is None else req.top_p),
                self._kc, self._vc)
        self._traced_buckets.add(bucket)
        compiled = self.trace_counts["prefill"] > before
        if queue_span is not None:
            prefill_span = obstrace.record_span(
                "serving.prefill", ts=t_prefill_wall,
                dur=time.perf_counter() - t_prefill,
                trace_id=req.trace_id, parent_id=queue_span.span_id,
                attrs={"request_id": req.request_id, "bucket": int(bucket),
                       "prompt_len": int(t0), "slot": int(slot_idx),
                       "compiled": compiled})
            # record_span returns None if tracing was disabled between the
            # two records — a telemetry toggle must never fail the tick
            if prefill_span is not None:
                req._decode_span_parent = prefill_span.span_id
        self.metrics.on_prefill(compiled)
        first, key = self._resume_state(req, seed, first, key)
        req.state = Request.RUNNING
        if req.observed:
            # continuation join (see _run_chunk): resume decode from the
            # last observed token with the fast-forwarded key chain
            self.metrics.on_continuation(len(req.observed))
            self._slots[slot_idx] = req
            self._activate(slot_idx, req, first, t0, key)
            return True
        req._append(first)
        self.metrics.on_first_token(req.first_token_at - req.submitted_at,
                                    trace_id=req.trace_id)
        self.metrics.on_tokens(1)
        if self._request_finished(req, first):
            # done at prefill (max_new=1 or instant eos): never activate
            self._retire(slot_idx, req)
            return False
        self._slots[slot_idx] = req
        self._activate(slot_idx, req, first, t0, key)
        return True

    def _activate(self, slot_idx: int, req: Request, first: int, pos: int,
                  key):
        self._active[slot_idx] = True
        self._tok[slot_idx] = first
        self._pos[slot_idx] = pos
        self._temp[slot_idx] = req.temperature
        self._topk[slot_idx] = -1 if req.top_k is None else req.top_k
        self._topp[slot_idx] = 1.0 if req.top_p is None else req.top_p
        self._keys[slot_idx] = np.asarray(key, np.uint32)
        if self._spec is not None:
            # draft catch-up: prefill the draft model's KV over this
            # stream's full sequence-so-far through the SAME page table
            self._spec.on_activate(slot_idx, req, int(first), int(pos))

    def _seed_for(self, req: Request) -> int:
        if req.seed is None:
            self._seed_counter += 1
            # recorded so a later export (live migration) can pin the key
            # chain the engine actually used for this stream
            req.effective_seed = self._seed_counter
            return self._seed_counter
        req.effective_seed = int(req.seed)
        return int(req.seed)

    def _resume_state(self, req: Request, seed: int, sampled_first,
                      sampled_key):
        """The (first, key) pair to activate decode with after the final
        prefill chunk. Fresh request: the in-graph sampled token and the
        advanced chain. Continuation join: the sampled token/key belong to
        a draw the ORIGINAL run already spent — discard them, resume from
        the last observed token with the chain fast-forwarded by
        len(observed) draws (bit-identical to the uninterrupted run)."""
        if not req.observed:
            return int(sampled_first), sampled_key
        import jax

        from ..models.generation import fast_forward_key

        key = fast_forward_key(jax.random.PRNGKey(int(seed)),
                               len(req.observed))
        return int(req.observed[-1]), key

    # -- paged admission + chunked prefill ----------------------------------
    def _alloc_pages(self, n: int, phase: str):
        """Allocate ``n`` pages, evicting cold radix prefixes under
        pressure. The ``serving.pages.exhausted`` injection point fires
        here (deterministic trigger counts — one per allocation event),
        so the r13 inject plane can prove the victim-only failure path
        without actually shrinking the pool."""
        from ..resilience.inject import fire as _inject_fire

        if n <= 0:
            return []
        _inject_fire("serving.pages.exhausted", phase=phase, n=int(n))
        evict = self._radix.evict if self._radix is not None else None
        return self._pool.alloc(n, evict=evict)

    def _release_request_pages(self, req: Request, slot_idx: Optional[int]):
        pages = getattr(req, "_pages", None)
        if pages:
            self._pool.release(pages)
            req._pages = []
        if slot_idx is not None:
            self._page_tables[slot_idx] = TRASH_PAGE

    def _admit_one_paged(self, req: Request, slot_idx: int) -> bool:
        """Match the prompt's shared prefix, allocate private prompt
        pages, and run the FIRST prefill chunk; further chunks (long
        prompts) continue on later ticks interleaved with decode. False
        when the request finished (or failed) without occupying the
        slot."""
        ps = self.page_size
        # the JOIN sequence: the whole prompt, plus — for a continuation
        # (resurrected/migrated stream) — every observed token but the
        # last; KV must cover exactly the positions the uninterrupted run
        # had written when it was interrupted
        seq = req.prefill_ids()
        t0 = seq.size
        req._pages = []
        try:
            matched: List[int] = []
            if self._radix is not None:
                matched = self._radix.match(seq)
                req._pages.extend(matched)
            resume = len(matched) * ps
            cow = (0, 0)
            if matched and resume >= t0:
                # whole prompt resident: recompute only the LAST token's
                # KV (its logits seed sampling) into a copy-on-write
                # duplicate of the final shared page
                cow_page = self._alloc_pages(1, "cow")[0]
                req._pages.append(cow_page)
                cow = (matched[-1], cow_page)
                resume = t0 - 1
                self.cow_pages += 1
                self.metrics.on_cow()
            # private pages covering the unmatched prompt tail (decode
            # pages are allocated lazily, tick by tick)
            first_pi = resume // ps if cow == (0, 0) else len(matched)
            last_pi = (t0 - 1) // ps
            fresh = self._alloc_pages(max(last_pi - first_pi + 1, 0)
                                      if cow == (0, 0) else 0, "prompt")
            req._pages.extend(fresh)
            table = self._page_tables[slot_idx]
            table[:] = TRASH_PAGE
            for i, p in enumerate(matched):
                table[i] = p
            if cow != (0, 0):
                table[len(matched) - 1] = cow[1]
            for i, p in enumerate(fresh):
                table[first_pi + i] = p
        except Exception:
            self._release_request_pages(req, slot_idx)
            raise
        self._settle_gate(req)
        queue_span = self._record_queue_span(req)
        import jax

        seed = self._seed_for(req)
        key = jax.random.PRNGKey(seed)
        state = {"req": req, "seq": seq, "seed": seed, "next": int(resume),
                 "key": key, "cow": cow, "queue_span": queue_span,
                 "chunks": 0}
        self._slots[slot_idx] = req
        self._prefill_slots[slot_idx] = state
        try:
            return self._run_chunk(slot_idx, state)
        except Exception:
            self._free_paged_slot(slot_idx, req)
            raise

    def _free_paged_slot(self, slot_idx: int, req: Request):
        self._release_request_pages(req, slot_idx)
        self._prefill_slots.pop(slot_idx, None)
        self._slots[slot_idx] = None
        self._active[slot_idx] = False
        if self._spec is not None:
            self._spec.on_free(slot_idx)

    def _chunk_bucket_for(self, rlen: int) -> int:
        for b in self.chunk_buckets:
            if rlen <= b:
                return b
        return self.chunk_buckets[-1]

    def _run_chunk(self, slot_idx: int, state: dict) -> bool:
        """Dispatch ONE prefill chunk for a mid-prefill slot. Returns True
        while the slot stays occupied (more chunks, or activated for
        decode); False when the request finished at prefill."""
        import jax.numpy as jnp

        from ..profiler.scope import scope

        req: Request = state["req"]
        seq = state["seq"]
        t0 = seq.size
        start = state["next"]
        rlen = min(t0 - start, self._chunk_limit)
        bucket = self._chunk_bucket_for(rlen)
        is_final = start + rlen >= t0
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :rlen] = seq[start:start + rlen]
        cow = state["cow"] if state["chunks"] == 0 else (0, 0)
        before = self.trace_counts["prefill"]
        t_prefill_wall, t_prefill = time.time(), time.perf_counter()
        guard = (contextlib.nullcontext() if bucket in self._traced_buckets
                 else self._trace_lock)
        args = (self._params, self._buffers, jnp.asarray(ids),
                jnp.asarray(np.int32(start)), jnp.asarray(np.int32(rlen)),
                jnp.asarray(bool(is_final)),
                jnp.asarray(self._page_tables[slot_idx]),
                state["key"], jnp.float32(req.temperature),
                jnp.int32(-1 if req.top_k is None else req.top_k),
                jnp.float32(1.0 if req.top_p is None else req.top_p),
                jnp.asarray(np.int32(cow[0])), jnp.asarray(np.int32(cow[1])),
                self._pool_k, self._pool_v)
        if self._kv_quant:
            args += (self._scale_k, self._scale_v)
        with scope("serving.prefill"), guard:
            if self._kv_quant:
                (first, key, self._pool_k, self._pool_v,
                 self._scale_k, self._scale_v) = self._prefill_jit(*args)
            else:
                first, key, self._pool_k, self._pool_v = \
                    self._prefill_jit(*args)
        self._traced_buckets.add(bucket)
        compiled = self.trace_counts["prefill"] > before
        state["key"] = key
        state["next"] = start + rlen
        state["chunks"] += 1
        if state["queue_span"] is not None:
            prefill_span = obstrace.record_span(
                "serving.prefill", ts=t_prefill_wall,
                dur=time.perf_counter() - t_prefill,
                trace_id=req.trace_id,
                parent_id=state["queue_span"].span_id,
                attrs={"request_id": req.request_id, "bucket": int(bucket),
                       "prompt_len": int(t0), "slot": int(slot_idx),
                       "chunk_start": int(start), "compiled": compiled})
            if prefill_span is not None:
                req._decode_span_parent = prefill_span.span_id
        self.metrics.on_prefill(compiled)
        if not is_final:
            return True  # slot stays in _prefill_slots; decode interleaves
        # final chunk: the first token was sampled in-graph
        del self._prefill_slots[slot_idx]
        if self._radix is not None:
            full = t0 // self.page_size
            if full:
                self._radix.insert(
                    seq, [int(p) for p in
                          self._page_tables[slot_idx][:full]])
        first, key = self._resume_state(req, state["seed"], first, key)
        req.state = Request.RUNNING
        if req.observed:
            # continuation join: the observed tokens were emitted (and
            # counted) on the previous home; decode resumes FROM the last
            # observed token — no append, no first-token latency sample
            self.metrics.on_continuation(len(req.observed))
            self._activate(slot_idx, req, first, t0, key)
            return True
        req._append(first)
        self.metrics.on_first_token(req.first_token_at - req.submitted_at,
                                    trace_id=req.trace_id)
        self.metrics.on_tokens(1)
        if self._request_finished(req, first):
            self._retire(slot_idx, req)
            self._free_paged_slot(slot_idx, req)
            return False
        self._activate(slot_idx, req, first, t0, key)
        return True

    def _advance_prefills(self, budget: int) -> int:
        """Continue chunked prefills (oldest slot first), re-checking each
        request's deadline BEFORE its next chunk: a request admitted
        pre-chunking can expire mid-prefill and must be shed with the
        typed 503 instead of burning more prefill programs. Returns the
        number of chunk programs dispatched."""
        ran = 0
        for slot_idx in sorted(self._prefill_slots):
            if ran >= budget:
                break
            if slot_idx not in self._prefill_slots:
                # a previous chunk's failure took the whole pool with it
                # (donated call died) and fail_pending already cleared
                # every mid-prefill slot — nothing left to advance
                continue
            state = self._prefill_slots[slot_idx]
            req = state["req"]
            if req.deadline_expired():
                # deadline re-check after chunked-prefill waits: typed
                # 503, sweep counters intact, pages released
                self._fail_deadline(req, where="mid-prefill")
                self._free_paged_slot(slot_idx, req)
                continue
            try:
                self._run_chunk(slot_idx, state)
            except Exception as e:
                msg = f"prefill failed: {type(e).__name__}: {e}"
                req._finish(Request.FAILED, msg)
                self._free_paged_slot(slot_idx, req)
                if self._cache_lost():
                    self.fail_pending(msg, _locked=True)
            ran += 1
        return ran

    def _ensure_decode_pages(self):
        """Lazy decode-page allocation: before the step, every active slot
        whose next write position crosses into an unallocated page gets
        one. Exhaustion (real or injected) fails ONLY the victim request
        and releases its refcounted pages — every other slot decodes on."""
        ps = self.page_size
        for i in range(self.n_slots):
            if not self._active[i]:
                continue
            pi = int(self._pos[i]) // ps
            if pi >= self.max_pages_per_slot:
                continue
            if self._page_tables[i, pi] != TRASH_PAGE:
                continue
            req = self._slots[i]
            try:
                page = self._alloc_pages(1, "decode")[0]
            except Exception as e:
                req._finish(
                    Request.FAILED,
                    f"{PagesExhaustedError.error_type}: page pool "
                    f"exhausted mid-generation after {len(req.tokens)} "
                    f"tokens: {e}",
                    error_type=PagesExhaustedError.error_type)
                self._free_paged_slot(i, req)
                continue
            req._pages.append(page)
            self._page_tables[i, pi] = page

    def _request_finished(self, req: Request, token: int) -> bool:
        if req.eos_token_id is not None and token == req.eos_token_id:
            return True
        return len(req.tokens) >= req.max_new_tokens

    def _retire(self, slot_idx: int, req: Request):
        req._finish(Request.DONE)
        self.metrics.on_complete()
        if self._paged:
            self._release_request_pages(req, slot_idx)
        if self._spec is not None:
            self._spec.on_free(slot_idx)

    def _fail_deadline(self, req: Request, where: str = "queue"):
        from .admission import DEADLINE_ERROR_TYPE

        waited = time.perf_counter() - req.submitted_at
        req._finish(
            Request.FAILED,
            f"{DEADLINE_ERROR_TYPE}: deadline_s={req.deadline_s} elapsed "
            f"after {waited:.3f}s (shed {where}, before "
            f"{'its next chunk' if where == 'mid-prefill' else 'prefill'})",
            error_type=DEADLINE_ERROR_TYPE)
        self.metrics.on_shed("deadline")
        self._settle_gate(req)

    def _fail_shed(self, req: Request):
        from .admission import SHED_ERROR_TYPE

        hint = self.metrics.retry_after_hint(
            queue_depth=self.scheduler.depth())
        req._finish(
            Request.FAILED,
            f"{SHED_ERROR_TYPE}: shed under sustained overload before "
            f"prefill; retry after {hint:.1f}s",
            error_type=SHED_ERROR_TYPE)
        self.metrics.on_shed("overload")
        self._settle_gate(req)

    def step_once(self) -> bool:
        """One engine tick: continue chunked prefills, admit waiting
        requests into free slots (bounded by the scheduler's interleave
        policy), then run ONE decode step for every active slot. Returns
        False when there was nothing to do."""
        import jax.numpy as jnp

        from ..profiler.scope import scope
        from ..resilience.inject import fire as _inject_fire

        # injection seam: a raised fault propagates into serve_forever's
        # containment (deterministic replay of the poison-tick suite), a
        # stall sleeps here — both without touching engine state. Fired
        # only on PRODUCTIVE ticks: idle polls are timing-dependent and
        # must not advance trigger counts
        if self._busy() or self.scheduler.depth() > 0:
            _inject_fire("engine.tick",
                         replica=getattr(self, "_replica_addr", None))
        with self._lock:
            did = False
            # queue hygiene before admissions: drop work whose deadline
            # already elapsed — it can never start in time, so it must
            # not consume an admission slot (failed VISIBLY, typed error
            # via poll/stream, never silently)
            for req in self.scheduler.sweep_expired():
                self._fail_deadline(req)
                did = True
            budget = self.scheduler.max_prefills_per_tick
            if self._prefill_slots:
                ran = self._advance_prefills(budget)
                budget -= ran
                did = did or ran > 0
            free = [i for i in range(self.n_slots)
                    if self._slots[i] is None and not self._active[i]]
            if free and budget > 0:
                for req in self.scheduler.take_admissions(
                        min(len(free), budget)):
                    slot = free.pop(0)
                    if req.deadline_expired():
                        # the mid-queue-expiry race: the deadline lapsed
                        # between the pop and this prefill — shed NOW,
                        # never burn a prefill on a dead request
                        self._fail_deadline(req)
                        self.scheduler.admission_settled()
                        free.insert(0, slot)
                        did = True
                        continue
                    try:
                        occupied = self._admit_one(req, slot)
                    except Exception as e:
                        # a poison request must not take down the queue:
                        # fail IT (it already left the scheduler) and move on
                        msg = f"prefill failed: {type(e).__name__}: {e}"
                        req._finish(Request.FAILED, msg)
                        self._settle_gate(req)
                        occupied = False
                        if self._cache_lost():
                            # the donated cache died with the call: in-flight
                            # slots lost their K/V — fail them, fresh cache
                            self.fail_pending(msg, _locked=True)
                    finally:
                        self.scheduler.admission_settled()
                    if not occupied:
                        free.append(slot)  # finished/failed at prefill
                    did = True
            # overload policy AFTER admissions: everything still queued
            # here genuinely waits at least a tick, so the shed target
            # never fails a request that could have started right now
            # (and free slots are never idled by the trim)
            if self.shed_policy is not None:
                for req in self.shed_policy.victims(self.scheduler):
                    self._fail_shed(req)
                    did = True
            if self._paged and self._active.any():
                self._ensure_decode_pages()
            if self._active.any():
                if self._spec is not None:
                    self._spec.tick()
                else:
                    self._decode_tick_plain()
                did = True
            self.metrics.set_gauges(self.scheduler.depth(),
                                    self.active_slots(), self.n_slots)
            if self._paged:
                self.metrics.set_page_gauges(self.page_state())
            return did

    def _decode_tables(self):
        """Page tables as shipped to the decode/verify programs: inactive
        slots' rows are masked to the trash page so a stale ``_pos``/
        ``_tok`` pair can never scatter into a mid-prefill slot's (possibly
        radix-shared) pages."""
        import jax.numpy as jnp

        return jnp.asarray(np.where(self._active[:, None],
                                    self._page_tables,
                                    np.int32(TRASH_PAGE)))

    def _decode_tick_plain(self):
        """ONE batched decode step for every active slot (lock held).
        The non-speculative decode path — also the per-tick fallback when
        a speculative verify is faulted out."""
        import jax.numpy as jnp

        from ..profiler.scope import scope

        before = self.trace_counts["step"]
        t_step_wall = time.time()
        t_step = time.perf_counter()
        guard = (self._trace_lock if self.trace_counts["step"] == 0
                 else contextlib.nullcontext())
        common = (self._params, self._buffers,
                  jnp.asarray(self._tok[:, None]),
                  jnp.asarray(self._pos),
                  jnp.asarray(self._active),
                  jnp.asarray(self._temp),
                  jnp.asarray(self._topk),
                  jnp.asarray(self._topp),
                  jnp.asarray(self._keys))
        with scope("serving.decode_step"), guard:
            if self._paged and self._kv_quant:
                (nxt, tok, pos, keys, self._pool_k, self._pool_v,
                 self._scale_k, self._scale_v) = self._step_jit(
                    *common, self._decode_tables(),
                    self._pool_k, self._pool_v,
                    self._scale_k, self._scale_v)
            elif self._paged:
                nxt, tok, pos, keys, self._pool_k, self._pool_v = \
                    self._step_jit(
                        *common, self._decode_tables(),
                        self._pool_k, self._pool_v)
            else:
                nxt, tok, pos, keys, self._kc, self._vc = \
                    self._step_jit(*common, self._kc, self._vc)
        nxt = np.asarray(nxt)  # device sync: tokens must stream out
        step_s = time.perf_counter() - t_step
        self.metrics.on_step(self.trace_counts["step"] > before)
        # np.array COPIES: device views are read-only, and slots
        # mutate these between steps
        self._tok = np.array(tok)[:, 0]
        self._pos = np.array(pos)
        self._keys = np.array(keys)
        emitted = 0
        spans_on = obstrace.tracing_enabled()
        for i in range(self.n_slots):
            req = self._slots[i]
            if req is None or not self._active[i]:
                continue
            token = int(nxt[i])
            req._append(token)
            if self._spec is not None:
                self._spec.on_token(i, token)
            emitted += 1
            if spans_on and req.trace_id is not None:
                # one span per generated token: the slot shares the
                # batched step's wall interval (they decode together)
                obstrace.record_span(
                    "serving.decode_token", ts=t_step_wall,
                    dur=step_s, trace_id=req.trace_id,
                    parent_id=req._decode_span_parent,
                    attrs={"request_id": req.request_id,
                           "token_index": len(req.tokens) - 1,
                           "slot": i})
            if self._request_finished(req, token):
                self._retire(i, req)
                self._slots[i] = None
                self._active[i] = False
        self.metrics.on_tokens(emitted, step_seconds=step_s)

    def run_until_idle(self, timeout: Optional[float] = None):
        """Drive ticks until the queue is empty and every slot is free
        (used by tests, bench, and graceful drain)."""
        deadline = None if timeout is None else time.perf_counter() + timeout
        while self.scheduler.depth() > 0 or self._busy():
            if deadline is not None and time.perf_counter() > deadline:
                raise TimeoutError("engine did not drain in time")
            self.step_once()

    def _cache_lost(self) -> bool:
        """True when a failed DONATED call already consumed the K/V buffers
        (jax invalidates donated inputs even if the computation errors)."""
        try:
            if self._paged:
                lost = bool(self._pool_k.is_deleted()
                            or self._pool_v.is_deleted())
                if self._kv_quant:
                    lost = lost or bool(self._scale_k.is_deleted()
                                        or self._scale_v.is_deleted())
                return lost
            return bool(self._kc.is_deleted() or self._vc.is_deleted())
        except Exception:
            return False

    def _reset_cache(self):
        import jax.numpy as jnp

        if self._paged:
            self._pool_k = jnp.zeros(self._pool_shape, self.kv_dtype)
            self._pool_v = jnp.zeros(self._pool_shape, self.kv_dtype)
            if self._kv_quant:
                self._scale_k = jnp.zeros(self._scale_shape, jnp.float32)
                self._scale_v = jnp.zeros(self._scale_shape, jnp.float32)
            # page CONTENT is gone with the pool: forget every allocation
            # and resident prefix (radix pages point at reallocated zeros)
            if self._radix is not None:
                self._radix.clear()
            self._pool.reset()
            self._page_tables[:] = TRASH_PAGE
            if self._spec is not None:
                self._spec.reset()
        else:
            self._kc = jnp.zeros(self._cache_shape, self._cache_dtype)
            self._vc = jnp.zeros(self._cache_shape, self._cache_dtype)

    def fail_pending(self, error: str, _locked: bool = False):
        """Fail every in-flight slot (decoding or mid-prefill) and queued
        request with ``error`` — the engine loop's containment path:
        clients polling/streaming see state FAILED instead of hanging on a
        silently dead loop thread. Reallocates the K/V pool if the failed
        call donated it away, so the engine keeps serving future
        requests."""
        ctx = contextlib.nullcontext() if _locked else self._lock
        with ctx:
            for i, req in enumerate(self._slots):
                if req is not None:
                    req._finish(Request.FAILED, error)
                    if self._paged:
                        req._pages = []  # pool reset below reclaims all
                    self._slots[i] = None
                    self._active[i] = False
            self._prefill_slots.clear()
            while self.scheduler.depth() > 0:  # interleave cap bounds each pop
                for req in self.scheduler.take_admissions(self.scheduler.depth()):
                    req._finish(Request.FAILED, error)
                    self._settle_gate(req)
                    self.scheduler.admission_settled()
            if self._paged:
                # refcounts are unrecoverable once their owners failed:
                # rebuild the allocator (and the pool array if donated
                # away) so future requests start from a clean pool
                lost = self._cache_lost()
                if self._radix is not None:
                    self._radix.clear()
                self._pool.reset()
                self._page_tables[:] = TRASH_PAGE
                if lost:
                    self._reset_cache()
                elif self._spec is not None:
                    self._spec.reset()
            elif self._cache_lost():
                self._reset_cache()
            self.metrics.set_gauges(self.scheduler.depth(),
                                    self.active_slots(), self.n_slots)
            if self._paged:
                self.metrics.set_page_gauges(self.page_state())

    def abort(self):
        """Abrupt-death hook (chaos testing / emergency teardown): the loop
        thread exits at its next iteration WITHOUT draining — queued and
        in-flight requests are simply orphaned, exactly like a SIGKILLed
        replica process. Failover responsibility moves to the serving
        router, which is the point of simulating it."""
        self._abort.set()

    def serve_forever(self, stop_event: threading.Event, idle_wait: float = 0.02):
        """Engine loop for a server thread: tick while there is work; block
        briefly on the admission queue when idle; exit when ``stop_event``
        is set AND all admitted work has drained (graceful drain). A tick
        that raises fails the affected requests (state FAILED, error
        recorded) instead of silently killing the loop thread."""
        from ..resilience.inject import fire as _inject_fire

        while not self._abort.is_set():
            # replica-death injection seam: counted only on PRODUCTIVE
            # ticks (work queued or slots active) so trigger counts are
            # deterministic — idle-wait iterations are timing-dependent
            # and must not advance the schedule
            try:
                # inside the try: a raise-kind fault at this point is
                # contained like any tick failure below, never a
                # silently dead loop thread
                if self._busy() or self.scheduler.depth() > 0:
                    f = _inject_fire(
                        "replica.tick",
                        replica=getattr(self, "_replica_addr", None))
                    if f is not None and f.kind == "kill":
                        # abrupt simulated SIGKILL: tear the whole
                        # replica down (HTTP plane included, via the
                        # server's kill hook) from a helper thread —
                        # kill() joins THIS thread, so it cannot run
                        # here — and exit the loop with no drain;
                        # queued/in-flight work is orphaned
                        kill_cb = getattr(self, "_server_kill", None)
                        self._abort.set()
                        if kill_cb is not None:
                            threading.Thread(target=kill_cb,
                                             daemon=True).start()
                        return
                did = self.step_once()
            except Exception as e:  # contain: fail work, keep serving
                err = f"engine tick failed: {type(e).__name__}: {e}"
                # flight-record the failure BEFORE failing the requests:
                # the ring still holds the spans leading up to the tick
                from ..observability.flight import flight_recorder

                flight_recorder().dump("engine_tick_failure",
                                       extra={"error": err})
                self.fail_pending(err)
                did = False
            if did:
                continue
            if stop_event.is_set() and self.scheduler.depth() == 0 \
                    and not self._busy():
                return
            self.scheduler.wait_for_work(idle_wait)

    def generate_batch(self, requests: Sequence[Request],
                       timeout: Optional[float] = None) -> List[np.ndarray]:
        """Convenience: submit all, drain, return per-request results
        (prompt + generated, int64 — models.generate's layout). Raises if
        any request FAILED — a partial token log must not pass for a
        legitimate early-eos completion."""
        reqs = [self.submit(r) for r in requests]
        self.run_until_idle(timeout=timeout)
        failed = [r for r in reqs if r.state == Request.FAILED]
        if failed:
            raise RuntimeError(
                f"{len(failed)}/{len(reqs)} requests failed; first: "
                f"{failed[0].request_id}: {failed[0].error}")
        return [r.result() for r in reqs]
