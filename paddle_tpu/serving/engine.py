"""Slot-based continuous-batching engine — iteration-level scheduling on TPU.

Parity: the reference serves production decoding through AnalysisPredictor's
ZeroCopyRun over exported programs and batches requests in Paddle Serving's
front-end; the scale story ("millions of users") on TPU is **continuous
batching** (Orca, OSDI'22; popularized by vLLM): requests join and leave a
shared decode batch *between* iterations instead of waiting for a full batch
to finish.

TPU-native design — fixed shapes, bounded compile cache, no paged kernels:

* ONE jitted decode step over a fixed ``[L, n_slots, H, S, D]`` K/V cache.
  Per-slot position vectors drive per-row ``dynamic_update_slice`` writes and
  per-row causal masks (models/gpt.py buffer-mode attention), so slots at
  different sequence positions decode together with zero recompilation.
* Sequences JOIN by prefilling into a free slot: the prompt is padded to a
  power-of-2 bucket (``scheduler.power_of_two_buckets``), the prefill program
  writes the slot's K/V rows via ``dynamic_update_slice`` and samples the
  first token in-graph. Compile cache over any workload: ``len(buckets)``
  prefill programs + 1 decode step (asserted by ``trace_count``).
* Sequences LEAVE when they emit eos / hit max_new_tokens — the slot is freed
  host-side (the freed row keeps computing garbage that nothing reads; rows
  are independent through the network, so active slots are unaffected).
* Per-request sampling params ride IN-GRAPH as per-slot arrays (temperature /
  top_k / top_p + per-slot PRNG key chains split inside the step), so a batch
  mixing greedy and nucleus requests shares the single compiled step
  (``models.generation.sample_tokens``).

Greedy decoding through the engine is token-for-token identical to
sequential ``models.generate`` (tested), which is what makes continuous
batching a pure throughput win rather than a quality trade.
"""
from __future__ import annotations

import contextlib
import threading
import time
import weakref
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..observability import trace as obstrace
from .metrics import ServingMetrics
from .scheduler import FCFSScheduler, Request, power_of_two_buckets

__all__ = ["ContinuousBatchingEngine"]

# Tracing prefill_fn/step_fn temporarily hangs `_gen_cache` off the model's
# attention layers; two engines sharing one model object (multi-replica
# tests, A/B harnesses) must not trace concurrently or the attrs race —
# one trace reads the other's tracers and the tick dies. One lock per
# model, held only while a call may trace (first use of a bucket / step).
_MODEL_TRACE_LOCKS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_MODEL_TRACE_LOCKS_GUARD = threading.Lock()


def _model_trace_lock(model) -> threading.RLock:
    with _MODEL_TRACE_LOCKS_GUARD:
        lock = _MODEL_TRACE_LOCKS.get(model)
        if lock is None:
            lock = _MODEL_TRACE_LOCKS[model] = threading.RLock()
        return lock


class ContinuousBatchingEngine:
    """Request-level serving engine over a fixed-capacity batched KV cache.

    ``model``: an eval-mode learned-position GPTForPretraining (rope needs
    per-slot rotary offsets in buffer mode — not wired, same restriction as
    ``inference.save_for_generation``). ``max_seq_len``: per-slot KV capacity
    S (prompt + generated must fit). ``prefill_buckets``: padded prompt
    lengths; defaults to power-of-2 buckets up to S.
    """

    def __init__(self, model, max_seq_len: int, n_slots: int = 8,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 scheduler: Optional[FCFSScheduler] = None,
                 metrics: Optional[ServingMetrics] = None,
                 max_queue: int = 64, max_prefills_per_tick: int = 2,
                 cache_dtype: str = "float32",
                 hbm_budget_bytes: Optional[int] = None,
                 admission_gate=None, shed_policy=None):
        import jax.numpy as jnp

        from ..models.gpt import GPTForPretraining

        if not isinstance(model, GPTForPretraining):
            raise TypeError("ContinuousBatchingEngine expects GPTForPretraining")
        cfg = model.gpt.config
        if cfg.position_embedding == "rope":
            raise NotImplementedError(
                "buffer-mode KV cache with rope is not wired "
                "(learned-position configs only)")
        from ..models.generation import _attn_layers

        model.eval()
        self.model = model
        self.n_slots = int(n_slots)
        self.max_seq_len = int(max_seq_len)
        self._layers = cfg.num_layers
        self._heads = cfg.num_attention_heads
        self._head_dim = cfg.head_dim
        self._attns = _attn_layers(model)
        buckets = (list(prefill_buckets) if prefill_buckets is not None
                   else power_of_two_buckets(self.max_seq_len))
        if max(buckets) > self.max_seq_len:
            raise ValueError("prefill bucket exceeds max_seq_len")
        self.scheduler = scheduler or FCFSScheduler(
            buckets, max_queue=max_queue,
            max_prefills_per_tick=max_prefills_per_tick)
        self.metrics = metrics or ServingMetrics()
        self.metrics.n_slots = self.n_slots

        # parameters are frozen for serving: snapshot once
        self._params = {n: p._data for n, p in model.named_parameters()}
        self._buffers = {n: b._data for n, b in model.named_buffers()}

        self._cache_dtype = jnp.dtype(cache_dtype)
        self._cache_shape = (self._layers, self.n_slots, self._heads,
                             self.max_seq_len, self._head_dim)
        self._kc = jnp.zeros(self._cache_shape, self._cache_dtype)
        self._vc = jnp.zeros(self._cache_shape, self._cache_dtype)
        # per-slot decode-state (host mirrors, shipped to device each tick)
        self._tok = np.zeros((self.n_slots,), np.int32)
        self._pos = np.zeros((self.n_slots,), np.int32)
        self._active = np.zeros((self.n_slots,), bool)
        self._temp = np.zeros((self.n_slots,), np.float32)
        self._topk = np.zeros((self.n_slots,), np.int32)
        self._topp = np.ones((self.n_slots,), np.float32)
        self._keys = np.zeros((self.n_slots, 2), np.uint32)
        self._slots: List[Optional[Request]] = [None] * self.n_slots
        self._seed_counter = 0
        # trace counters: the jitted bodies below run ONLY when jax traces a
        # new program, so these count compiles — the bounded-compile-cache
        # acceptance gauge (len(buckets) prefills + 1 step)
        self.trace_counts: Dict[str, int] = {"prefill": 0, "step": 0}
        self._step_jit = None
        self._prefill_jit = None
        self._trace_lock = _model_trace_lock(model)
        self._traced_buckets: set = set()  # prefill avals already compiled
        self._lock = threading.Lock()  # engine tick mutual exclusion
        self._abort = threading.Event()  # crash simulation: loop exits, NO drain
        self._build_programs()
        # overload protection (serving/admission.py), both opt-in: the
        # gate prices each request's prefill against an HBM budget with
        # the r10 liveness estimator; the shed policy bounds queue wait
        # under sustained overload by failing the oldest queued work
        if admission_gate is None and hbm_budget_bytes is not None:
            from .admission import AdmissionGate

            admission_gate = AdmissionGate(self, hbm_budget_bytes)
        self.admission_gate = admission_gate
        self.shed_policy = shed_policy.bind(self) if shed_policy else None

    # -- traced programs ----------------------------------------------------
    def _build_programs(self):
        import jax
        import jax.numpy as jnp

        from ..autograd.tape import no_grad
        from ..models.generation import sample_tokens
        from ..ops._primitive import unwrap, wrap
        from ..profiler.scope import scope

        model, attns = self.model, self._attns
        heads, hd, s = self._heads, self._head_dim, self.max_seq_len

        def _forward(params, buffers, ids_t, position_ids_t):
            out, _ = model.functional_call_with_state(
                params, buffers, ids_t, position_ids_t)
            return unwrap(out)

        def prefill_fn(params, buffers, ids, length, slot, key, temp,
                       topk, topp, kc, vc):
            # ids [1, Tb] bucket-padded; length = real prompt length; the
            # causal mask keeps pad positions out of row length-1's logits
            self.trace_counts["prefill"] += 1
            zeros = jnp.zeros((1, heads, s, hd), kc.dtype)
            pos0 = jnp.zeros((1,), jnp.int32)
            for a in attns:
                a._gen_cache = {"mode": "buffer", "k": zeros, "v": zeros,
                                "pos": pos0}
            try:
                with no_grad():
                    logits = _forward(params, buffers, wrap(ids), None)
                ks = jnp.stack([unwrap(a._gen_cache["k"]) for a in attns])
                vs = jnp.stack([unwrap(a._gen_cache["v"]) for a in attns])
            finally:
                for a in attns:
                    if hasattr(a, "_gen_cache"):
                        del a._gen_cache
            z = jnp.zeros((), jnp.int32)
            slot = slot.astype(jnp.int32)
            # the slot row is REPLACED wholesale (pad rows beyond the prompt
            # are zeros, overwritten again as decode advances), so freed
            # slots can't leak K/V into their successors
            kc = jax.lax.dynamic_update_slice(kc, ks.astype(kc.dtype),
                                              (z, slot, z, z, z))
            vc = jax.lax.dynamic_update_slice(vc, vs.astype(vc.dtype),
                                              (z, slot, z, z, z))
            last = jax.lax.dynamic_slice(
                logits, (jnp.zeros((), jnp.int32), length - 1,
                         jnp.zeros((), jnp.int32)),
                (1, 1, logits.shape[-1]))[:, 0]
            key, sub = jax.random.split(key)
            # named region (r6 scope, r14 perf-doctor row): the sampling
            # machinery is real per-token work, not model compute — it
            # must be attributable, not "(unscoped)"
            with scope("serving.sample"):
                first = sample_tokens(last.astype(jnp.float32), sub,
                                      temp, topk, topp)[0]
            return first.astype(jnp.int32), key, kc, vc

        def step_fn(params, buffers, tok, pos, active, temp, topk, topp,
                    keys, kc, vc):
            # tok [n,1] last sampled token per slot; pos [n] its position
            self.trace_counts["step"] += 1
            posj = pos.astype(jnp.int32)
            for li, a in enumerate(attns):
                a._gen_cache = {"mode": "buffer", "k": kc[li], "v": vc[li],
                                "pos": posj}
            try:
                with no_grad():
                    logits = _forward(params, buffers, wrap(tok),
                                      wrap(posj[:, None]))
                ks = jnp.stack([unwrap(a._gen_cache["k"]) for a in attns])
                vs = jnp.stack([unwrap(a._gen_cache["v"]) for a in attns])
            finally:
                for a in attns:
                    if hasattr(a, "_gen_cache"):
                        del a._gen_cache
            pair = jax.vmap(lambda k_: jax.random.split(k_))(keys)
            with scope("serving.sample"):
                nxt = sample_tokens(
                    logits[:, -1].astype(jnp.float32),
                    pair[:, 1], temp, topk, topp).astype(jnp.int32)
            nxt = jnp.where(active, nxt, 0)
            new_tok = jnp.where(active, nxt, tok[:, 0])[:, None]
            new_pos = jnp.where(active, posj + 1, posj)
            new_keys = jnp.where(active[:, None], pair[:, 0], keys)
            return nxt, new_tok, new_pos, new_keys, ks.astype(kc.dtype), \
                vs.astype(vc.dtype)

        # donate the K/V caches and the PRNG key chains: the engine replaces
        # them with the returned buffers every call, so XLA can update in
        # place instead of copying the full [L, n_slots, H, S, D] pair per
        # token.  The intended donation is recorded unconditionally (the
        # analysis donation-miss rule lints against it — the TPU deployment
        # contract) but applied only off-CPU, where XLA honors aliasing
        # (donating on CPU just warns per program).
        self._donate_prefill = (5, 9, 10)   # key, kc, vc
        self._donate_step = (8, 9, 10)      # keys, kc, vc
        on_cpu = jax.default_backend() == "cpu"
        self._prefill_jit = jax.jit(
            prefill_fn, donate_argnums=() if on_cpu else self._donate_prefill)
        self._step_jit = jax.jit(
            step_fn, donate_argnums=() if on_cpu else self._donate_step)

    # -- public API ---------------------------------------------------------
    @property
    def trace_count(self) -> int:
        """Total compiled programs (prefill buckets used + decode step)."""
        return self.trace_counts["prefill"] + self.trace_counts["step"]

    def free_slots(self) -> int:
        return int((~self._active).sum())

    def active_slots(self) -> int:
        return int(self._active.sum())

    def submit(self, prompt, **kwargs) -> Request:
        """Admit one request (FCFS). Raises QueueFullError / SchedulerClosed
        on backpressure/drain and ValueError on capacity violations."""
        req = prompt if isinstance(prompt, Request) else Request(prompt, **kwargs)
        if req.prompt.size + req.max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt ({req.prompt.size}) + max_new_tokens "
                f"({req.max_new_tokens}) exceeds KV capacity "
                f"max_seq_len={self.max_seq_len}")
        if req.deadline_expired():
            # dead on arrival: refuse up front (503) — queueing it would
            # only burn a prefill the client has already given up on
            from .admission import DeadlineExceededError

            self.metrics.on_reject()
            raise DeadlineExceededError(
                f"request {req.request_id} arrived with its deadline "
                f"already elapsed (deadline_s={req.deadline_s})")
        if self.admission_gate is not None:
            try:
                self.admission_gate.check(req)
            except Exception:
                self.metrics.on_reject()
                raise
        try:
            self.scheduler.submit(req)
        except Exception:
            self.metrics.on_reject()
            raise
        self.metrics.on_submit()
        return req

    # -- engine ticks -------------------------------------------------------
    def _admit_one(self, req: Request, slot_idx: int) -> bool:
        """Prefill ``req`` into ``slot_idx``; False when the request finished
        at prefill (slot stays free)."""
        import jax
        import jax.numpy as jnp

        from ..profiler.scope import scope

        t0 = req.prompt.size
        bucket = req.bucket or self.scheduler.bucket_for(t0)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :t0] = req.prompt
        if req.seed is None:
            self._seed_counter += 1
            seed = self._seed_counter
        else:
            seed = int(req.seed)
        key = jax.random.PRNGKey(seed)
        before = self.trace_counts["prefill"]
        # request-scoped spans: queue wait is recorded retrospectively
        # (submit → this admission), and the prefill span parents the
        # per-token decode spans — route ⊃ queue ⊃ prefill ⊃ decode
        queue_span = None
        if obstrace.tracing_enabled() and req.trace_id is not None:
            queue_span = obstrace.record_span(
                "serving.queue_wait", ts=req.submitted_wall,
                dur=time.perf_counter() - req.submitted_at,
                trace_id=req.trace_id, parent_id=req.parent_span_id,
                attrs={"request_id": req.request_id})
        t_prefill_wall, t_prefill = time.time(), time.perf_counter()
        # first use of a bucket traces, and tracing mutates the SHARED
        # model's attention layers — exclude other engines on this model
        guard = (contextlib.nullcontext() if bucket in self._traced_buckets
                 else self._trace_lock)
        with scope("serving.prefill"), guard:
            first, key, self._kc, self._vc = self._prefill_jit(
                self._params, self._buffers, jnp.asarray(ids),
                jnp.asarray(np.int32(t0)), jnp.asarray(np.int32(slot_idx)),
                key, jnp.float32(req.temperature),
                jnp.int32(-1 if req.top_k is None else req.top_k),
                jnp.float32(1.0 if req.top_p is None else req.top_p),
                self._kc, self._vc)
        self._traced_buckets.add(bucket)
        compiled = self.trace_counts["prefill"] > before
        if queue_span is not None:
            prefill_span = obstrace.record_span(
                "serving.prefill", ts=t_prefill_wall,
                dur=time.perf_counter() - t_prefill,
                trace_id=req.trace_id, parent_id=queue_span.span_id,
                attrs={"request_id": req.request_id, "bucket": int(bucket),
                       "prompt_len": int(t0), "slot": int(slot_idx),
                       "compiled": compiled})
            # record_span returns None if tracing was disabled between the
            # two records — a telemetry toggle must never fail the tick
            if prefill_span is not None:
                req._decode_span_parent = prefill_span.span_id
        self.metrics.on_prefill(compiled)
        first = int(first)
        req.state = Request.RUNNING
        req._append(first)
        self.metrics.on_first_token(req.first_token_at - req.submitted_at,
                                    trace_id=req.trace_id)
        self.metrics.on_tokens(1)
        if self._request_finished(req, first):
            # done at prefill (max_new=1 or instant eos): never activate
            self._retire(slot_idx, req)
            return False
        self._slots[slot_idx] = req
        self._active[slot_idx] = True
        self._tok[slot_idx] = first
        self._pos[slot_idx] = t0
        self._temp[slot_idx] = req.temperature
        self._topk[slot_idx] = -1 if req.top_k is None else req.top_k
        self._topp[slot_idx] = 1.0 if req.top_p is None else req.top_p
        self._keys[slot_idx] = np.asarray(key, np.uint32)
        return True

    def _request_finished(self, req: Request, token: int) -> bool:
        if req.eos_token_id is not None and token == req.eos_token_id:
            return True
        return len(req.tokens) >= req.max_new_tokens

    def _retire(self, slot_idx: int, req: Request):
        req._finish(Request.DONE)
        self.metrics.on_complete()

    def _fail_deadline(self, req: Request):
        from .admission import DEADLINE_ERROR_TYPE

        waited = time.perf_counter() - req.submitted_at
        req._finish(
            Request.FAILED,
            f"{DEADLINE_ERROR_TYPE}: deadline_s={req.deadline_s} elapsed "
            f"after {waited:.3f}s in queue (shed before prefill)",
            error_type=DEADLINE_ERROR_TYPE)
        self.metrics.on_shed("deadline")

    def _fail_shed(self, req: Request):
        from .admission import SHED_ERROR_TYPE

        hint = self.metrics.retry_after_hint(
            queue_depth=self.scheduler.depth())
        req._finish(
            Request.FAILED,
            f"{SHED_ERROR_TYPE}: shed under sustained overload before "
            f"prefill; retry after {hint:.1f}s",
            error_type=SHED_ERROR_TYPE)
        self.metrics.on_shed("overload")

    def step_once(self) -> bool:
        """One engine tick: admit waiting requests into free slots (bounded
        by the scheduler's interleave policy), then run ONE decode step for
        every active slot. Returns False when there was nothing to do."""
        import jax.numpy as jnp

        from ..profiler.scope import scope
        from ..resilience.inject import fire as _inject_fire

        # injection seam: a raised fault propagates into serve_forever's
        # containment (deterministic replay of the poison-tick suite), a
        # stall sleeps here — both without touching engine state. Fired
        # only on PRODUCTIVE ticks: idle polls are timing-dependent and
        # must not advance trigger counts
        if self._active.any() or self.scheduler.depth() > 0:
            _inject_fire("engine.tick",
                         replica=getattr(self, "_replica_addr", None))
        with self._lock:
            did = False
            # queue hygiene before admissions: drop work whose deadline
            # already elapsed — it can never start in time, so it must
            # not consume an admission slot (failed VISIBLY, typed error
            # via poll/stream, never silently)
            for req in self.scheduler.sweep_expired():
                self._fail_deadline(req)
                did = True
            free = [i for i in range(self.n_slots) if not self._active[i]]
            if free:
                for req in self.scheduler.take_admissions(len(free)):
                    slot = free.pop(0)
                    if req.deadline_expired():
                        # the mid-queue-expiry race: the deadline lapsed
                        # between the pop and this prefill — shed NOW,
                        # never burn a prefill on a dead request
                        self._fail_deadline(req)
                        self.scheduler.admission_settled()
                        free.insert(0, slot)
                        did = True
                        continue
                    try:
                        occupied = self._admit_one(req, slot)
                    except Exception as e:
                        # a poison request must not take down the queue:
                        # fail IT (it already left the scheduler) and move on
                        msg = f"prefill failed: {type(e).__name__}: {e}"
                        req._finish(Request.FAILED, msg)
                        occupied = False
                        if self._cache_lost():
                            # the donated cache died with the call: in-flight
                            # slots lost their K/V — fail them, fresh cache
                            for j, r2 in enumerate(self._slots):
                                if r2 is not None:
                                    r2._finish(Request.FAILED, msg)
                                    self._slots[j] = None
                                    self._active[j] = False
                            self._reset_cache()
                    finally:
                        self.scheduler.admission_settled()
                    if not occupied:
                        free.append(slot)  # finished/failed at prefill
                    did = True
            # overload policy AFTER admissions: everything still queued
            # here genuinely waits at least a tick, so the shed target
            # never fails a request that could have started right now
            # (and free slots are never idled by the trim)
            if self.shed_policy is not None:
                for req in self.shed_policy.victims(self.scheduler):
                    self._fail_shed(req)
                    did = True
            if self._active.any():
                before = self.trace_counts["step"]
                t_step_wall = time.time()
                t_step = time.perf_counter()
                guard = (self._trace_lock if self.trace_counts["step"] == 0
                         else contextlib.nullcontext())
                with scope("serving.decode_step"), guard:
                    nxt, tok, pos, keys, self._kc, self._vc = self._step_jit(
                        self._params, self._buffers,
                        jnp.asarray(self._tok[:, None]),
                        jnp.asarray(self._pos), jnp.asarray(self._active),
                        jnp.asarray(self._temp), jnp.asarray(self._topk),
                        jnp.asarray(self._topp), jnp.asarray(self._keys),
                        self._kc, self._vc)
                nxt = np.asarray(nxt)  # device sync: tokens must stream out
                step_s = time.perf_counter() - t_step
                self.metrics.on_step(self.trace_counts["step"] > before)
                # np.array COPIES: device views are read-only, and slots
                # mutate these between steps
                self._tok = np.array(tok)[:, 0]
                self._pos = np.array(pos)
                self._keys = np.array(keys)
                emitted = 0
                spans_on = obstrace.tracing_enabled()
                for i in range(self.n_slots):
                    req = self._slots[i]
                    if req is None or not self._active[i]:
                        continue
                    token = int(nxt[i])
                    req._append(token)
                    emitted += 1
                    if spans_on and req.trace_id is not None:
                        # one span per generated token: the slot shares the
                        # batched step's wall interval (they decode together)
                        obstrace.record_span(
                            "serving.decode_token", ts=t_step_wall,
                            dur=step_s, trace_id=req.trace_id,
                            parent_id=req._decode_span_parent,
                            attrs={"request_id": req.request_id,
                                   "token_index": len(req.tokens) - 1,
                                   "slot": i})
                    if self._request_finished(req, token):
                        self._retire(i, req)
                        self._slots[i] = None
                        self._active[i] = False
                self.metrics.on_tokens(emitted, step_seconds=step_s)
                did = True
            self.metrics.set_gauges(self.scheduler.depth(),
                                    self.active_slots(), self.n_slots)
            return did

    def run_until_idle(self, timeout: Optional[float] = None):
        """Drive ticks until the queue is empty and every slot is free
        (used by tests, bench, and graceful drain)."""
        deadline = None if timeout is None else time.perf_counter() + timeout
        while self.scheduler.depth() > 0 or self._active.any():
            if deadline is not None and time.perf_counter() > deadline:
                raise TimeoutError("engine did not drain in time")
            self.step_once()

    def _cache_lost(self) -> bool:
        """True when a failed DONATED call already consumed the K/V buffers
        (jax invalidates donated inputs even if the computation errors)."""
        try:
            return bool(self._kc.is_deleted() or self._vc.is_deleted())
        except Exception:
            return False

    def _reset_cache(self):
        import jax.numpy as jnp

        self._kc = jnp.zeros(self._cache_shape, self._cache_dtype)
        self._vc = jnp.zeros(self._cache_shape, self._cache_dtype)

    def fail_pending(self, error: str, _locked: bool = False):
        """Fail every in-flight slot and queued request with ``error`` —
        the engine loop's containment path: clients polling/streaming see
        state FAILED instead of hanging on a silently dead loop thread.
        Reallocates the K/V cache if the failed call donated it away, so
        the engine keeps serving future requests."""
        ctx = contextlib.nullcontext() if _locked else self._lock
        with ctx:
            for i, req in enumerate(self._slots):
                if req is not None:
                    req._finish(Request.FAILED, error)
                    self._slots[i] = None
                    self._active[i] = False
            while self.scheduler.depth() > 0:  # interleave cap bounds each pop
                for req in self.scheduler.take_admissions(self.scheduler.depth()):
                    req._finish(Request.FAILED, error)
                    self.scheduler.admission_settled()
            if self._cache_lost():
                self._reset_cache()
            self.metrics.set_gauges(self.scheduler.depth(),
                                    self.active_slots(), self.n_slots)

    def abort(self):
        """Abrupt-death hook (chaos testing / emergency teardown): the loop
        thread exits at its next iteration WITHOUT draining — queued and
        in-flight requests are simply orphaned, exactly like a SIGKILLed
        replica process. Failover responsibility moves to the serving
        router, which is the point of simulating it."""
        self._abort.set()

    def serve_forever(self, stop_event: threading.Event, idle_wait: float = 0.02):
        """Engine loop for a server thread: tick while there is work; block
        briefly on the admission queue when idle; exit when ``stop_event``
        is set AND all admitted work has drained (graceful drain). A tick
        that raises fails the affected requests (state FAILED, error
        recorded) instead of silently killing the loop thread."""
        from ..resilience.inject import fire as _inject_fire

        while not self._abort.is_set():
            # replica-death injection seam: counted only on PRODUCTIVE
            # ticks (work queued or slots active) so trigger counts are
            # deterministic — idle-wait iterations are timing-dependent
            # and must not advance the schedule
            try:
                # inside the try: a raise-kind fault at this point is
                # contained like any tick failure below, never a
                # silently dead loop thread
                if self._active.any() or self.scheduler.depth() > 0:
                    f = _inject_fire(
                        "replica.tick",
                        replica=getattr(self, "_replica_addr", None))
                    if f is not None and f.kind == "kill":
                        # abrupt simulated SIGKILL: tear the whole
                        # replica down (HTTP plane included, via the
                        # server's kill hook) from a helper thread —
                        # kill() joins THIS thread, so it cannot run
                        # here — and exit the loop with no drain;
                        # queued/in-flight work is orphaned
                        kill_cb = getattr(self, "_server_kill", None)
                        self._abort.set()
                        if kill_cb is not None:
                            threading.Thread(target=kill_cb,
                                             daemon=True).start()
                        return
                did = self.step_once()
            except Exception as e:  # contain: fail work, keep serving
                err = f"engine tick failed: {type(e).__name__}: {e}"
                # flight-record the failure BEFORE failing the requests:
                # the ring still holds the spans leading up to the tick
                from ..observability.flight import flight_recorder

                flight_recorder().dump("engine_tick_failure",
                                       extra={"error": err})
                self.fail_pending(err)
                did = False
            if did:
                continue
            if stop_event.is_set() and self.scheduler.depth() == 0 \
                    and not self._active.any():
                return
            self.scheduler.wait_for_work(idle_wait)

    def generate_batch(self, requests: Sequence[Request],
                       timeout: Optional[float] = None) -> List[np.ndarray]:
        """Convenience: submit all, drain, return per-request results
        (prompt + generated, int64 — models.generate's layout). Raises if
        any request FAILED — a partial token log must not pass for a
        legitimate early-eos completion."""
        reqs = [self.submit(r) for r in requests]
        self.run_until_idle(timeout=timeout)
        failed = [r for r in reqs if r.state == Request.FAILED]
        if failed:
            raise RuntimeError(
                f"{len(failed)}/{len(reqs)} requests failed; first: "
                f"{failed[0].request_id}: {failed[0].error}")
        return [r.result() for r in reqs]
