"""Serving metrics — the observability half of the serving engine.

Parity: the reference's serving stack exports per-request latency and
throughput counters from its brpc workers (Paddle Serving's
``op_latency``/``qps`` vars); here one thread-safe registry owns the
continuous-batching engine's numbers:

* **TTFT** (time-to-first-token: submit → first sampled token),
* **per-token latency** (decode-step wall time — every active slot gets
  exactly one token per step),
* **throughput** (generated tokens/sec over the emission window),
* **queue depth** and **slot occupancy** gauges,
* **compile-cache counters** (bucketed prefill + decode-step traces vs
  calls — the bounded-compile-cache guarantee, observable).

The engine also brackets its prefill/step dispatches with
``profiler.scope("serving.prefill"/"serving.decode_step")`` so the same
regions land in the profiler's :class:`TimerRegistry` when timers are armed
(host spans) and in HLO metadata inside the traced programs (device traces);
:meth:`snapshot` folds any ``serving.*`` timer rows in, which is what the
``/metrics`` endpoint serves.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional

__all__ = ["ServingMetrics", "percentile"]


def percentile(samples, q: float) -> Optional[float]:
    """Nearest-rank percentile (q in [0, 100]) of a sequence; None if empty."""
    if not samples:
        return None
    s = sorted(samples)
    idx = max(0, min(len(s) - 1, int(round(q / 100.0 * (len(s) - 1)))))
    return s[idx]


class ServingMetrics:
    """Thread-safe counters/gauges/samples for one serving engine."""

    def __init__(self, max_samples: int = 4096):
        self._lock = threading.Lock()
        self.requests_submitted = 0
        self.requests_rejected = 0
        self.requests_completed = 0
        self.tokens_generated = 0
        self.prefill_calls = 0
        self.prefill_compiles = 0
        self.step_calls = 0
        self.step_compiles = 0
        self.queue_depth = 0
        self.active_slots = 0
        self.n_slots = 0
        self._ttft = deque(maxlen=max_samples)
        self._token_lat = deque(maxlen=max_samples)
        self._first_emit: Optional[float] = None
        self._last_emit: Optional[float] = None

    # -- counters -----------------------------------------------------------
    def on_submit(self):
        with self._lock:
            self.requests_submitted += 1

    def on_reject(self):
        with self._lock:
            self.requests_rejected += 1

    def on_complete(self):
        with self._lock:
            self.requests_completed += 1

    def on_first_token(self, ttft_seconds: float):
        with self._lock:
            self._ttft.append(ttft_seconds)

    def on_tokens(self, n: int, step_seconds: Optional[float] = None):
        now = time.perf_counter()
        with self._lock:
            self.tokens_generated += n
            if self._first_emit is None:
                self._first_emit = now
            self._last_emit = now
            if step_seconds is not None and n > 0:
                self._token_lat.append(step_seconds)

    def on_prefill(self, compiled: bool):
        with self._lock:
            self.prefill_calls += 1
            if compiled:
                self.prefill_compiles += 1

    def on_step(self, compiled: bool):
        with self._lock:
            self.step_calls += 1
            if compiled:
                self.step_compiles += 1

    # -- gauges (engine-owned, set each tick) -------------------------------
    def set_gauges(self, queue_depth: int, active_slots: int, n_slots: int):
        with self._lock:
            self.queue_depth = queue_depth
            self.active_slots = active_slots
            self.n_slots = n_slots

    def retry_after_hint(self, queue_depth: Optional[int] = None) -> float:
        """Seconds a 429'd client should wait before retrying: the queued
        work ahead of it (queue depth × mean generated tokens per completed
        request) at the CURRENT measured token rate. Floors at 1s when the
        engine has no rate history yet; capped at 60s so a stale rate can't
        tell clients to go away for minutes."""
        tput = self.tokens_per_sec()
        with self._lock:
            depth = self.queue_depth if queue_depth is None else int(queue_depth)
            completed = self.requests_completed
            tokens = self.tokens_generated
        if not tput or tput <= 0 or completed <= 0 or depth <= 0:
            return 1.0
        eta = depth * (tokens / completed) / tput
        return float(min(max(eta, 1.0), 60.0))

    # -- snapshot -----------------------------------------------------------
    def tokens_per_sec(self) -> Optional[float]:
        with self._lock:
            if (self._first_emit is None or self._last_emit is None
                    or self._last_emit <= self._first_emit):
                return None
            return self.tokens_generated / (self._last_emit - self._first_emit)

    def snapshot(self) -> Dict:
        """JSON-ready view (the ``/metrics`` endpoint body)."""
        tput = self.tokens_per_sec()
        with self._lock:
            ttft = list(self._ttft)
            lat = list(self._token_lat)
            out = {
                "requests": {
                    "submitted": self.requests_submitted,
                    "rejected": self.requests_rejected,
                    "completed": self.requests_completed,
                },
                "tokens_generated": self.tokens_generated,
                "throughput_tokens_per_sec": tput,
                "ttft_seconds": {
                    "count": len(ttft),
                    "p50": percentile(ttft, 50),
                    "p95": percentile(ttft, 95),
                },
                "token_latency_seconds": {
                    "count": len(lat),
                    "p50": percentile(lat, 50),
                    "p95": percentile(lat, 95),
                },
                "queue_depth": self.queue_depth,
                "slot_occupancy": {
                    "active": self.active_slots,
                    "total": self.n_slots,
                    "fraction": (self.active_slots / self.n_slots
                                 if self.n_slots else 0.0),
                },
                "compile_cache": {
                    "prefill_calls": self.prefill_calls,
                    "prefill_compiles": self.prefill_compiles,
                    "prefill_hits": self.prefill_calls - self.prefill_compiles,
                    "step_calls": self.step_calls,
                    "step_compiles": self.step_compiles,
                    "step_hits": self.step_calls - self.step_compiles,
                },
            }
        # fold in any armed profiler host spans for the serving regions
        try:
            from ..profiler.scope import timer_report

            spans = {k: v for k, v in timer_report().items()
                     if k.startswith("serving.")}
            if spans:
                out["profiler_spans"] = spans
        except Exception:
            pass
        return out
