"""Serving metrics — the observability half of the serving engine.

Parity: the reference's serving stack exports per-request latency and
throughput counters from its brpc workers (Paddle Serving's
``op_latency``/``qps`` vars); here one thread-safe registry owns the
continuous-batching engine's numbers:

* **TTFT** (time-to-first-token: submit → first sampled token),
* **per-token latency** (decode-step wall time — every active slot gets
  exactly one token per step),
* **throughput** (generated tokens/sec over the emission window),
* **queue depth** and **slot occupancy** gauges,
* **compile-cache counters** (bucketed prefill + decode-step traces vs
  calls — the bounded-compile-cache guarantee, observable).

The engine also brackets its prefill/step dispatches with
``profiler.scope("serving.prefill"/"serving.decode_step")`` so the same
regions land in the profiler's :class:`TimerRegistry` when timers are armed
(host spans) and in HLO metadata inside the traced programs (device traces);
:meth:`snapshot` folds any ``serving.*`` timer rows in, which is what the
``/metrics`` endpoint serves.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional

__all__ = ["ServingMetrics", "percentile"]


def percentile(samples, q: float) -> Optional[float]:
    """Nearest-rank percentile (q in [0, 100]) of a sequence; None if empty."""
    if not samples:
        return None
    s = sorted(samples)
    idx = max(0, min(len(s) - 1, int(round(q / 100.0 * (len(s) - 1)))))
    return s[idx]


class ServingMetrics:
    """Thread-safe counters/gauges/samples for one serving engine.

    Every observation ALSO lands in an :class:`~paddle_tpu.observability
    .metrics.MetricsRegistry` (one per instance), which is what the
    Prometheus side of the ``/metrics`` endpoint exposes
    (:meth:`prometheus_text`); :meth:`snapshot`'s JSON body is unchanged
    — existing ``ServingClient``/router consumers parse byte-identical
    output."""

    def __init__(self, max_samples: int = 4096, registry=None):
        from ..observability.flight import register_metrics_registry
        from ..observability.metrics import MetricsRegistry, log_buckets

        self._lock = threading.Lock()
        self.requests_submitted = 0   # guarded-by: self._lock
        self.requests_rejected = 0    # guarded-by: self._lock
        self.requests_completed = 0   # guarded-by: self._lock
        self.requests_shed = 0        # guarded-by: self._lock
        self.tokens_generated = 0     # guarded-by: self._lock
        self.prefill_calls = 0        # guarded-by: self._lock
        self.prefill_compiles = 0     # guarded-by: self._lock
        self.step_calls = 0           # guarded-by: self._lock
        self.step_compiles = 0        # guarded-by: self._lock
        self.queue_depth = 0          # guarded-by: self._lock
        self.active_slots = 0         # guarded-by: self._lock
        self.n_slots = 0              # guarded-by: self._lock
        self._ttft = deque(maxlen=max_samples)       # guarded-by: self._lock
        self._token_lat = deque(maxlen=max_samples)  # guarded-by: self._lock
        # guarded-by: self._lock
        self._first_emit: Optional[float] = None
        # guarded-by: self._lock
        self._last_emit: Optional[float] = None
        r = self.registry = registry or MetricsRegistry()
        # crash dumps must freeze THIS engine's series, not just the
        # process registry (weak attachment: dies with the engine)
        register_metrics_registry("serving", r)
        self._c_submitted = r.counter(
            "serving_requests_submitted_total", "requests admitted")
        self._c_rejected = r.counter(
            "serving_requests_rejected_total", "requests rejected (429/503)")
        self._c_completed = r.counter(
            "serving_requests_completed_total", "requests finished")
        self._c_shed = r.counter(
            "serving_requests_shed_total",
            "queued requests shed before prefill", ("reason",))
        self._c_tokens = r.counter(
            "serving_tokens_generated_total", "generated tokens")
        self._c_prefills = r.counter(
            "serving_prefill_calls_total", "prefill program dispatches",
            ("compiled",))
        self._c_steps = r.counter(
            "serving_decode_steps_total", "decode step dispatches",
            ("compiled",))
        lat = log_buckets(1e-4, 64.0)
        # exemplars on (r14): each latency bucket remembers the last
        # trace_id observed into it, so a p99 TTFT bucket links to the
        # exact serving.route span tree (OpenMetrics exposition only —
        # the 0.0.4 text and JSON snapshot stay byte-identical)
        self._h_ttft = r.histogram(
            "serving_ttft_seconds", "submit to first token", buckets=lat,
            exemplars=True)
        self._h_token = r.histogram(
            "serving_token_latency_seconds", "decode step wall time",
            buckets=lat, exemplars=True)
        self._g_queue = r.gauge("serving_queue_depth",
                                "admission queue depth")
        self._g_in_admission = r.gauge(
            "serving_in_admission", "requests popped but not yet placed")
        self._g_active = r.gauge("serving_active_slots",
                                 "occupied decode slots")
        self._g_slots = r.gauge("serving_slots_total", "decode slots")
        self._g_draining = r.gauge("serving_draining",
                                   "1 while admissions are closed")
        self._g_tput = r.gauge("serving_throughput_tokens_per_sec",
                               "generated-token rate over emission window")
        # block-paged KV pool (ISSUE 11): occupancy gauges + sharing
        # counters — zero/absent for the slot layout
        self._g_pages_free = r.gauge("serving_kv_pages_free",
                                     "free pages in the KV pool")
        self._g_pages_used = r.gauge("serving_kv_pages_used",
                                     "allocated pages in the KV pool")
        self._g_pages_shared = r.gauge(
            "serving_kv_pages_shared",
            "pages referenced by more than one owner (prefix sharing)")
        self._c_prefix_hits = r.counter(
            "serving_prefix_hits_total",
            "prompts that reused at least one resident prefix page")
        self._c_prefix_tokens = r.counter(
            "serving_prefix_tokens_shared_total",
            "prompt tokens whose prefill was skipped via prefix sharing")
        self._c_cow = r.counter(
            "serving_cow_pages_total",
            "copy-on-write page duplications (whole-prompt prefix hits)")
        self._c_continuations = r.counter(
            "serving_continuation_joins_total",
            "streams admitted mid-transcript (resurrection/migration joins)")
        self._c_continuation_tokens = r.counter(
            "serving_continuation_tokens_total",
            "observed tokens carried into continuation joins")
        self._c_exports = r.counter(
            "serving_streams_exported_total",
            "active streams exported to a peer (live migration source)")
        # speculative decoding (ISSUE 18): proposal/acceptance accounting
        self.spec_proposed = 0        # guarded-by: self._lock
        self.spec_accepted = 0        # guarded-by: self._lock
        self.spec_emitted = 0         # guarded-by: self._lock
        self.spec_verify_steps = 0    # guarded-by: self._lock
        self.spec_fallback_ticks = 0  # guarded-by: self._lock
        self.spec_rollback_pages = 0  # guarded-by: self._lock
        self._c_spec_proposed = r.counter(
            "serving_spec_tokens_proposed_total",
            "draft tokens proposed to the verifier")
        self._c_spec_accepted = r.counter(
            "serving_spec_tokens_accepted_total",
            "draft tokens accepted by the target verifier")
        self._c_spec_verifies = r.counter(
            "serving_spec_verify_steps_total",
            "per-stream verify passes (one target forward covers a batch)")
        self._c_spec_fallbacks = r.counter(
            "serving_spec_fallback_ticks_total",
            "ticks that fell back to plain decode (verify seam fault)")
        self._c_spec_rollbacks = r.counter(
            "serving_spec_rollback_pages_total",
            "lookahead KV pages released after draft-suffix rejection")
        self._page_state: Dict = {}
        self._prefix_hits_seen = 0
        self._prefix_tokens_seen = 0

    # -- counters -----------------------------------------------------------
    def on_submit(self):
        with self._lock:
            self.requests_submitted += 1
        self._c_submitted.inc()

    def on_reject(self):
        with self._lock:
            self.requests_rejected += 1
        self._c_rejected.inc()

    def on_complete(self):
        with self._lock:
            self.requests_completed += 1
        self._c_completed.inc()

    def on_shed(self, reason: str = "overload"):
        """A QUEUED request was failed before prefill (overload policy or
        deadline sweep) — visible shedding, labelled by why."""
        with self._lock:
            self.requests_shed += 1
        self._c_shed.inc(reason=str(reason))

    def on_first_token(self, ttft_seconds: float,
                       trace_id: Optional[str] = None):
        with self._lock:
            self._ttft.append(ttft_seconds)
        self._h_ttft.observe(ttft_seconds, trace_id=trace_id)

    def on_tokens(self, n: int, step_seconds: Optional[float] = None):
        now = time.perf_counter()
        with self._lock:
            self.tokens_generated += n
            if self._first_emit is None:
                self._first_emit = now
            self._last_emit = now
            if step_seconds is not None and n > 0:
                self._token_lat.append(step_seconds)
        if n > 0:
            self._c_tokens.inc(n)
            if step_seconds is not None:
                self._h_token.observe(step_seconds)

    def on_prefill(self, compiled: bool):
        with self._lock:
            self.prefill_calls += 1
            if compiled:
                self.prefill_compiles += 1
        self._c_prefills.inc(compiled="true" if compiled else "false")

    def on_step(self, compiled: bool):
        with self._lock:
            self.step_calls += 1
            if compiled:
                self.step_compiles += 1
        self._c_steps.inc(compiled="true" if compiled else "false")

    def on_continuation(self, n_observed: int):
        """One continuation join admitted (a resurrected or migrated
        stream resuming mid-transcript), carrying ``n_observed`` tokens
        already generated elsewhere — those are NOT re-counted as emitted
        tokens here (their first home counted them)."""
        self._c_continuations.inc()
        if n_observed > 0:
            self._c_continuation_tokens.inc(int(n_observed))

    def on_export(self):
        """One active stream exported to a peer (live-migration source)."""
        self._c_exports.inc()

    def on_spec_verify(self, proposed: int, accepted: int, emitted: int):
        """One stream's verify outcome this tick: ``proposed`` draft
        tokens went in, ``accepted`` matched the target's samples, and
        ``emitted`` tokens actually landed on the request (``accepted+1``
        unless the stream finished mid-block)."""
        with self._lock:
            self.spec_proposed += proposed
            self.spec_accepted += accepted
            self.spec_emitted += emitted
            self.spec_verify_steps += 1
        if proposed > 0:
            self._c_spec_proposed.inc(proposed)
        if accepted > 0:
            self._c_spec_accepted.inc(accepted)
        self._c_spec_verifies.inc()

    def on_spec_fallback(self):
        """One tick degraded to the plain (non-speculative) decode step
        after a verify-seam fault — correctness preserved, speedup lost."""
        with self._lock:
            self.spec_fallback_ticks += 1
        self._c_spec_fallbacks.inc()

    def on_spec_rollback(self, pages: int):
        """Lookahead pages released because the draft suffix they were
        allocated for was rejected by the verifier."""
        if pages <= 0:
            return
        with self._lock:
            self.spec_rollback_pages += pages
        self._c_spec_rollbacks.inc(int(pages))

    def on_cow(self):
        """One copy-on-write page duplication (a whole-prompt prefix hit
        recomputing its final token into a private page copy)."""
        self._c_cow.inc()

    def set_page_gauges(self, state: Dict):
        """Fold the engine's :meth:`~ContinuousBatchingEngine.page_state`
        into the registry (gauges) and the prefix-sharing counters
        (monotonic — the engine reports totals, the registry wants
        increments)."""
        if not state:
            return
        with self._lock:
            self._page_state = dict(state)
            hits = int(state.get("prefix_hits", 0))
            toks = int(state.get("prefix_hit_tokens", 0))
            d_hits = max(hits - self._prefix_hits_seen, 0)
            d_toks = max(toks - self._prefix_tokens_seen, 0)
            self._prefix_hits_seen = hits
            self._prefix_tokens_seen = toks
        self._g_pages_free.set(int(state.get("free", 0)))
        self._g_pages_used.set(int(state.get("used", 0)))
        self._g_pages_shared.set(int(state.get("shared", 0)))
        if d_hits:
            self._c_prefix_hits.inc(d_hits)
        if d_toks:
            self._c_prefix_tokens.inc(d_toks)

    # -- gauges (engine-owned, set each tick) -------------------------------
    def set_gauges(self, queue_depth: int, active_slots: int, n_slots: int):
        with self._lock:
            self.queue_depth = queue_depth
            self.active_slots = active_slots
            self.n_slots = n_slots
        self._g_queue.set(queue_depth)
        self._g_active.set(active_slots)
        self._g_slots.set(n_slots)

    def retry_after_hint(self, queue_depth: Optional[int] = None) -> float:
        """Seconds a 429'd client should wait before retrying: the queued
        work ahead of it (queue depth × mean generated tokens per completed
        request) at the CURRENT measured token rate. Floors at 1s when the
        engine has no rate history yet; capped at 60s so a stale rate can't
        tell clients to go away for minutes."""
        tput = self.tokens_per_sec()
        with self._lock:
            depth = self.queue_depth if queue_depth is None else int(queue_depth)
            completed = self.requests_completed
            tokens = self.tokens_generated
        if not tput or tput <= 0 or completed <= 0 or depth <= 0:
            return 1.0
        eta = depth * (tokens / completed) / tput
        return float(min(max(eta, 1.0), 60.0))

    # -- snapshot -----------------------------------------------------------
    def tokens_per_sec(self) -> Optional[float]:
        with self._lock:
            if (self._first_emit is None or self._last_emit is None
                    or self._last_emit <= self._first_emit):
                return None
            return self.tokens_generated / (self._last_emit - self._first_emit)

    def snapshot(self) -> Dict:
        """JSON-ready view (the ``/metrics`` endpoint body)."""
        tput = self.tokens_per_sec()
        with self._lock:
            ttft = list(self._ttft)
            lat = list(self._token_lat)
            out = {
                "requests": {
                    "submitted": self.requests_submitted,
                    "rejected": self.requests_rejected,
                    "completed": self.requests_completed,
                    "shed": self.requests_shed,
                },
                "tokens_generated": self.tokens_generated,
                "throughput_tokens_per_sec": tput,
                "ttft_seconds": {
                    "count": len(ttft),
                    "p50": percentile(ttft, 50),
                    "p95": percentile(ttft, 95),
                },
                "token_latency_seconds": {
                    "count": len(lat),
                    "p50": percentile(lat, 50),
                    "p95": percentile(lat, 95),
                },
                "queue_depth": self.queue_depth,
                "slot_occupancy": {
                    "active": self.active_slots,
                    "total": self.n_slots,
                    "fraction": (self.active_slots / self.n_slots
                                 if self.n_slots else 0.0),
                },
                "compile_cache": {
                    "prefill_calls": self.prefill_calls,
                    "prefill_compiles": self.prefill_compiles,
                    "prefill_hits": self.prefill_calls - self.prefill_compiles,
                    "step_calls": self.step_calls,
                    "step_compiles": self.step_compiles,
                    "step_hits": self.step_calls - self.step_compiles,
                },
            }
            if self.spec_verify_steps or self.spec_fallback_ticks:
                out["spec_decode"] = {
                    "proposed": self.spec_proposed,
                    "accepted": self.spec_accepted,
                    "emitted": self.spec_emitted,
                    "verify_steps": self.spec_verify_steps,
                    "fallback_ticks": self.spec_fallback_ticks,
                    "rollback_pages": self.spec_rollback_pages,
                    "acceptance_rate": (
                        self.spec_accepted / self.spec_proposed
                        if self.spec_proposed else None),
                    "accepted_per_verify": (
                        self.spec_emitted / self.spec_verify_steps
                        if self.spec_verify_steps else None),
                }
            if self._page_state:
                ps = dict(self._page_state)
                queries = ps.get("prefix_queries", 0)
                out["kv_pages"] = {
                    "capacity": ps.get("capacity"),
                    "free": ps.get("free"),
                    "used": ps.get("used"),
                    "shared": ps.get("shared"),
                    "page_bytes": ps.get("page_bytes"),
                    "cow_pages": ps.get("cow_pages", 0),
                    "prefix_hit_rate": (ps.get("prefix_hits", 0) / queries
                                        if queries else None),
                    "prefix_hit_tokens": ps.get("prefix_hit_tokens", 0),
                }
        # fold in any armed profiler host spans for the serving regions
        try:
            from ..profiler.scope import timer_report

            spans = {k: v for k, v in timer_report().items()
                     if k.startswith("serving.")}
            if spans:
                out["profiler_spans"] = spans
        except Exception:
            pass
        return out

    def _refresh_live(self, queue_depth=None, in_admission=None,
                      active_slots=None, n_slots=None, draining=None):
        """Fold the LIVE admission state the server reads at request time
        into the gauges — the same freshness rule the JSON body follows
        for the router's sake (shared by both text expositions)."""
        with self._lock:
            q = self.queue_depth if queue_depth is None else queue_depth
            a = self.active_slots if active_slots is None else active_slots
            n = self.n_slots if n_slots is None else n_slots
        self._g_queue.set(int(q))
        self._g_active.set(int(a))
        self._g_slots.set(int(n))
        if in_admission is not None:
            self._g_in_admission.set(int(in_admission))
        if draining is not None:
            self._g_draining.set(1 if draining else 0)
        tput = self.tokens_per_sec()
        if tput is not None:
            self._g_tput.set(tput)

    def prometheus_text(self, **live) -> str:
        """Prometheus 0.0.4 exposition of this engine's series (the
        negotiated side of ``/metrics``); keyword overrides as
        :meth:`_refresh_live`. Byte-identical with exemplars on or off."""
        self._refresh_live(**live)
        return self.registry.prometheus_text()

    def openmetrics_text(self, **live) -> str:
        """OpenMetrics exposition — same series, plus latency-bucket
        exemplars (``# {trace_id="..."}``) linking to request traces."""
        self._refresh_live(**live)
        return self.registry.openmetrics_text()
