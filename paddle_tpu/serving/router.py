"""Multi-replica serving router: health-checked least-loaded failover.

Parity: PaddlePaddle deploys inference behind Paddle Serving's multi-worker
front-end (N brpc workers behind a dispatcher) and the fleet
parameter-server's liveness-tracked worker pool; this is that capability for
the continuous-batching engine — N :class:`~.server.ServingServer` replicas
behind one router, surviving a replica dying mid-stream.

Mechanics:

* **Health + load** come from each replica's ``/metrics`` endpoint (the
  :class:`~.metrics.ServingMetrics` snapshot): liveness is "the endpoint
  answers", load is ``queue_depth + active_slots`` — new requests go to the
  least-loaded CLOSED replica (drain-marked replicas are never picked).
* **Circuit breaker** per replica: ``failure_threshold`` consecutive
  transport failures OPEN the breaker (ejected from routing); after
  ``cooldown_s`` it goes HALF_OPEN and the next health probe (or routed
  call) decides — success rejoins (CLOSED), failure re-opens.
* **Failover**: every routed request remembers the tokens the router has
  OBSERVED. When a replica dies, requests with zero observed tokens
  (queued / not yet prefilled) are resubmitted with backoff onto a
  surviving replica; requests that already streamed tokens are
  RESURRECTED — the observed transcript rides along as a continuation
  join, the survivor prefills prompt+observed and fast-forwards the PRNG
  key chain, and the continued stream is bit-identical to the
  uninterrupted run (greedy and sampled; the router pins seeds at entry).
  Only when no survivor can take the continuation does the stream settle
  FAILED, typed as :class:`ResurrectionFailedError`.
* **Live migration**: :meth:`ServingRouter.migrate` drains one stream off
  a replica between decode ticks — the source exports a CRC-stamped
  continuation record, the target continuation-prefills it, routing flips
  atomically, and a mid-migration death falls back to resurrection.
* **Drain-aware takedown**: :meth:`ServingRouter.drain` stops routing to a
  replica, asks it to close admissions (``POST /admin/drain``), and polls
  its metrics until queue and slots are empty — the replica can then be
  stopped with zero dropped queued requests.
"""
from __future__ import annotations

import http.client
import math
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..observability import trace as obstrace
from ..observability.metrics import MetricsHTTPServer, MetricsRegistry
from ..resilience.inject import fire as _inject_fire
from ..resilience.retry import RetryError, backoff_delays
from .admission import AdmissionRejected, DeadlineExceededError
from .engine import MIGRATED_ERROR_TYPE
from .scheduler import QueueFullError, Request, SchedulerClosed
from .server import RequestFailedError, ServingClient, StreamIncompleteError

__all__ = ["ServingRouter", "RoutedRequest", "NoReplicaAvailable",
           "ResurrectionFailedError"]


class NoReplicaAvailable(RuntimeError):
    """Every replica is ejected, draining, or unreachable — HTTP 503."""

    http_status = 503


class ResurrectionFailedError(RuntimeError):
    """A confirmed replica death orphaned an in-flight stream and NO
    survivor could take the continuation (all full/draining/unreachable,
    or the retry budget ran out) — the typed terminal verdict for the
    zero-loss path, never a silent retry loop. The router's observed
    token log is still intact on the RoutedRequest for salvage."""


class _Replica:
    """Router-side view of one engine replica (breaker + load gauges)."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, addr: str, timeout: float, probe_timeout: float):
        self.addr = addr
        # retries=0: the ROUTER owns retry policy — a dead replica must
        # surface immediately so failover starts, not after 4 backoffs
        self.client = ServingClient(addr, timeout=timeout, retries=0)
        # health probes get their own short-deadline client: the single
        # health thread walks every replica sequentially, so one SYN
        # black hole (host partitioned, not RST-ing) must cost
        # probe_timeout, not a full request_timeout per cycle — otherwise
        # the survivors' load gauges go stale and the corpse's breaker
        # takes threshold×request_timeout to open
        self.probe_client = ServingClient(addr, timeout=probe_timeout,
                                          retries=0)
        self.state = _Replica.CLOSED
        self.consecutive_failures = 0
        self.opened_at: Optional[float] = None
        self.draining = False
        self.alive = True
        # one flight dump per confirmed death (reset when the replica —
        # or its restarted successor on the same address — answers again)
        self.flight_dumped = False
        self.queue_depth = 0
        self.active_slots = 0
        self.n_slots = 0
        self.tokens_per_sec: Optional[float] = None

    def load(self) -> float:
        return self.queue_depth + self.active_slots

    def snapshot(self) -> Dict:
        return {"addr": self.addr, "state": self.state,
                "draining": self.draining, "alive": self.alive,
                "queue_depth": self.queue_depth,
                "active_slots": self.active_slots, "n_slots": self.n_slots,
                "consecutive_failures": self.consecutive_failures}


class RoutedRequest:
    """One generation request as the ROUTER tracks it: the immutable spec
    (so it can be replayed on a survivor), where it currently lives, and
    how many tokens the router has observed (the resubmit-eligibility
    line)."""

    def __init__(self, prompt, **spec):
        self.prompt = np.asarray(prompt, dtype=np.int32).reshape(-1).tolist()
        self.spec = dict(spec)
        # the deadline anchors at the ROUTER (the request's entry point);
        # every (re)submit forwards only the REMAINING seconds, so a
        # failover does not silently grant the request a fresh deadline.
        # NaN would defeat every expiry comparison — reject up front
        ds = self.spec.pop("deadline_s", None)
        if ds is not None and not math.isfinite(float(ds)):
            raise ValueError(f"deadline_s must be finite, got {ds}")
        self.deadline_s = None if ds is None else float(ds)
        # transcript-memory bound (and the resurrection sanity line): the
        # observed token log can never legitimately exceed the generation
        # limit, so _observe caps there — an unbounded stream race must
        # not grow router memory past it
        self.max_new_tokens = int(self.spec.get("max_new_tokens", 32))
        # minted at the router (the request's entry point) and propagated
        # via headers — the one id stitching router + replica spans
        self.trace_id: Optional[str] = (
            obstrace.new_trace_id() if obstrace.tracing_enabled() else None)
        self.route_span_id: Optional[str] = None
        self.replica_addr: Optional[str] = None
        self.remote_id: Optional[str] = None
        self.tokens: List[int] = []   # guarded-by: self._tokens_lock
        self.state = Request.PENDING
        self.error: Optional[str] = None
        # "request" (replica answered: request-level verdict) vs
        # "transport" (replica death) — _replay_settled re-raises the same
        # exception class a live poll/stream of the failure would have
        self.failure_kind: Optional[str] = None
        self.resubmits = 0
        # continuation re-homes of THIS stream (death resurrection or
        # migration-fallback) — distinct from zero-token resubmits
        self.resurrections = 0
        self.submitted_at = time.perf_counter()
        self.deadline_at = (None if self.deadline_s is None
                            else self.submitted_at + self.deadline_s)
        # guarded-by: self._tokens_lock
        self.first_token_at: Optional[float] = None
        # guarded-by: self._tokens_lock
        self.failover_first_token_at: Optional[float] = None
        # serializes failover: poll() and stream() may race on the same
        # request, and both observing the same death must not resubmit
        # the prompt twice. It intentionally holds across the confirming
        # probe, the backoff sleeps and the resubmit RPCs — that
        # serialization IS the at-most-once guarantee.
        self._failover_lock = threading.Lock()  # hostrace: blocking-ok
        self._tokens_lock = threading.Lock()

    @property
    def done(self) -> bool:
        return self.state in (Request.DONE, Request.FAILED)

    def _observe(self, tokens: Sequence[int]):
        # the length check and the assignment must be one atomic unit: a
        # poll thread and a stream thread observe the same request, and a
        # stream preempted between check and write could REGRESS a longer
        # log a racing poll just recorded — _replay_settled would then
        # yield the truncated log as a complete generation
        with self._tokens_lock:
            # cap at the generation limit: a racing stream must not grow
            # the log past what the engine can legitimately emit (the
            # registry eviction path asserts the same bound server-side)
            tokens = list(tokens)[:self.max_new_tokens]
            if len(tokens) <= len(self.tokens):
                return
            now = time.perf_counter()
            if self.first_token_at is None:
                self.first_token_at = now
            if self.resubmits and self.failover_first_token_at is None:
                self.failover_first_token_at = now
            self.tokens = tokens


class ServingRouter:
    """Spread requests over N engine replicas with failover.

    ``with ServingRouter([addr1, addr2]) as r:`` starts the health-check
    thread; ``submit``/``wait``/``stream`` mirror :class:`ServingClient`
    but survive a replica death for requests the dead replica had not
    started generating.
    """

    def __init__(self, replicas: Sequence[str], *,
                 failure_threshold: int = 3, cooldown_s: float = 1.0,
                 health_interval_s: float = 0.2, request_timeout: float = 10.0,
                 probe_timeout_s: float = 1.0,
                 resubmit_retries: int = 4, poll_s: float = 0.02):
        if not replicas:
            raise ValueError("need at least one replica address")
        probe_timeout = min(float(probe_timeout_s), float(request_timeout))
        self.replicas: Dict[str, _Replica] = {
            a: _Replica(a, timeout=request_timeout,
                        probe_timeout=probe_timeout) for a in replicas}
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self.health_interval_s = float(health_interval_s)
        self.resubmit_retries = int(resubmit_retries)
        self.poll_s = float(poll_s)
        # replica deaths acted on; guarded-by: self._lock
        self.failovers = 0
        # requests re-homed onto a survivor; guarded-by: self._lock
        self.resubmits = 0
        # requests surfaced FAILED (had tokens); guarded-by: self._lock
        self.inflight_failures = 0
        # in-flight streams resurrected as continuations after a confirmed
        # replica death; guarded-by: self._lock
        self.resurrections = 0
        # observed tokens those resurrections preserved; guarded-by: self._lock
        self.resurrected_tokens = 0
        # live migrations completed; guarded-by: self._lock
        self.migrations = 0
        # migrations whose import failed and fell back to resurrection;
        # guarded-by: self._lock
        self.migration_fallbacks = 0
        # seeds minted for sampled requests submitted without one: the
        # engine's fallback seed is replica-local, so a resurrection could
        # not reproduce the key chain — the router pins one up front;
        # guarded-by: self._lock
        self._seed_mint = 0
        self._lock = threading.RLock()
        self._health_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # first-class series: breaker state, failover accounting, per-
        # replica load — the Prometheus face of snapshot(); attached to
        # the flight recorder so a replica-death dump freezes them
        from ..observability.flight import register_metrics_registry

        r = self.registry = MetricsRegistry()
        register_metrics_registry("router", r)
        self._c_failovers = r.counter(
            "router_failovers_total", "confirmed replica deaths acted on")
        self._c_resubmits = r.counter(
            "router_resubmits_total", "requests re-homed onto a survivor")
        self._c_inflight = r.counter(
            "router_inflight_failures_total",
            "requests surfaced FAILED after streaming tokens")
        self._c_resurrections = r.counter(
            "router_resurrections_total",
            "in-flight streams re-homed as continuations after a death")
        self._c_resurrected_tokens = r.counter(
            "router_resurrected_tokens_total",
            "observed tokens preserved across stream resurrections")
        self._c_migrations = r.counter(
            "router_migrations_total", "live stream migrations completed")
        self._c_migration_fallbacks = r.counter(
            "router_migration_fallbacks_total",
            "migrations that fell back to resurrection mid-flight")
        self._g_breaker = r.gauge(
            "router_breaker_state",
            "per-replica breaker (0=closed 1=half_open 2=open)",
            ("replica",))
        self._g_up = r.gauge(
            "router_replica_up", "last probe answered", ("replica",))
        self._g_queue = r.gauge(
            "router_replica_queue_depth", "replica admission queue",
            ("replica",))
        self._g_active = r.gauge(
            "router_replica_active_slots", "replica occupied slots",
            ("replica",))
        self._g_draining = r.gauge(
            "router_replica_draining", "replica drain flag", ("replica",))
        self._metrics_http: Optional[MetricsHTTPServer] = None

    # -- lifecycle ------------------------------------------------------
    def start(self):
        self._health_thread = threading.Thread(target=self._health_loop,
                                               daemon=True)
        self._health_thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._health_thread is not None:
            self._health_thread.join(5.0)
            self._health_thread = None
        if self._metrics_http is not None:
            self._metrics_http.stop()
            self._metrics_http = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- breaker bookkeeping --------------------------------------------
    _BREAKER_CODE = {_Replica.CLOSED: 0, _Replica.HALF_OPEN: 1,
                     _Replica.OPEN: 2}

    def _record_failure(self, rep: _Replica):
        with self._lock:
            rep.consecutive_failures += 1
            rep.alive = False
            opened = False
            if (rep.state == _Replica.HALF_OPEN
                    or rep.consecutive_failures >= self.failure_threshold):
                opened = rep.state != _Replica.OPEN
                rep.state = _Replica.OPEN
                rep.opened_at = time.monotonic()
        self._g_up.set(0, replica=rep.addr)
        self._g_breaker.set(self._BREAKER_CODE[rep.state], replica=rep.addr)
        if opened:
            obstrace.event("router.breaker_open", replica=rep.addr)

    def _record_success(self, rep: _Replica):
        with self._lock:
            rep.consecutive_failures = 0
            rep.alive = True
            rep.flight_dumped = False
            if rep.state != _Replica.CLOSED:
                rep.state = _Replica.CLOSED
                rep.opened_at = None
        self._g_up.set(1, replica=rep.addr)
        self._g_breaker.set(0, replica=rep.addr)

    def _tick_breaker(self, rep: _Replica):
        with self._lock:
            if (rep.state == _Replica.OPEN and rep.opened_at is not None
                    # det-ok: breaker probe timers pace RECOVERY against
                    # real outage duration; request ordering never
                    # observes the cooldown clock
                    and time.monotonic() - rep.opened_at >= self.cooldown_s):
                rep.state = _Replica.HALF_OPEN  # next probe decides

    # -- health ----------------------------------------------------------
    def _probe(self, rep: _Replica):
        """One health check: /metrics answers → liveness + load gauges; a
        HALF_OPEN replica that answers rejoins (the half-open probe)."""
        self._tick_breaker(rep)
        if rep.state == _Replica.OPEN:
            return
        try:
            snap = rep.probe_client.metrics()
        except (OSError, RetryError, RuntimeError, ValueError,
                http.client.HTTPException):
            # HTTPException covers a replica killed mid-response
            # (IncompleteRead/BadStatusLine are NOT OSErrors)
            self._record_failure(rep)
            return
        with self._lock:
            rep.queue_depth = int(snap.get("queue_depth", 0))
            occ = snap.get("slot_occupancy", {})
            rep.active_slots = int(occ.get("active", 0))
            rep.n_slots = int(occ.get("total", 0))
            rep.tokens_per_sec = snap.get("throughput_tokens_per_sec")
        self._g_queue.set(rep.queue_depth, replica=rep.addr)
        self._g_active.set(rep.active_slots, replica=rep.addr)
        self._g_draining.set(1 if snap.get("draining") else 0,
                             replica=rep.addr)
        with self._lock:
            # MIRROR the replica's drain state rather than latching it: a
            # replica restarted on the same address (reporting
            # draining=false) must rejoin the rotation. A request racing
            # the brief window between drain()'s flag and the replica
            # closing admissions just completes on the draining replica —
            # drain() polls until empty, so nothing is dropped.
            rep.draining = bool(snap.get("draining"))
        self._record_success(rep)

    def _health_loop(self):
        while not self._stop.wait(self.health_interval_s):
            for rep in list(self.replicas.values()):
                try:
                    self._probe(rep)
                except Exception:
                    # a probe failure mode we did not anticipate must
                    # count as the probe failing, never kill the daemon
                    # health thread (breakers would freeze OPEN forever)
                    self._record_failure(rep)

    def check_health(self):
        """Synchronous probe of every replica (tests / just-started
        routers that have not accumulated health history yet)."""
        for rep in list(self.replicas.values()):
            self._probe(rep)

    # -- routing ----------------------------------------------------------
    def _candidates(self) -> List[_Replica]:
        with self._lock:
            reps = [r for r in self.replicas.values() if not r.draining]
            for r in reps:
                self._tick_breaker(r)
            closed = [r for r in reps if r.state == _Replica.CLOSED]
            half = [r for r in reps if r.state == _Replica.HALF_OPEN]
        # least-loaded first; replicas OBSERVED dead (alive=False, breaker
        # not yet open) go last so a failover resubmit doesn't re-dial the
        # corpse (and block a request_timeout) while a live peer is free;
        # HALF_OPEN replicas are probe targets of last resort (their first
        # real request decides the breaker)
        key = lambda r: (not r.alive, _Replica.load(r))
        return sorted(closed, key=key) + sorted(half, key=key)

    def _submit_somewhere(self, rr: RoutedRequest) -> None:
        if rr.deadline_at is not None \
                and rr.deadline_at - time.perf_counter() <= 0:
            raise DeadlineExceededError(
                f"deadline_s={rr.deadline_s} elapsed before the request "
                f"could be (re)submitted")
        # continuation join: tokens the router has already observed ride
        # along, so a survivor resumes the stream mid-transcript instead
        # of regenerating from scratch (zero-token requests submit the
        # plain prompt — the original fresh-resubmit path, unchanged)
        with rr._tokens_lock:
            observed = list(rr.tokens)
        extra = {"observed_tokens": observed} if observed else {}
        last_exc: Optional[Exception] = None
        for rep in self._candidates():
            # the remaining deadline is re-derived PER ATTEMPT: time
            # burned timing out against a dead candidate must be deducted
            # from what the next replica is told, or a later hop
            # re-anchors a deadline that has already elapsed
            deadline_remaining: Optional[float] = None
            if rr.deadline_at is not None:
                deadline_remaining = rr.deadline_at - time.perf_counter()
                if deadline_remaining <= 0:
                    # cannot start anywhere before the deadline: shed at
                    # the router instead of spending a replica's queue
                    # slot on it
                    raise DeadlineExceededError(
                        f"deadline_s={rr.deadline_s} elapsed before the "
                        f"request could be (re)submitted")
            try:
                rid = rep.client.submit(
                    rr.prompt, trace_id=rr.trace_id,
                    parent_span_id=rr.route_span_id,
                    deadline_s=deadline_remaining, **extra, **rr.spec)
            except DeadlineExceededError:
                # the remaining budget evaporated in flight — expired
                # everywhere by definition, never spill
                raise
            except (OSError, RetryError, ValueError,
                    http.client.HTTPException) as e:  # transport: breaker
                self._record_failure(rep)
                last_exc = e
                continue
            except (QueueFullError, SchedulerClosed, AdmissionRejected) as e:
                # semantic backpressure: the replica is healthy, just full/
                # draining/over-budget — spill to the next one, surface if
                # ALL are
                last_exc = e
                continue
            self._record_success(rep)
            with self._lock:
                rep.queue_depth += 1  # optimistic, until the next probe
            # remote_id MUST be published before replica_addr: poll/stream
            # read addr first, so addr=new ⇒ id=new (addr=old + id=new just
            # dials the corpse → transport error → addr-mismatch retry).
            # The reverse order lets a racing poll send the OLD id to the
            # NEW replica, whose 404 is a permanent request-level FAILED.
            rr.remote_id = rid
            rr.replica_addr = rep.addr
            return
        if isinstance(last_exc, (QueueFullError, SchedulerClosed,
                                 AdmissionRejected)):
            raise last_exc
        raise NoReplicaAvailable(
            f"no replica accepted the request "
            f"({[r.snapshot() for r in self.replicas.values()]})"
        ) from last_exc

    def submit(self, prompt, **spec) -> RoutedRequest:
        """Route one request to the least-loaded healthy replica. Raises
        :class:`QueueFullError`/:class:`SchedulerClosed` only when EVERY
        healthy replica says so, :class:`NoReplicaAvailable` when none is
        reachable. With tracing armed the request gets a fresh trace id
        and a ``serving.route`` root span; the replica's queue/prefill/
        decode spans hang off it through the propagated headers."""
        rr = RoutedRequest(prompt, **spec)
        if (rr.spec.get("seed") is None
                and float(rr.spec.get("temperature") or 0.0) > 0.0):
            # pin a seed for sampled requests at the ENTRY point: the
            # engine's fallback seed is replica-local state, so without
            # this a resurrection could never fast-forward the key chain
            # the dead replica was actually sampling from
            with self._lock:
                self._seed_mint += 1
                rr.spec["seed"] = self._seed_mint
        with obstrace.span("serving.route", trace_id=rr.trace_id) as sp:
            if sp is not None:
                rr.route_span_id = sp.span_id
            self._submit_somewhere(rr)
            if sp is not None:
                sp.attrs["replica"] = rr.replica_addr
                sp.attrs["remote_id"] = rr.remote_id
        return rr

    # -- failover ---------------------------------------------------------
    def _handle_replica_death(self, rr: RoutedRequest, err: Exception,
                              addr: str) -> bool:
        """A call for ``rr`` against replica ``addr`` hit a dead replica.
        Returns True when the request was re-homed (safe: router never
        observed a token), False when it must surface as FAILED
        (generation had started). ``addr`` is the replica the CALLER was
        talking to: a poll and a stream racing on the same request must
        charge the breaker of the replica that actually died (never a
        survivor the other thread already re-homed onto) and resubmit the
        prompt at most once."""
        with rr._failover_lock:
            if rr.done:
                return rr.state == Request.DONE
            if rr.replica_addr != addr:
                # another caller already re-homed rr onto a survivor while
                # this one was timing out against the corpse
                return True
            return self._handle_replica_death_locked(rr, err)

    # hostrace: requires(rr._failover_lock)
    def _handle_replica_death_locked(self, rr: RoutedRequest,
                                     err: Exception) -> bool:
        rep = self.replicas.get(rr.replica_addr)
        if rep is not None:
            # confirm the death before acting on ONE caller-side transport
            # error: a healthy replica stalled past request_timeout (e.g.
            # GIL-held jit of a new prefill bucket) times out a poll yet
            # answers /metrics fine — declaring death would permanently
            # FAIL an in-flight request the replica will finish, or
            # resubmit a still-running prompt (two concurrent
            # generations). Probe says alive ⇒ transient: leave the
            # request in place, the caller's next poll/stream retries.
            try:
                rep.probe_client.metrics()
            except (OSError, RetryError, RuntimeError, ValueError,
                    http.client.HTTPException):
                pass  # probe agrees: confirmed dead
            else:
                self._record_success(rep)
                return True
            self._record_failure(rep)
            with self._lock:
                first_confirmation = not rep.flight_dumped
                rep.flight_dumped = True
            if first_confirmation:
                # first CONFIRMED observation of this death (probe agreed):
                # freeze the flight record once, not per affected request
                from ..observability.flight import flight_recorder

                flight_recorder().dump(
                    "replica_death",
                    extra={"replica": rep.addr, "error": str(err)})
        if rr.tokens:
            # in-flight stream: resurrect it on a survivor as a
            # continuation join instead of surfacing the death
            return self._rehome_continuation(rr, err, dead=rr.replica_addr)
        with self._lock:
            self.failovers += 1
        self._c_failovers.inc()
        delays = backoff_delays(self.resubmit_retries)
        for attempt in range(self.resubmit_retries + 1):
            try:
                self._submit_somewhere(rr)
                with self._lock:
                    self.resubmits += 1
                self._c_resubmits.inc()
                rr.resubmits += 1
                return True
            except DeadlineExceededError as e:
                # the deadline lapsed during failover: a request-level
                # verdict (nothing is wrong with the survivors)
                rr.failure_kind = "request"
                rr.state = Request.FAILED
                rr.error = f"{DeadlineExceededError.error_type}: {e}"
                return False
            except (QueueFullError, SchedulerClosed, NoReplicaAvailable,
                    AdmissionRejected):
                if attempt >= self.resubmit_retries:
                    break
                time.sleep(next(delays))
        rr.failure_kind = "transport"
        rr.state = Request.FAILED
        rr.error = (f"replica {rr.replica_addr} died and no survivor "
                    f"accepted the resubmit: {err}")
        return False

    # hostrace: requires(rr._failover_lock)
    def _rehome_continuation(self, rr: RoutedRequest, err: Exception,
                             dead: Optional[str] = None) -> bool:
        """Re-home an in-flight stream as a CONTINUATION JOIN: the token
        log the router observed (``rr.tokens``, authoritative — every
        delivered token passed through :meth:`RoutedRequest._observe`)
        rides along in the resubmit, the survivor prefills
        prompt+observed through the ordinary chunk-bucket programs and
        fast-forwards the PRNG key chain, and the continued trajectory is
        bit-identical to the uninterrupted run — greedy AND sampled,
        because :meth:`submit` minted the seed at the entry point.
        Returns True when re-homed, False when the stream settles FAILED:
        deadline lapsed (``failure_kind='request'``) or no survivor could
        take the continuation (``failure_kind='resurrection'``, replayed
        to observers as :class:`ResurrectionFailedError` — a typed
        terminal verdict, never a silent retry loop)."""
        with rr._tokens_lock:
            n_observed = len(rr.tokens)
        # deterministic inject seam: a stall here models the wall-clock a
        # real recovery burns before the resubmit (deadline tests), a
        # raise models the recovery machinery itself dying
        _inject_fire("router.resurrect", request=rr.remote_id,
                     replica=dead or rr.replica_addr, tokens=n_observed)
        if (float(rr.spec.get("temperature") or 0.0) > 0.0
                and rr.spec.get("seed") is None):
            # a sampled stream without a pinned seed (request constructed
            # around submit()'s seed mint): the dead replica's key chain
            # is unrecoverable, so the continuation can never bit-match
            with self._lock:
                self.inflight_failures += 1
            self._c_inflight.inc()
            rr.failure_kind = "resurrection"
            rr.state = Request.FAILED
            rr.error = (f"{n_observed}-token sampled stream on "
                        f"{rr.replica_addr} has no pinned seed — the key "
                        f"chain died with the replica: {err}")
            return False
        from ..observability.flight import flight_recorder

        flight_recorder().dump(
            "stream_resurrection",
            extra={"replica": dead or rr.replica_addr,
                   "request": rr.remote_id, "tokens_observed": n_observed,
                   "error": str(err)})
        with self._lock:
            self.failovers += 1
        self._c_failovers.inc()
        delays = backoff_delays(self.resubmit_retries)
        for attempt in range(self.resubmit_retries + 1):
            try:
                self._submit_somewhere(rr)
            except DeadlineExceededError as e:
                # the deadline lapsed during recovery (time burned on the
                # dead replica counts against the SAME deadline_at — a
                # request-level verdict, nothing wrong with the survivors)
                rr.failure_kind = "request"
                rr.state = Request.FAILED
                rr.error = f"{DeadlineExceededError.error_type}: {e}"
                return False
            except (QueueFullError, SchedulerClosed, NoReplicaAvailable,
                    AdmissionRejected):
                if attempt >= self.resubmit_retries:
                    break
                time.sleep(next(delays))
            else:
                with self._lock:
                    self.resubmits += 1
                    self.resurrections += 1
                    self.resurrected_tokens += n_observed
                self._c_resubmits.inc()
                self._c_resurrections.inc()
                self._c_resurrected_tokens.inc(n_observed)
                rr.resubmits += 1
                rr.resurrections += 1
                return True
        with self._lock:
            self.inflight_failures += 1
        self._c_inflight.inc()
        rr.failure_kind = "resurrection"
        rr.state = Request.FAILED
        rr.error = (f"{n_observed}-token stream orphaned by the death of "
                    f"{dead or rr.replica_addr} and no survivor accepted "
                    f"the continuation: {err}")
        return False

    # -- retrieval ---------------------------------------------------------
    def poll(self, rr: RoutedRequest) -> Dict:
        """One status poll, with failover. Returns the /v1/result payload
        shape (id/status/tokens/error) from wherever ``rr`` currently
        lives."""
        if rr.done:
            return {"id": rr.remote_id, "status": rr.state,
                    "tokens": list(rr.tokens), "error": rr.error}
        addr = rr.replica_addr
        rid = rr.remote_id
        rep = self.replicas.get(addr)
        try:
            out = rep.client.result(rid)
        except RequestFailedError as e:
            if (getattr(e, "error_type", None) == MIGRATED_ERROR_TYPE
                    or (rr.replica_addr, rr.remote_id) != (addr, rid)):
                # the stream MOVED while this poll was in flight (a
                # migration exported it off ``addr``, or a racing caller
                # re-homed it): transient, not a settlement — the next
                # poll reads the new home
                self._record_success(rep)
                return {"id": rr.remote_id, "status": Request.RUNNING,
                        "tokens": list(rr.tokens), "error": None}
            # the replica ANSWERED: a request-level verdict (unknown or
            # evicted id), not a death — the breaker stays untouched and
            # the request is NOT replayed elsewhere
            self._record_success(rep)
            rr.failure_kind = "request"
            rr.state = Request.FAILED
            rr.error = str(e)
            return {"id": rr.remote_id, "status": rr.state,
                    "tokens": list(rr.tokens), "error": rr.error}
        except (OSError, RetryError, RuntimeError, ValueError,
                http.client.HTTPException) as e:
            # ValueError: a response truncated by the death parses as
            # garbage JSON — same event as the connection dropping
            self._handle_replica_death(rr, e, addr)
            return {"id": rr.remote_id, "status": rr.state,
                    "tokens": list(rr.tokens), "error": rr.error}
        self._record_success(rep)
        if (out.get("status") == Request.FAILED
                and out.get("error_type") == MIGRATED_ERROR_TYPE):
            # polled the migration SOURCE after export but before the
            # router flipped routing: the stream lives on, elsewhere
            return {"id": rr.remote_id, "status": Request.RUNNING,
                    "tokens": list(rr.tokens), "error": None}
        rr._observe(out.get("tokens", ()))
        if out.get("status") in (Request.DONE, Request.FAILED):
            rr.state = out["status"]
            rr.error = out.get("error")
        return out

    def wait(self, rr: RoutedRequest, timeout: float = 60.0) -> Dict:
        """Poll until ``rr`` finishes (surviving replica deaths along the
        way); raises TimeoutError if it neither completes nor fails."""
        deadline = time.perf_counter() + timeout
        while True:
            out = self.poll(rr)
            if rr.done:
                return out
            if time.perf_counter() > deadline:
                raise TimeoutError(f"request not done within {timeout}s "
                                   f"(on {rr.replica_addr})")
            time.sleep(self.poll_s)

    def stream(self, rr: RoutedRequest):
        """Yield generated tokens incrementally, failing over mid-stream:
        a replica death before the first token transparently re-streams
        from a survivor; after the first token it raises (the router must
        not splice two generations together)."""
        # tokens already handed to THIS caller: a reconnect (resurrection
        # or migration) replays the replica's full transcript from token
        # 0, and only indices >= delivered may be yielded again — the
        # zero-duplicate half of the zero-loss contract
        delivered = 0
        while True:
            if rr.done:
                # settled (polled to completion, replica since dead, or
                # re-homed and finished between attempts): replay the
                # recorded outcome — never reconnect to a corpse for
                # tokens the router already has
                yield from self._replay_settled(rr, delivered)
                return
            addr = rr.replica_addr
            rid = rr.remote_id
            rep = self.replicas.get(addr)
            # the replica's stream replays from token 0 and is the
            # authoritative sequence: observe THAT, never append to
            # rr.tokens (a poll racing this stream may already have
            # recorded tokens the stream is still catching up to)
            streamed: List[int] = []
            try:
                for tok in rep.client.stream(rid):
                    streamed.append(int(tok))
                    rr._observe(streamed)
                    if len(streamed) > delivered:
                        delivered = len(streamed)
                        yield int(tok)
                rr.state = Request.DONE
                return
            except RequestFailedError as e:
                if (getattr(e, "error_type", None) == MIGRATED_ERROR_TYPE
                        or (rr.replica_addr, rr.remote_id) != (addr, rid)):
                    # the stream MOVED mid-attempt (migration export, or
                    # a racing caller re-homed it): reconnect to wherever
                    # it lives now — delivered dedups the replay
                    self._record_success(rep)
                    time.sleep(self.poll_s)
                    continue
                # the replica is healthy and says THE REQUEST failed: no
                # breaker hit, no resubmit (a poison request replayed on
                # every replica would open every breaker in turn)
                self._record_success(rep)
                rr.failure_kind = "request"
                rr.state = Request.FAILED
                rr.error = str(e)
                raise
            except StreamIncompleteError:
                # server-side stream timeout: the request is still RUNNING
                # on a healthy replica — surface to the caller (who can
                # re-stream or poll), touch neither breaker nor request
                self._record_success(rep)
                raise
            except (OSError, RetryError, RuntimeError, ValueError,
                    http.client.HTTPException) as e:
                # transport truncation/refusal (incl. a death-truncated
                # body parsing as garbage JSON): the replica (or its
                # handler) died mid-stream — the failover rule applies
                if self._handle_replica_death(rr, e, addr):
                    if rr.done:
                        # settled while this observer was timing out (a
                        # racing poll finished it): replay the remainder
                        # instead of re-dialing the dead replica forever
                        yield from self._replay_settled(rr, delivered)
                        return
                    continue  # re-homed: stream from the survivor
                # a racing poll may have settled rr with a REQUEST-level
                # verdict while this stream was failing on transport:
                # surface the class the verdict contract promises
                if rr.failure_kind == "request":
                    raise RequestFailedError(rr.error or str(e)) from e
                if rr.failure_kind == "resurrection":
                    raise ResurrectionFailedError(
                        rr.error or str(e)) from e
                raise RuntimeError(rr.error or str(e)) from e

    def _replay_settled(self, rr: RoutedRequest, skip: int):
        """Yield a settled request's recorded tokens after ``skip`` (the
        count a live stream already delivered); raise if it FAILED.
        rr.tokens is safe to replay: state only reaches DONE after the
        full token log was observed, and a re-home never happens once a
        token exists, so the log is a single generation."""
        if rr.state == Request.FAILED:
            # same exception class a LIVE observation of this failure
            # raised: request-level verdicts are RequestFailedError (the
            # documented switch point), exhausted continuation re-homes
            # are ResurrectionFailedError, other deaths stay RuntimeError
            if rr.failure_kind == "request":
                raise RequestFailedError(rr.error or "request failed")
            if rr.failure_kind == "resurrection":
                raise ResurrectionFailedError(rr.error or "request failed")
            raise RuntimeError(rr.error or "request failed")
        for tok in list(rr.tokens)[skip:]:
            yield int(tok)

    # -- migration ---------------------------------------------------------
    def migrate(self, rr: RoutedRequest, to_addr: str) -> None:
        """Live-migrate one in-flight stream onto ``to_addr`` between
        decode ticks, zero tokens dropped or duplicated: the source
        exports a CRC-stamped continuation record (transcript + sampling
        params + key-chain position), the target imports it as a
        continuation join, and routing flips atomically (remote_id
        published before replica_addr, the failover ordering). Observers
        polling/streaming the source inside the window see the
        ``MigratedError`` verdict and treat it as "moved", not settled.
        A mid-migration target death (or refusal) falls back to
        resurrection — the stream is never lost to a failed migration.
        Raises KeyError for an unknown target, ValueError for a settled
        request, :class:`RequestFailedError` when the source answers the
        stream is not exportable (unknown / still queued / finished), and
        RuntimeError when the migration aborted with the stream intact on
        the source."""
        target = self.replicas.get(to_addr)
        if target is None:
            raise KeyError(f"unknown replica {to_addr!r}")
        with rr._failover_lock:
            if rr.done:
                raise ValueError(
                    f"cannot migrate {rr.remote_id!r}: already settled "
                    f"({rr.state})")
            src_addr = rr.replica_addr
            if src_addr == to_addr:
                return
            src = self.replicas.get(src_addr)
            if src is None:
                raise KeyError(f"request lives on unknown replica "
                               f"{src_addr!r}")
            _inject_fire("router.migrate", request=rr.remote_id,
                         src=src_addr, dst=to_addr, stage="export")
            try:
                record = src.client.migrate_export(rr.remote_id)
            except RequestFailedError as nx:
                # the source ANSWERED: not exportable (unknown id, still
                # queued, or already finished) — nothing moved, nothing
                # to recover. A stream that raced to completion before
                # the export gets the same verdict as the early rr.done
                # check (the caller's next poll settles rr normally).
                self._record_success(src)
                try:
                    out = src.probe_client.result(rr.remote_id)
                except (OSError, RetryError, RuntimeError, ValueError,
                        RequestFailedError, http.client.HTTPException):
                    raise nx
                if out.get("status") in (Request.DONE, Request.FAILED):
                    raise ValueError(
                        f"cannot migrate {rr.remote_id!r}: already "
                        f"settled ({out['status']} on {src_addr})"
                    ) from nx
                raise nx
            except (OSError, RetryError, RuntimeError, ValueError,
                    http.client.HTTPException) as e:
                # ambiguous: the export may or may not have committed
                # before the transport tore (or the source refused with a
                # 409/500). Ask the source: a settled MigratedError
                # verdict means the slot WAS freed and the record was
                # lost in transit — fall back to resurrection from the
                # router's own observed log (safe: a continuation from
                # ANY prefix of the transcript regenerates the identical
                # trajectory). Still RUNNING means nothing was exported.
                # An unreachable source is the ordinary confirmed-death
                # path, which itself resurrects.
                try:
                    out = src.probe_client.result(rr.remote_id)
                except RequestFailedError:
                    committed = True  # registry evicted it post-export
                except (OSError, RetryError, RuntimeError, ValueError,
                        http.client.HTTPException):
                    self._handle_replica_death_locked(rr, e)
                    raise RuntimeError(
                        f"migration aborted at export ({src_addr} "
                        f"unreachable): {e}") from e
                else:
                    committed = (
                        out.get("status") == Request.FAILED
                        and out.get("error_type") == MIGRATED_ERROR_TYPE)
                if not committed:
                    self._record_failure(src)
                    raise RuntimeError(
                        f"migration aborted at export (stream intact on "
                        f"{src_addr}): {e}") from e
                return self._migration_fallback(rr, e)
            self._record_success(src)
            # the engine's transcript is authoritative and may be ahead
            # of the router's: adopt it before the import (or fallback)
            rr._observe(record.get("tokens", ()))
            _inject_fire("router.migrate", request=rr.remote_id,
                         src=src_addr, dst=to_addr, stage="import")
            deadline_remaining: Optional[float] = None
            if rr.deadline_at is not None:
                deadline_remaining = rr.deadline_at - time.perf_counter()
            try:
                if (deadline_remaining is not None
                        and deadline_remaining <= 0):
                    raise DeadlineExceededError(
                        f"deadline_s={rr.deadline_s} elapsed mid-"
                        f"migration")
                new_id = target.client.migrate_import(
                    record, trace_id=rr.trace_id,
                    parent_span_id=rr.route_span_id,
                    deadline_s=deadline_remaining)
            except (OSError, RetryError,
                    http.client.HTTPException) as e:
                # the TARGET died under the import: the record is gone
                # with it but the transcript is not — resurrect
                self._record_failure(target)
                return self._migration_fallback(rr, e)
            except (QueueFullError, SchedulerClosed, AdmissionRejected,
                    DeadlineExceededError, ValueError, RuntimeError) as e:
                # target refused (backpressure / bad record / 500): the
                # source already gave the stream up, so a survivor must
                # take the continuation
                return self._migration_fallback(rr, e)
            self._record_success(target)
            with self._lock:
                target.queue_depth += 1
            rr.remote_id = new_id
            rr.replica_addr = to_addr
            with self._lock:
                self.migrations += 1
            self._c_migrations.inc()

    # hostrace: requires(rr._failover_lock)
    def _migration_fallback(self, rr: RoutedRequest,
                            err: Exception) -> None:
        """The source exported (its slot is free) but the target never
        took the stream: re-home it as a plain resurrection. Raises the
        typed verdict when even that fails — the caller of
        :meth:`migrate` must not believe the stream survived."""
        with self._lock:
            self.migration_fallbacks += 1
        self._c_migration_fallbacks.inc()
        if self._rehome_continuation(rr, err):
            return
        if rr.failure_kind == "request":
            raise RequestFailedError(rr.error or str(err)) from err
        raise ResurrectionFailedError(rr.error or str(err)) from err

    # -- drain -------------------------------------------------------------
    def drain(self, addr: str, timeout: float = 60.0):
        """Take ``addr`` out of rotation with zero dropped queued requests:
        stop routing to it, close its admissions, and block until its
        queue and slots are empty. The replica process can then be stopped
        (or killed) with nothing in flight."""
        rep = self.replicas[addr]
        with self._lock:
            rep.draining = True
        rep.client.admin_drain()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            snap = rep.client.metrics()
            occ = snap.get("slot_occupancy", {})
            if (int(snap.get("queue_depth", 0)) == 0
                    and int(snap.get("in_admission", 0)) == 0
                    and int(occ.get("active", 0)) == 0):
                return
            time.sleep(self.poll_s)
        raise TimeoutError(f"replica {addr} did not drain within {timeout}s")

    # -- observability ------------------------------------------------------
    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "replicas": {a: r.snapshot()
                             for a, r in self.replicas.items()},
                "failovers": self.failovers,
                "resubmits": self.resubmits,
                "inflight_failures": self.inflight_failures,
                "resurrections": self.resurrections,
                "resurrected_tokens": self.resurrected_tokens,
                "migrations": self.migrations,
                "migration_fallbacks": self.migration_fallbacks,
            }

    def _refresh_replica_gauges(self):
        with self._lock:
            reps = list(self.replicas.values())
        for rep in reps:
            self._g_breaker.set(self._BREAKER_CODE[rep.state],
                                replica=rep.addr)
            self._g_up.set(1 if rep.alive else 0, replica=rep.addr)
            self._g_queue.set(rep.queue_depth, replica=rep.addr)
            self._g_active.set(rep.active_slots, replica=rep.addr)
            self._g_draining.set(1 if rep.draining else 0, replica=rep.addr)

    def prometheus_text(self) -> str:
        """Prometheus exposition of the router's series (breaker state,
        failover accounting, per-replica load — refreshed from the live
        replica views first)."""
        self._refresh_replica_gauges()
        return self.registry.prometheus_text()

    def openmetrics_text(self) -> str:
        """OpenMetrics exposition of the same series (exemplar-capable;
        served only under ``Accept: application/openmetrics-text``)."""
        self._refresh_replica_gauges()
        return self.registry.openmetrics_text()

    def serve_metrics(self, host: str = "127.0.0.1",
                      port: int = 0) -> str:
        """Mount the router's metrics on ``GET /metrics`` (the router-side
        scrape endpoint): JSON :meth:`snapshot` by default, Prometheus
        text under a negotiated ``Accept``, OpenMetrics (with exemplars)
        under ``Accept: application/openmetrics-text``. Returns the bound
        address; :meth:`stop` tears it down."""
        if self._metrics_http is None:
            self._metrics_http = MetricsHTTPServer(
                json_fn=self.snapshot, prom_fn=self.prometheus_text,
                om_fn=self.openmetrics_text,
                host=host, port=port).start()
        return self._metrics_http.addr
