"""Threaded HTTP front-end for the continuous-batching engine.

Parity: Paddle Serving's HTTP front-end (submit → queue → batched workers →
poll/stream results) and the reference's AnalysisPredictor service demos;
the implementation reuses the ``fleet/utils/http_server.py`` idiom — a
``ThreadingHTTPServer`` with a per-server bound handler class — so the
serving plane looks like the rendezvous plane operators already run.

Endpoints (JSON in/out):

* ``POST /v1/generate``  body ``{"prompt": [ids...], "max_new_tokens": n,
  "temperature": t, "top_k": k, "top_p": p, "eos_token_id": e, "seed": s}``
  → ``202 {"id": ...}``; **429** when the admission queue is full
  (backpressure), **503** while draining, **400** on bad requests.
* ``GET /v1/result/<id>`` → ``{"status", "prompt", "tokens", "text?"}`` —
  poll-style retrieval.
* ``GET /v1/stream/<id>`` → incremental token streaming: newline-delimited
  JSON (``{"token": t}`` per generated token, final ``{"done": true, ...}``),
  written as tokens land in the request's log — a client reads tokens while
  the engine is still decoding other slots.
* ``GET /metrics`` → ``ServingMetrics.snapshot()`` (TTFT/latency/throughput
  percentiles, queue depth, slot occupancy, compile-cache hit counters).

Graceful drain: :meth:`ServingServer.drain` stops admissions (subsequent
submits get 503), lets in-flight and queued requests finish, then
:meth:`stop` tears the HTTP plane down.

:class:`ServingClient` wraps the wire protocol with ``resilience/retry.py``
backoff on transport faults (connection refused/reset while a server
restarts), mirroring how the elastic store hardens its KV client.
"""
from __future__ import annotations

import json
import socket
import threading
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

import numpy as np

from ..observability import trace as obstrace
from ..observability.metrics import (
    openmetrics_content_type,
    prometheus_content_type,
    wants_openmetrics,
    wants_prometheus,
)
from .admission import AdmissionRejected, DeadlineExceededError
from .engine import ContinuousBatchingEngine
from .scheduler import QueueFullError, Request, SchedulerClosed

__all__ = ["ServingServer", "ServingClient", "RequestFailedError",
           "StreamIncompleteError"]


class RequestFailedError(RuntimeError):
    """The replica ANSWERED and its verdict is about the REQUEST (engine
    reported it failed, or the id is unknown/evicted) — the replica
    itself is healthy. Routers must not count this against the replica's
    circuit breaker or resubmit the request elsewhere (a poison request
    would cascade through every replica opening every breaker).

    ``error_type`` carries the request's typed discriminator when the
    replica shipped one — routers switch on it (a ``MigratedError``
    verdict means the stream MOVED, not failed)."""

    def __init__(self, msg: str, error_type: Optional[str] = None):
        super().__init__(msg)
        self.error_type = error_type


class StreamIncompleteError(RuntimeError):
    """The server's stream ended while the request was still RUNNING (the
    server-side stream timeout). The request may yet finish — poll it;
    neither a replica death nor a request failure."""


class _QuietHTTPServer(ThreadingHTTPServer):
    """handle_error lives on the SERVER (socketserver.BaseServer), not the
    request handler — kill() severs established sockets, and every handler
    thread's ConnectionResetError lands here instead of a stderr
    traceback per open connection."""

    def handle_error(self, request, client_address):  # quiet
        pass


class _Handler(BaseHTTPRequestHandler):
    server_ref: "ServingServer"  # bound per-server subclass

    protocol_version = "HTTP/1.0"  # close-delimited bodies (streaming)

    def log_message(self, *args):  # quiet
        pass

    def setup(self):
        super().setup()
        self.server_ref._track_conn(self.connection)

    def finish(self):
        self.server_ref._untrack_conn(self.connection)
        super().finish()

    # -- helpers ------------------------------------------------------------
    def _json(self, status: int, payload: Dict):
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json_429(self, payload: Dict, hint: float):
        """Backpressure response: JSON body + RFC 7231 ``Retry-After``
        (whole seconds, floored at 1) — one writer for queue-full and
        admission-gate refusals."""
        body = json.dumps(payload).encode()
        self.send_response(429)
        self.send_header("Content-Type", "application/json")
        self.send_header("Retry-After", str(int(hint + 0.5) or 1))
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _request_or_404(self, rid: str) -> Optional[Request]:
        req = self.server_ref._requests.get(rid)
        if req is None:
            self._json(404, {"error": f"unknown request id {rid!r}"})
        return req

    # -- routes -------------------------------------------------------------
    def do_POST(self):
        path = self.path.rstrip("/")
        if path == "/admin/drain":
            # drain-aware takedown, step 1: stop admitting. Queued and
            # in-flight requests still run to completion; the router polls
            # /metrics until the replica is empty before retiring it.
            self.server_ref.engine.scheduler.close()
            self._json(200, {"draining": True})
            return
        if path == "/admin/migrate_export":
            self._migrate_export()
            return
        if path == "/admin/migrate_import":
            self._migrate_import()
            return
        if path != "/v1/generate":
            self._json(404, {"error": "unknown endpoint"})
            return
        try:
            n = int(self.headers.get("Content-Length", 0))
            spec = json.loads(self.rfile.read(n).decode() or "{}")
            prompt = spec.pop("prompt")
        except Exception as e:
            self._json(400, {"error": f"bad request body: {e}"})
            return
        try:
            # the client deadline rides the trace-header family as
            # REMAINING seconds; a body key is also accepted for direct
            # JSON callers
            deadline = self.headers.get(obstrace.DEADLINE_HEADER)
            if deadline is None:
                deadline = spec.pop("deadline_s", None)
            req = Request(prompt, **{
                k: spec[k] for k in ("max_new_tokens", "eos_token_id",
                                     "temperature", "top_k", "top_p", "seed",
                                     "observed_tokens")
                if k in spec},
                # trace context rides HEADERS, not the body — the JSON
                # protocol stays byte-compatible for existing clients
                trace_id=self.headers.get(obstrace.TRACE_HEADER),
                parent_span_id=self.headers.get(obstrace.PARENT_HEADER),
                deadline_s=None if deadline is None else float(deadline))
            self.server_ref.engine.submit(req)
        except DeadlineExceededError as e:
            self._json(503, {"error": str(e),
                             "error_type": e.error_type})
            return
        except AdmissionRejected as e:
            # the refusal CITES the liveness estimate: operators see the
            # predicted peak vs the budget in the error body itself
            hint = e.retry_after or 1.0
            self._json_429({"error": str(e),
                            "error_type": e.error_type,
                            "estimate": e.estimate,
                            "retry_after_s": hint}, hint)
            return
        except QueueFullError as e:
            # backpressure with a USEFUL hint: seconds of queued work ahead
            # at the measured token rate
            hint = self.server_ref.engine.metrics.retry_after_hint(
                queue_depth=self.server_ref.engine.scheduler.depth())
            self._json_429({"error": str(e), "retry_after_s": hint}, hint)
            return
        except SchedulerClosed as e:
            self._json(503, {"error": str(e)})
            return
        except (TypeError, ValueError) as e:
            self._json(400, {"error": str(e)})
            return
        except Exception as e:
            # an internal failure (e.g. the admission gate's estimator
            # tracing a new bucket) must be an HTTP answer, not an
            # aborted connection — the router reads a dropped connection
            # as a replica DEATH and opens the breaker on a healthy
            # replica over a per-request pricing bug
            self._json(500, {"error": f"submit failed internally: "
                                      f"{type(e).__name__}: {e}"})
            return
        self.server_ref._register(req)
        self._json(202, {"id": req.request_id})

    # -- live stream migration ---------------------------------------------
    def _migrate_export(self):
        """Source half of a live migration: drain one active stream into a
        CRC-stamped continuation record. 404 for an id this engine is not
        decoding, 409 for a mid-prefill slot (retry next tick)."""
        try:
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n).decode() or "{}")
            rid = str(body["id"])
        except Exception as e:
            self._json(400, {"error": f"bad request body: {e}"})
            return
        try:
            record = self.server_ref.engine.export_stream(rid)
        except KeyError as e:
            self._json(404, {"error": str(e)})
            return
        except ValueError as e:
            self._json(409, {"error": str(e)})
            return
        except Exception as e:
            self._json(500, {"error": f"export failed internally: "
                                      f"{type(e).__name__}: {e}"})
            return
        self._json(200, record)

    def _migrate_import(self):
        """Target half: verify the record's CRC, admit the stream as a
        continuation join (same admission gate/queue discipline as a fresh
        submit — a migration must not over-admit past the page budget)."""
        from .engine import verify_continuation_record

        try:
            n = int(self.headers.get("Content-Length", 0))
            record = json.loads(self.rfile.read(n).decode() or "{}")
            verify_continuation_record(record)
        except Exception as e:
            self._json(400, {"error": f"bad continuation record: {e}"})
            return
        try:
            deadline = self.headers.get(obstrace.DEADLINE_HEADER)
            if deadline is None:
                deadline = record.get("deadline_remaining")
            req = Request(
                record["prompt"],
                observed_tokens=record["tokens"],
                max_new_tokens=record["max_new_tokens"],
                eos_token_id=record.get("eos_token_id"),
                temperature=record.get("temperature", 0.0),
                top_k=record.get("top_k"),
                top_p=record.get("top_p"),
                seed=record.get("seed"),
                trace_id=self.headers.get(obstrace.TRACE_HEADER),
                parent_span_id=self.headers.get(obstrace.PARENT_HEADER),
                deadline_s=None if deadline is None else float(deadline))
            self.server_ref.engine.submit(req)
        except DeadlineExceededError as e:
            self._json(503, {"error": str(e), "error_type": e.error_type})
            return
        except AdmissionRejected as e:
            hint = e.retry_after or 1.0
            self._json_429({"error": str(e), "error_type": e.error_type,
                            "estimate": e.estimate,
                            "retry_after_s": hint}, hint)
            return
        except QueueFullError as e:
            hint = self.server_ref.engine.metrics.retry_after_hint(
                queue_depth=self.server_ref.engine.scheduler.depth())
            self._json_429({"error": str(e), "retry_after_s": hint}, hint)
            return
        except SchedulerClosed as e:
            self._json(503, {"error": str(e)})
            return
        except (TypeError, ValueError) as e:
            self._json(400, {"error": str(e)})
            return
        except Exception as e:
            self._json(500, {"error": f"import failed internally: "
                                      f"{type(e).__name__}: {e}"})
            return
        self.server_ref._register(req)
        self._json(202, {"id": req.request_id})

    def do_GET(self):
        parts = [p for p in self.path.split("/") if p]
        if parts == ["metrics"]:
            eng = self.server_ref.engine
            try:
                # LIVE page-pool occupancy (paged layout): the registry's
                # page gauges are only as fresh as the last engine tick,
                # and admission/drain decisions ride on them
                eng.metrics.set_page_gauges(eng.page_state())
            except Exception:
                pass
            accept = self.headers.get("Accept")
            if wants_openmetrics(accept) or wants_prometheus(accept):
                # negotiated text exposition; the JSON default below stays
                # byte-compatible for ServingClient/router consumers.
                # OpenMetrics (checked FIRST — it is the only exposition
                # carrying exemplars) needs the explicit Accept; any other
                # text-ish Accept keeps the byte-stable 0.0.4 body
                live = dict(
                    queue_depth=eng.scheduler.depth(),
                    in_admission=eng.scheduler.in_admission(),
                    active_slots=eng.active_slots(), n_slots=eng.n_slots,
                    draining=eng.scheduler.closed)
                if wants_openmetrics(accept):
                    body = eng.metrics.openmetrics_text(**live).encode()
                    ctype = openmetrics_content_type()
                else:
                    body = eng.metrics.prometheus_text(**live).encode()
                    ctype = prometheus_content_type()
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            snap = eng.metrics.snapshot()
            # the router's routing/drain decisions ride on these, so they
            # must be LIVE admission state — the registry's gauges are only
            # as fresh as the last engine tick (stale while the loop is
            # compiling, idle, or wedged, which is exactly when a router
            # must not believe the replica is empty)
            snap["queue_depth"] = eng.scheduler.depth()
            # popped from the queue but not yet active (mid-prefill): a
            # drain that ignored these would orphan a request whose
            # first compile outlasts the poll interval
            snap["in_admission"] = eng.scheduler.in_admission()
            active = eng.active_slots()
            snap["slot_occupancy"] = {
                "active": active, "total": eng.n_slots,
                "fraction": active / eng.n_slots if eng.n_slots else 0.0}
            snap["draining"] = eng.scheduler.closed
            self._json(200, snap)
            return
        if len(parts) == 3 and parts[:2] == ["v1", "result"]:
            req = self._request_or_404(parts[2])
            if req is None:
                return
            self._json(200, {
                "id": req.request_id,
                "status": req.state,
                "prompt": req.prompt.tolist(),
                "tokens": list(req.tokens),
                "error": req.error,
                "error_type": req.error_type,
            })
            return
        if len(parts) == 3 and parts[:2] == ["v1", "stream"]:
            req = self._request_or_404(parts[2])
            if req is None:
                return
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Connection", "close")
            self.end_headers()
            try:
                for tok in req.iter_tokens(
                        timeout=self.server_ref.stream_timeout):
                    self.wfile.write(
                        (json.dumps({"token": int(tok)}) + "\n").encode())
                    self.wfile.flush()
                self.wfile.write((json.dumps(
                    {"done": True, "status": req.state,
                     "n_tokens": len(req.tokens),
                     "error_type": req.error_type}) + "\n").encode())
                self.wfile.flush()
            except OSError:
                pass  # client went away / kill() severed the socket
            return
        self._json(404, {"error": "unknown endpoint"})


class ServingServer:
    """HTTP front-end + engine loop thread. ``with ServingServer(engine):``
    or start()/drain()/stop()."""

    def __init__(self, engine: ContinuousBatchingEngine, port: int = 0,
                 host: str = "127.0.0.1", stream_timeout: float = 60.0,
                 max_kept_requests: int = 4096, drain_timeout_s: float = 30.0):
        self.engine = engine
        self.stream_timeout = float(stream_timeout)
        self.max_kept_requests = int(max_kept_requests)
        # graceful-drain deadline: how long stop()/drain() wait for queued +
        # in-flight work before declaring the engine stuck (was an implicit
        # hard-coded default; operators sizing long generations need it)
        self.drain_timeout_s = float(drain_timeout_s)
        # guarded-by: self._requests_lock
        self._requests: "OrderedDict[str, Request]" = OrderedDict()
        self._requests_lock = threading.Lock()
        # established handler connections: kill() must sever these so a
        # client mid-stream sees a reset (like a real process SIGKILL),
        # not a silent socket that only dies at its own read timeout
        self._conns: set = set()  # guarded-by: self._conns_lock
        self._conns_lock = threading.Lock()
        handler = type("_BoundHandler", (_Handler,), {"server_ref": self})
        self._httpd = _QuietHTTPServer((host, port), handler)
        self.host = host
        self.port = self._httpd.server_address[1]
        self.addr = f"{host}:{self.port}"
        # fault-injection hooks: the engine loop's `replica.tick` point
        # matches schedules on this address, and an injected `kill` tears
        # down the WHOLE replica (HTTP plane included) like a SIGKILL
        engine._replica_addr = self.addr
        engine._server_kill = self.kill
        self._http_thread: Optional[threading.Thread] = None
        self._engine_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def _track_conn(self, sock):
        with self._conns_lock:
            self._conns.add(sock)

    def _untrack_conn(self, sock):
        with self._conns_lock:
            self._conns.discard(sock)

    def _register(self, req: Request):
        """Track a request for poll/stream, evicting the OLDEST finished
        ones past ``max_kept_requests`` — a long-running server must not
        accumulate every token log ever served (in-flight entries are never
        evicted, so a full queue can exceed the cap transiently)."""
        with self._requests_lock:
            self._requests[req.request_id] = req
            while len(self._requests) > self.max_kept_requests:
                victim = next((k for k, r in self._requests.items() if r.done),
                              None)
                if victim is None:
                    break
                v = self._requests.pop(victim)
                # registry eviction is the last observer of a finished
                # transcript: the token log is bounded by the generation
                # limit BY CONSTRUCTION (continuation joins validate the
                # observed prefix; decode retires at max_new_tokens) — a
                # longer log here means a splice bug upstream
                assert len(v.tokens) <= v.max_new_tokens, (
                    f"evicting {victim!r} with {len(v.tokens)} tokens past "
                    f"max_new_tokens={v.max_new_tokens}")

    def start(self):
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._http_thread.start()
        self._engine_thread = threading.Thread(
            target=self.engine.serve_forever, args=(self._stop,), daemon=True)
        self._engine_thread.start()
        return self

    def drain(self, timeout: Optional[float] = None):
        """Graceful drain: stop admitting (new submits → 503), finish every
        queued and in-flight request, stop the engine loop. ``timeout``
        defaults to the server's configured ``drain_timeout_s``."""
        timeout = self.drain_timeout_s if timeout is None else timeout
        self.engine.scheduler.close()
        self._stop.set()
        if self._engine_thread is not None:
            self._engine_thread.join(timeout)
            if self._engine_thread.is_alive():
                raise TimeoutError(
                    f"engine did not drain within {timeout}s "
                    f"(drain_timeout_s={self.drain_timeout_s})")
            self._engine_thread = None

    def stop(self, timeout: Optional[float] = None):
        timeout = self.drain_timeout_s if timeout is None else timeout
        self.drain(timeout)
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._http_thread is not None:
            self._http_thread.join(timeout)
            self._http_thread = None

    def kill(self):
        """Abrupt-death chaos hook: tear down the HTTP plane and abort the
        engine loop with NO drain — queued/in-flight requests are orphaned
        exactly as if the replica process took a SIGKILL. Clients see
        connection-refused; recovery is the ROUTER's job (resubmit of
        never-prefilled requests, surfaced failure for in-flight ones)."""
        self.engine.abort()
        self._stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        # sever established connections: a client blocked on an open
        # stream must see the reset NOW (as with a real SIGKILL), not
        # discover the death at its own socket timeout
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        if self._http_thread is not None:
            self._http_thread.join(5.0)
            self._http_thread = None
        if self._engine_thread is not None:
            self._engine_thread.join(5.0)
            self._engine_thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


class ServingClient:
    """Wire client with transport-fault retries (resilience/retry.py)."""

    def __init__(self, addr: str, timeout: float = 30.0, retries: int = 3):
        self.addr = addr  # "host:port"
        self.timeout = timeout
        self.retries = retries

    def _conn(self):
        import http.client

        host, port = self.addr.rsplit(":", 1)
        return http.client.HTTPConnection(host, int(port),
                                          timeout=self.timeout)

    def _call(self, method: str, path: str, body: Optional[Dict] = None,
              retries: Optional[int] = None,
              headers: Optional[Dict[str, str]] = None):
        from ..resilience.inject import fire as _inject_fire
        from ..resilience.retry import call_with_retries

        def attempt():
            # transport injection seam: `timeout` raises socket.timeout
            # before dialing (inside fire); `garbage` lets the request
            # REACH the server (side effects happen) and corrupts only
            # the response body — the lost-202 / truncated-read shape
            f = _inject_fire("router.transport", addr=self.addr,
                             method=method, path=path)
            c = self._conn()
            try:
                hdrs = {"Content-Type": "application/json"}
                if headers:
                    hdrs.update(headers)
                c.request(method, path,
                          body=None if body is None else json.dumps(body).encode(),
                          headers=hdrs)
                r = c.getresponse()
                raw = r.read()
                if f is not None and f.kind == "garbage":
                    raw = b"\x00injected-garbage-body\x00"
                return r.status, json.loads(raw.decode() or "{}")
            finally:
                c.close()

        # retry TRANSPORT faults only — 4xx/5xx are semantic answers
        # (429 backpressure must surface to the caller, not be retried away)
        return call_with_retries(
            attempt, retries=self.retries if retries is None else retries,
            retry_on=(OSError,))

    def submit(self, prompt, trace_id: Optional[str] = None,
               parent_span_id: Optional[str] = None,
               deadline_s: Optional[float] = None, **kwargs) -> str:
        # NO transport retry: a lost 202 after the server enqueued would
        # silently duplicate the generation (submit is not idempotent).
        # Trace context propagates via headers (body stays protocol-stable);
        # the deadline ships as REMAINING seconds on the same family.
        headers = {}
        if trace_id:
            headers[obstrace.TRACE_HEADER] = trace_id
        if parent_span_id:
            headers[obstrace.PARENT_HEADER] = parent_span_id
        if deadline_s is not None:
            headers[obstrace.DEADLINE_HEADER] = repr(float(deadline_s))
        status, out = self._call("POST", "/v1/generate",
                                 {"prompt": np.asarray(prompt).tolist(),
                                  **kwargs}, retries=0,
                                 headers=headers or None)
        if status == 429:
            if out.get("error_type") == AdmissionRejected.error_type:
                raise AdmissionRejected(
                    out.get("error", "admission refused"),
                    estimate=out.get("estimate"),
                    retry_after=out.get("retry_after_s"))
            raise QueueFullError(out.get("error", "queue full"),
                                 retry_after=out.get("retry_after_s"))
        if status == 503:
            if out.get("error_type") == DeadlineExceededError.error_type:
                raise DeadlineExceededError(
                    out.get("error", "deadline exceeded"))
            raise SchedulerClosed(out.get("error", "draining"))
        if status != 202:
            raise RuntimeError(f"submit failed ({status}): {out}")
        return out["id"]

    def result(self, request_id: str) -> Dict:
        status, out = self._call("GET", f"/v1/result/{request_id}")
        if status == 404:
            raise RequestFailedError(
                f"unknown request {request_id!r} (finished + evicted, or "
                f"never submitted here): {out}")
        if status != 200:
            raise RuntimeError(f"result failed ({status}): {out}")
        return out

    def wait(self, request_id: str, timeout: float = 60.0,
             poll: float = 0.02) -> Dict:
        import time

        deadline = time.perf_counter() + timeout
        while True:
            out = self.result(request_id)
            if out["status"] in (Request.DONE, Request.FAILED):
                return out
            if time.perf_counter() > deadline:
                raise TimeoutError(f"request {request_id} not done in time")
            time.sleep(poll)

    def stream(self, request_id: str):
        """Yield generated tokens incrementally from the NDJSON stream.

        The server's final line carries the request state; anything other
        than "done" raises so a truncated stream can't be mistaken for a
        complete generation — :class:`RequestFailedError` when the engine
        reported the request failed (replica healthy),
        :class:`StreamIncompleteError` on the server-side stream timeout
        (request still running), plain RuntimeError only for transport
        truncation (the replica or its handler died mid-stream)."""
        from ..resilience.inject import fire as _inject_fire

        f = _inject_fire("router.transport", addr=self.addr, method="GET",
                         path=f"/v1/stream/{request_id}")
        c = self._conn()
        try:
            c.request("GET", f"/v1/stream/{request_id}")
            r = c.getresponse()
            if f is not None and f.kind == "garbage":
                # the stream connected but the first read is corrupt —
                # parses as garbage JSON, the death-truncation shape
                raise ValueError(
                    f"injected garbage stream body from {self.addr}")
            if r.status == 404:
                raise RequestFailedError(
                    f"unknown request {request_id!r} on this replica")
            if r.status != 200:
                raise RuntimeError(f"stream failed ({r.status})")
            buf = b""
            while True:
                chunk = r.read1(65536) if hasattr(r, "read1") else r.read(1)
                if not chunk:
                    # transport EOF before the done sentinel: the server (or
                    # its handler thread) died mid-stream — truncation must
                    # raise, never masquerade as completion
                    raise RuntimeError(
                        f"stream for {request_id} closed without completing")
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if not line.strip():
                        continue
                    msg = json.loads(line.decode())
                    if msg.get("done"):
                        if msg.get("status") == Request.FAILED:
                            raise RequestFailedError(
                                f"request {request_id} failed after "
                                f"{msg.get('n_tokens')} tokens",
                                error_type=msg.get("error_type"))
                        if msg.get("status") != Request.DONE:
                            raise StreamIncompleteError(
                                f"stream for {request_id} ended incomplete "
                                f"(status={msg.get('status')!r} after "
                                f"{msg.get('n_tokens')} tokens)")
                        return
                    yield msg["token"]
        finally:
            c.close()

    def metrics(self) -> Dict:
        status, out = self._call("GET", "/metrics")
        if status != 200:
            raise RuntimeError(f"metrics failed ({status})")
        return out

    def migrate_export(self, request_id: str) -> Dict:
        """Ask the replica to drain one active stream into a continuation
        record (live-migration source half). Raises
        :class:`RequestFailedError` when the replica answers that the id
        is not exportable (unknown/finished: 404) and RuntimeError with
        the 409 body for a mid-prefill slot (retry next tick)."""
        status, out = self._call("POST", "/admin/migrate_export",
                                 {"id": request_id}, retries=0)
        if status == 404:
            raise RequestFailedError(
                f"request {request_id!r} not exportable: {out.get('error')}")
        if status != 200:
            raise RuntimeError(
                f"migrate_export failed ({status}): {out.get('error', out)}")
        return out

    def migrate_import(self, record: Dict,
                       trace_id: Optional[str] = None,
                       parent_span_id: Optional[str] = None,
                       deadline_s: Optional[float] = None) -> str:
        """Hand a continuation record to the target replica (live-migration
        import half). NO transport retry — like submit, a lost 202 would
        duplicate the continuation. Raises the same typed backpressure
        errors as :meth:`submit`."""
        headers = {}
        if trace_id:
            headers[obstrace.TRACE_HEADER] = trace_id
        if parent_span_id:
            headers[obstrace.PARENT_HEADER] = parent_span_id
        if deadline_s is not None:
            headers[obstrace.DEADLINE_HEADER] = repr(float(deadline_s))
        status, out = self._call("POST", "/admin/migrate_import", record,
                                 retries=0, headers=headers or None)
        if status == 429:
            if out.get("error_type") == AdmissionRejected.error_type:
                raise AdmissionRejected(
                    out.get("error", "admission refused"),
                    estimate=out.get("estimate"),
                    retry_after=out.get("retry_after_s"))
            raise QueueFullError(out.get("error", "queue full"),
                                 retry_after=out.get("retry_after_s"))
        if status == 503:
            if out.get("error_type") == DeadlineExceededError.error_type:
                raise DeadlineExceededError(
                    out.get("error", "deadline exceeded"))
            raise SchedulerClosed(out.get("error", "draining"))
        if status == 400:
            raise ValueError(out.get("error", "bad continuation record"))
        if status != 202:
            raise RuntimeError(f"migrate_import failed ({status}): {out}")
        return out["id"]

    def admin_drain(self) -> Dict:
        """Ask the replica to stop admitting (drain step 1); poll
        :meth:`metrics` until queue depth and active slots hit zero to know
        the drain finished."""
        status, out = self._call("POST", "/admin/drain")
        if status != 200:
            raise RuntimeError(f"drain failed ({status}): {out}")
        return out
