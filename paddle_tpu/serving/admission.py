"""Memory-aware admission control + overload protection for the engine.

Parity: Paddle Inference's deployment surface exposes capacity knobs
(workspace/memory-pool sizing, max batch, queue bounds) that operators tune
by hand; Paddle Serving rejects on queue overflow and nothing else. This
module replaces hand-tuned capacity with the r10 static analyzer used AS A
RUNTIME COMPONENT (ROADMAP item 1's graduation): the liveness-based
peak-HBM estimator (:mod:`paddle_tpu.analysis.memory`) prices each
request's prefill program — params + buffers + both KV cache halves
resident, plus the bucket's activation transient — and the admission gate
refuses work whose predicted footprint exceeds the device budget, citing
the estimate in the refusal body.

Three layers, composable and individually optional:

* :class:`AdmissionGate` — per-bucket liveness pricing against
  ``budget_bytes``. A refusal is :class:`AdmissionRejected` (HTTP 429 +
  ``Retry-After``) whose ``estimate`` dict carries the predicted peak, the
  resident breakdown, the per-slot KV share, and the budget — operators
  see WHY in the error body, not in a log. Estimates are cached per
  bucket; pricing holds the engine's trace lock and restores the compile
  counters (pricing is a trace, not a compile). Paged KV layout (r15):
  the gate ALSO prices the predicted **page-pool watermark** — pages
  resident + reserved for queued admissions + this request's worst-case
  need net of radix-resident prefixes — against the page budget; pages
  are the allocation unit, so predicted-resident tracks true occupancy,
  and the 429 cites ``pages{predicted/free/budget}``.
* **Deadline propagation** — a request's ``deadline_s`` rides the r12
  header family (:data:`~paddle_tpu.observability.trace.DEADLINE_HEADER`,
  remaining-seconds relative so clock skew cannot bite). A request whose
  deadline elapses while QUEUED is failed with
  :class:`DeadlineExceededError` (503 + JSON body) before prefill — work
  that cannot start before its deadline is shed from the queue instead of
  timing out mid-decode and wasting the slots it stole.
* :class:`LoadShedPolicy` — goodput-preserving shedding under sustained
  overload: when the queue holds more than ``high_watermark`` requests
  continuously for ``sustain_s``, the OLDEST queued requests (they have
  burned the most deadline and are likeliest to be abandoned/retried
  already) are shed down to ``low_watermark`` with a retryable error +
  Retry-After hint. Requests that reached a slot are NEVER shed — a
  started generation always finishes, which is what keeps admitted-request
  TTFT bounded (the 2×-overload acceptance bound) instead of everyone
  timing out together. Shed counters land in the r12 metrics registry
  (``serving_requests_shed_total{reason}``) and each overload episode is
  flight-recorded once.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

__all__ = [
    "AdmissionGate",
    "AdmissionRejected",
    "DeadlineExceededError",
    "LoadShedPolicy",
    "SHED_ERROR_TYPE",
    "DEADLINE_ERROR_TYPE",
]

#: ``error_type`` strings stamped on requests failed by this layer (the
#: JSON bodies' typed discriminator — clients switch on these, not on
#: message prose)
SHED_ERROR_TYPE = "ShedError"
DEADLINE_ERROR_TYPE = "DeadlineExceededError"


class DeadlineExceededError(RuntimeError):
    """The request's deadline elapsed before it could start (at submit, in
    the queue, or pre-prefill) — HTTP 503 with a typed JSON body."""

    http_status = 503
    error_type = DEADLINE_ERROR_TYPE


class AdmissionRejected(RuntimeError):
    """The admission gate refused the request: its predicted KV+prefill
    HBM exceeds the configured device budget. ``estimate`` carries the
    liveness numbers the refusal is based on (cited verbatim in the HTTP
    error body); ``retry_after`` is the backpressure hint."""

    http_status = 429
    error_type = "AdmissionRejected"

    def __init__(self, msg: str, estimate: Optional[Dict] = None,
                 retry_after: Optional[float] = None):
        super().__init__(msg)
        self.estimate = dict(estimate or {})
        self.retry_after = None if retry_after is None else float(retry_after)


class AdmissionGate:
    """Prices a request's prefill program with the r10 liveness estimator
    and refuses over-budget work.

    ``budget_bytes``: per-device HBM budget the engine may occupy at
    prefill peak. ``safety_frac`` scales the prediction (the estimator is
    certified within 15% of measured — a 1.15 safety factor makes the gate
    conservative against that bound)."""

    def __init__(self, engine, budget_bytes: int, *,
                 safety_frac: float = 1.0, precompute: bool = False,
                 page_budget: Optional[int] = None):
        self.engine = engine
        self.budget_bytes = int(budget_bytes)
        self.safety_frac = float(safety_frac)
        # bucket -> MemoryEstimate; guarded-by: self._lock
        self._estimates: Dict[int, object] = {}
        self._lock = threading.Lock()
        # page-pool watermark (paged KV layout): pages are the allocation
        # unit, so predicted-resident tracks true occupancy — the gate
        # reserves each admitted request's worst-case page need until the
        # engine allocates (or the request fails), and refuses work whose
        # predicted watermark would exceed the pool
        paged = getattr(engine, "kv_layout", "slot") == "paged"
        if page_budget is None and paged:
            page_budget = engine._pool.capacity
        self.page_budget = None if page_budget is None else int(page_budget)
        self._committed_pages = 0  # guarded-by: self._lock
        if precompute:
            for b in engine.scheduler.buckets:
                self.estimate_for_bucket(b)

    # -- pricing --------------------------------------------------------
    def _build_estimate(self, bucket: int):
        from ..analysis.graph import AnalysisTarget
        from ..analysis.memory import estimate_memory

        eng = self.engine
        args = eng._prefill_arg_specs(bucket)
        target = AnalysisTarget(
            f"serving_prefill_b{int(bucket)}", eng._prefill_jit, args,
            tags=("serving",), donate_argnums=eng._donate_prefill)
        # tracing the prefill body mutates the SHARED model's attention
        # layers and bumps the engine's compile counters; pricing must do
        # neither observably — hold the model trace lock and restore the
        # counters even when the trace dies partway (a priced bucket is
        # not a compiled bucket, failed or not)
        with eng._trace_lock:
            before = dict(eng.trace_counts)
            try:
                # pricing IS a trace by design (r15): the model trace lock
                # must be held for the whole jaxpr build or a concurrent
                # engine trace reads our tracers
                # hostrace: ok(host-blocking-under-lock)
                target.jaxpr()
            finally:
                eng.trace_counts.update(before)
        return estimate_memory(target)

    def estimate_for_bucket(self, bucket: int):
        """Cached :class:`~paddle_tpu.analysis.memory.MemoryEstimate` of
        the prefill program at ``bucket``."""
        bucket = int(bucket)
        with self._lock:
            est = self._estimates.get(bucket)
        if est is None:
            est = self._build_estimate(bucket)
            with self._lock:
                self._estimates.setdefault(bucket, est)
        return est

    def kv_bytes_per_slot(self) -> int:
        """One slot's worst-case share of the paired K/V state: the whole
        ``[L, S, H, D]`` row for the slot layout, ``max_pages_per_slot``
        pages for the paged layout (actual paged usage is live pages —
        see the ``pages`` dict in :meth:`price`)."""
        eng = self.engine
        import numpy as np

        if getattr(eng, "kv_layout", "slot") == "paged":
            return eng.max_pages_per_slot * eng.page_bytes
        per_el = np.dtype(eng._cache_dtype).itemsize
        l, n, h, s, d = eng._cache_shape
        return 2 * l * h * s * d * per_el

    def price(self, bucket: int) -> Dict:
        """The liveness numbers for one bucket, JSON-ready (this dict IS
        the ``estimate`` body a refusal cites)."""
        est = self.estimate_for_bucket(bucket)
        predicted = int(est.peak_bytes * self.safety_frac)
        return {
            "source": "analysis.memory liveness estimator",
            "bucket": int(bucket),
            "predicted_peak_hbm_bytes": predicted,
            "raw_peak_hbm_bytes": int(est.peak_bytes),
            "safety_frac": self.safety_frac,
            "resident_bytes": int(est.resident_bytes),
            "args_bytes": int(est.args_bytes),
            "kv_bytes_per_slot": int(self.kv_bytes_per_slot()),
            "budget_bytes": int(self.budget_bytes),
            "peak_site": est.peak_where,
        }

    def predicted_live_bytes(self, bucket: Optional[int] = None) -> int:
        """Predicted post-prefill RESIDENT footprint: every entry arg
        (params, buffers, both cache halves — donated args alias outputs,
        so they stay live) plus closure consts. This is the number the
        accounting test holds against the ``jax.live_arrays()`` census
        (the r10 estimator-vs-measured 15% bound, now on the serving
        plane)."""
        if bucket is None:
            bucket = max(self.engine.scheduler.buckets)
        est = self.estimate_for_bucket(bucket)
        return int(est.args_bytes + est.consts_bytes)

    # -- page-pool watermark (paged layout) -----------------------------
    def page_watermark(self, req=None) -> Optional[Dict]:
        """Predicted page-pool occupancy if ``req`` were admitted now:
        pages currently allocated + pages reserved for queued admissions
        + this request's worst-case need (net of resident shared
        prefixes). None for the slot layout."""
        eng = self.engine
        if getattr(eng, "kv_layout", "slot") != "paged":
            return None
        state = eng.page_state()
        need = eng.pages_needed(req) if req is not None else 0
        with self._lock:
            committed = self._committed_pages
        return {
            "predicted": state["used"] + committed + need,
            "needed": need,
            "committed_queued": committed,
            "used": state["used"],
            "free": state["free"],
            "budget": self.page_budget,
            "page_bytes": state["page_bytes"],
            "kv_dtype": str(getattr(eng, "kv_dtype", None)
                            or eng._cache_dtype),
        }

    def settle(self, req):
        """The engine placed (or failed) a request whose page reservation
        this gate holds — release it. Idempotent per request."""
        n = getattr(req, "_page_commit", None)
        if n:
            req._page_commit = None
            with self._lock:
                self._committed_pages = max(self._committed_pages - int(n), 0)

    # -- the gate -------------------------------------------------------
    def check(self, req) -> Dict:
        """Admit or refuse ``req``; returns the price on admit, raises
        :class:`AdmissionRejected` (estimate attached) on refusal. Paged
        layout: the refusal cites the predicted page-pool watermark
        (predicted/free/budget) alongside the liveness bytes."""
        # the gate runs BEFORE scheduler.submit assigns req.bucket, so the
        # fallback must price what will actually be prefilled: for a
        # continuation join that is prompt+observed (net of radix-resident
        # pages on the page side), not the bare prompt
        bucket = req.bucket or self.engine.scheduler.bucket_for(
            req.prefill_len)
        price = self.price(bucket)
        if price["predicted_peak_hbm_bytes"] > self.budget_bytes:
            pages = self.page_watermark(req)
            if pages is not None:
                price["pages"] = pages
            raise AdmissionRejected(
                f"admission refused: predicted KV+prefill HBM "
                f"{price['predicted_peak_hbm_bytes']} bytes exceeds the "
                f"device budget {self.budget_bytes} bytes "
                f"(bucket {bucket}, liveness peak at "
                f"{price['peak_site'] or 'entry'})",
                estimate=price, retry_after=self._hint())
        eng = self.engine
        if getattr(eng, "kv_layout", "slot") == "paged":
            state = eng.page_state()
            need = eng.pages_needed(req)
            # predict-compare-COMMIT under one lock: two concurrent
            # submits must not both read the pre-commit reservation count
            # and jointly over-admit past the page budget
            with self._lock:
                pages = {
                    "predicted": state["used"] + self._committed_pages
                                 + need,
                    "needed": need,
                    "committed_queued": self._committed_pages,
                    "used": state["used"],
                    "free": state["free"],
                    "budget": self.page_budget,
                    "page_bytes": state["page_bytes"],
                    # the quantized layout the budget was priced for: int8
                    # pages are ~half the f16 bytes, so the SAME budget
                    # admits ~2x the pages — cite which layout this is
                    "kv_dtype": str(getattr(eng, "kv_dtype", None)
                                    or eng._cache_dtype),
                }
                admitted = pages["predicted"] <= pages["budget"]
                if admitted:
                    req._page_commit = need
                    self._committed_pages += need
            price["pages"] = pages
            if not admitted:
                raise AdmissionRejected(
                    f"admission refused: predicted page-pool watermark "
                    f"{pages['predicted']} pages (resident "
                    f"{pages['used']} + queued "
                    f"{pages['committed_queued']} + this request "
                    f"{pages['needed']}) exceeds the page budget "
                    f"{pages['budget']} ({pages['free']} free, "
                    f"{pages['page_bytes']} B/page, "
                    f"kv_dtype {pages['kv_dtype']})",
                    estimate=price, retry_after=self._hint())
        return price

    def _hint(self) -> float:
        try:
            return self.engine.metrics.retry_after_hint(
                queue_depth=self.engine.scheduler.depth())
        except Exception:
            return 1.0


class LoadShedPolicy:
    """Oldest-queued-first shedding under sustained overload.

    ``high_watermark``/``low_watermark`` default to ``n_slots`` and
    ``n_slots // 2`` when bound to an engine: a queue holding more than
    one full batch continuously for ``sustain_s`` is sustained overload
    (arrivals outpace the slot turnover), and trimming to half a batch
    keeps every ADMITTED request's queue wait under roughly one
    generation — which is what holds admitted p99 TTFT within the 3×-of-
    unloaded acceptance bound while the slots stay saturated (goodput
    preserved: only queued work is shed, active slots are never touched)."""

    def __init__(self, *, high_watermark: Optional[int] = None,
                 low_watermark: Optional[int] = None,
                 sustain_s: float = 0.25):
        self.high_watermark = high_watermark
        self.low_watermark = low_watermark
        self.sustain_s = float(sustain_s)
        self.shed_total = 0        # guarded-by: self._lock
        # guarded-by: self._lock
        self._over_since: Optional[float] = None
        self._episode_dumped = False  # guarded-by: self._lock
        self._lock = threading.Lock()
        self._bound_engine = None

    def bind(self, engine):
        # one policy per engine: the sustain timer and episode flag are
        # per-queue state — silently sharing an instance across engines
        # would let one engine's recovery reset the other's sustain clock
        if self._bound_engine is not None and self._bound_engine is not engine:
            raise ValueError(
                "LoadShedPolicy is already bound to another engine; "
                "construct one policy per engine")
        self._bound_engine = engine
        if self.high_watermark is None:
            self.high_watermark = engine.n_slots
        if self.low_watermark is None:
            self.low_watermark = max(1, engine.n_slots // 2)
        if self.low_watermark > self.high_watermark:
            raise ValueError("low_watermark must be <= high_watermark")
        return self

    def victims(self, scheduler, now: Optional[float] = None) -> List:
        """The requests to shed THIS tick (popped oldest-first from the
        queue; empty while overload is not sustained). The caller fails
        them — the policy only decides."""
        # det-ok: sustained-overload timing (Retry-After family) is
        # wall-clock by contract; deterministic callers inject `now`
        now = time.monotonic() if now is None else now
        depth = scheduler.depth()
        with self._lock:
            if depth <= self.high_watermark:
                self._over_since = None
                if depth <= self.low_watermark:
                    self._episode_dumped = False
                return []
            if self._over_since is None:
                self._over_since = now
                return []
            if now - self._over_since < self.sustain_s:
                return []
        out = scheduler.shed_oldest(depth - self.low_watermark)
        with self._lock:
            self.shed_total += len(out)
            shed_total_now = self.shed_total  # captured for the dump
            first_of_episode = out and not self._episode_dumped
            if first_of_episode:
                self._episode_dumped = True
        if first_of_episode:
            # one flight dump per overload episode: the ring still holds
            # the spans leading into saturation, and the dump freezes the
            # shed/breaker counters alongside them
            from ..observability.flight import flight_recorder

            flight_recorder().dump(
                "sustained_overload",
                extra={"queue_depth": depth,
                       "high_watermark": self.high_watermark,
                       "shed_now": len(out),
                       "shed_total": shed_total_now})
        return out
