"""Speculative decoding under the tick scheduler (ISSUE 18).

Leviathan et al., "Fast Inference from Transformers via Speculative
Decoding" (ICML 2023), composed with the paged engine: a small DRAFT
model proposes ``k`` greedy tokens per tick, and the TARGET model
verifies all of them in ONE batched multi-token step — the same paged
programs machinery the chunked prefill already uses, so the verify step
is one jitted program regardless of ``k``.

The acceptance rule is exact-match prefix accept against the target's
OWN samples: at every proposed position the target draws its token from
its own logits with the stream's real PRNG chain (greedy when
``temperature <= 0``), and the draft's proposal only decides whether the
NEXT position's logits had the right context.  Emitted tokens are
therefore always the target's tokens with the baseline key discipline —
spec output is token-for-token identical to the non-speculative engine
(greedy AND sampled), which is the replay certificate; the draft only
changes how many tokens one target step amortizes (1..k+1).

Composition with the rest of the serving plane:

* **paged COW / prefix sharing** — the draft keeps its OWN fp pools
  (``[L_d, n_pages, H_d, page_size, D_d]``) indexed by the SAME per-slot
  page tables; on activation it chunk-prefills the stream's sequence
  through the slot's table, so radix-shared and COW pages simply get the
  draft's (deterministic, identical) K/V written once more — harmless.
* **page accounting** — verify writes positions ``pos..pos+k``, so the
  tick pre-allocates the lookahead pages (victim-only failure, exactly
  like ``_ensure_decode_pages``); pages past the accepted frontier are
  released immediately after verify (``spec_rollback_pages``).
* **r21 continuation joins** — a resurrected spec stream re-homes
  through the ordinary join path; ``on_activate`` rebuilds the history
  from ``prefill_ids() + [first]`` so the key-chain position invariant
  (splits == emitted tokens) is untouched.
* **r13 fault injection** — the ``serving.spec.verify`` seam fires per
  active stream before the verify program; a raise-kind fault fails ONLY
  the matched request(s), and the remaining streams fall back to the
  plain decode step for that tick (``spec_fallback_ticks``).

Staleness safety: positions past the accepted frontier hold rejected
K/V in the target pool (and mispredicted K/V in the draft pool), but the
next round's writes start exactly at the frontier and every program
scatters before it gathers, with reads masked to ``j <= wpos`` — stale
entries are always overwritten before an unmasked read, the same
argument the chunked-prefill padding already relies on.
"""
from __future__ import annotations

import contextlib
import time
from typing import Dict, List, Optional

import numpy as np

from .paged import TRASH_PAGE, PagesExhaustedError

__all__ = ["SpecDecodeConfig", "SpecDecodeState"]


class SpecDecodeConfig:
    """Knobs for the speculative plane: ``draft_model`` (a small
    GPTForPretraining sharing the target's tokenizer/vocab) and ``k``
    (draft tokens proposed per verify step)."""

    def __init__(self, draft_model, k: int = 4):
        self.draft_model = draft_model
        self.k = int(k)
        if self.k < 1:
            raise ValueError("spec_decode k must be >= 1")


class SpecDecodeState:
    """Per-engine speculative-decoding state + programs (lock discipline:
    every method except construction runs with the engine tick lock
    held)."""

    def __init__(self, engine, config):
        if not isinstance(config, SpecDecodeConfig):
            raise TypeError("spec_decode expects a SpecDecodeConfig")
        import jax.numpy as jnp

        from ..models.generation import _attn_layers
        from ..models.gpt import GPTForPretraining
        from .engine import _model_trace_lock

        draft = config.draft_model
        if not isinstance(draft, GPTForPretraining):
            raise TypeError("draft_model must be a GPTForPretraining")
        dcfg = draft.gpt.config
        tcfg = engine.model.gpt.config
        if dcfg.position_embedding == "rope":
            raise NotImplementedError(
                "draft model must be learned-position (same engine "
                "restriction as the target)")
        if dcfg.vocab_size != tcfg.vocab_size:
            raise ValueError(
                f"draft vocab {dcfg.vocab_size} != target vocab "
                f"{tcfg.vocab_size}: proposals would not be token ids "
                f"the target understands")
        draft.eval()
        self.engine = engine
        self.config = config
        self.k = config.k
        self.draft = draft
        self._draft_attns = _attn_layers(draft)
        self._d_layers = dcfg.num_layers
        self._d_heads = dcfg.num_attention_heads
        self._d_head_dim = dcfg.head_dim
        self._draft_params = {n: p._data for n, p in draft.named_parameters()}
        self._draft_buffers = {n: b._data for n, b in draft.named_buffers()}
        # draft pools: same page geometry as the engine's, draft widths,
        # always fp (the draft is small — quantizing it buys nothing)
        self._draft_pool_shape = (self._d_layers, engine.n_pages,
                                  self._d_heads, engine.page_size,
                                  self._d_head_dim)
        self._dpool_k = jnp.zeros(self._draft_pool_shape,
                                  engine._cache_dtype)
        self._dpool_v = jnp.zeros(self._draft_pool_shape,
                                  engine._cache_dtype)
        # per-slot host state: full token history (prompt + generated;
        # hist[p] is the token AT position p, len == pos + 1) and the
        # draft KV frontier (positions 0..dp-1 hold valid draft K/V)
        self._hist: List[Optional[List[int]]] = [None] * engine.n_slots
        self._draft_pos = np.zeros((engine.n_slots,), np.int64)
        self.trace_counts: Dict[str, int] = {
            "draft_prefill": 0, "draft_step": 0, "verify": 0}
        self._draft_trace_lock = _model_trace_lock(draft)
        self._draft_traced_buckets: set = set()
        self._build_programs()

    # -- traced programs ---------------------------------------------------
    def _build_programs(self):
        import jax
        import jax.numpy as jnp

        from ..autograd.tape import no_grad
        from ..models.generation import sample_tokens
        from ..ops._primitive import unwrap, wrap
        from ..profiler.scope import scope

        eng = self.engine
        draft, dattns = self.draft, self._draft_attns
        tattns = eng._attns
        ps = eng.page_size
        k = self.k
        quant = eng._kv_quant

        def _draft_forward(params, buffers, ids_t, position_ids_t):
            out, _ = draft.functional_call_with_state(
                params, buffers, ids_t, position_ids_t)
            return unwrap(out)

        def _target_forward(params, buffers, ids_t, position_ids_t):
            out, _ = eng.model.functional_call_with_state(
                params, buffers, ids_t, position_ids_t)
            return unwrap(out)

        def _set_draft_caches(pk, pv, pages, pos):
            for li, a in enumerate(dattns):
                a._gen_cache = {"mode": "paged", "k": pk[li], "v": pv[li],
                                "pages": pages, "pos": pos,
                                "page_size": ps, "attn_impl": "xla"}

        def _collect_draft_caches():
            pk = jnp.stack([unwrap(a._gen_cache["k"]) for a in dattns])
            pv = jnp.stack([unwrap(a._gen_cache["v"]) for a in dattns])
            return pk, pv

        def _clear(attns):
            for a in attns:
                if hasattr(a, "_gen_cache"):
                    del a._gen_cache

        def draft_prefill_fn(params, buffers, ids, start, pages, pk, pv):
            # one chunk of the draft's catch-up prefill: write K/V only,
            # no sampling (the first propose step refeeds hist[pos])
            self.trace_counts["draft_prefill"] += 1
            start = start.astype(jnp.int32)
            tc = ids.shape[1]
            pos_ids = (start + jnp.arange(tc, dtype=jnp.int32))[None, :]
            _set_draft_caches(pk, pv, pages[None, :], start[None])
            try:
                with no_grad():
                    _draft_forward(params, buffers, wrap(ids),
                                   wrap(pos_ids))
                pk, pv = _collect_draft_caches()
            finally:
                _clear(dattns)
            return pk, pv

        def draft_step_fn(params, buffers, tok, pos, tables, pk, pv):
            # one greedy draft token for every slot row (used both for
            # catch-up rewrites and for the k propose steps)
            self.trace_counts["draft_step"] += 1
            posj = pos.astype(jnp.int32)
            _set_draft_caches(pk, pv, tables, posj)
            try:
                with no_grad():
                    logits = _draft_forward(params, buffers, wrap(tok),
                                            wrap(posj[:, None]))
                pk, pv = _collect_draft_caches()
            finally:
                _clear(dattns)
            nxt = jnp.argmax(logits[:, -1].astype(jnp.float32),
                             axis=-1).astype(jnp.int32)
            return nxt, pk, pv

        def _set_target_caches(pk, pv, pages, pos, scales):
            for li, a in enumerate(tattns):
                c = {"mode": "paged", "k": pk[li], "v": pv[li],
                     "pages": pages, "pos": pos, "page_size": ps,
                     "attn_impl": eng.attn_impl}
                if scales:
                    c["k_scale"] = scales[0][li]
                    c["v_scale"] = scales[1][li]
                a._gen_cache = c

        def _collect_target_caches():
            pk = jnp.stack([unwrap(a._gen_cache["k"]) for a in tattns])
            pv = jnp.stack([unwrap(a._gen_cache["v"]) for a in tattns])
            if not quant:
                return pk, pv, ()
            sk = jnp.stack([unwrap(a._gen_cache["k_scale"])
                            for a in tattns])
            sv = jnp.stack([unwrap(a._gen_cache["v_scale"])
                            for a in tattns])
            return pk, pv, (sk, sv)

        def verify_fn(params, buffers, toks, pos, active, temp, topk,
                      topp, keys, tables, pk, pv, *scales):
            # toks [n, k+1]: column 0 = the stream's last sampled token
            # (position pos), columns 1..k the draft proposals.  ONE
            # target forward writes K/V for all k+1 positions and yields
            # logits for positions pos+1..pos+k+1; the unrolled accept
            # loop then samples each position with the stream's real key
            # chain, emitting while the accept chain holds.  The key
            # chain advances by EXACTLY the emitted count per slot —
            # the baseline splits == tokens invariant.
            self.trace_counts["verify"] += 1
            posj = pos.astype(jnp.int32)
            pos_ids = posj[:, None] + jnp.arange(k + 1,
                                                 dtype=jnp.int32)[None, :]
            _set_target_caches(pk, pv, tables, posj, scales)
            try:
                with no_grad():
                    logits = _target_forward(params, buffers, wrap(toks),
                                             wrap(pos_ids))
                pk, pv, scales = _collect_target_caches()
            finally:
                _clear(tattns)
            logits = logits.astype(jnp.float32)
            acc = active
            cur_keys = keys
            outs = []
            emitted = jnp.zeros(active.shape, jnp.int32)
            for j in range(k + 1):
                pair = jax.vmap(lambda k_: jax.random.split(k_))(cur_keys)
                with scope("serving.sample"):
                    tok_j = sample_tokens(logits[:, j], pair[:, 1], temp,
                                          topk, topp).astype(jnp.int32)
                emit = acc
                outs.append(jnp.where(emit, tok_j, 0))
                cur_keys = jnp.where(emit[:, None], pair[:, 0], cur_keys)
                emitted = emitted + emit.astype(jnp.int32)
                if j < k:
                    acc = acc & (tok_j == toks[:, j + 1])
            out = jnp.stack(outs, axis=1)          # [n, k+1]
            return (out, emitted, cur_keys, pk, pv) + tuple(scales)

        # donation mirrors the engine: pools + key chains are the only
        # large threaded state (recorded always, applied off-CPU)
        self._donate_draft_prefill = (5, 6)        # pk, pv
        self._donate_draft_step = (5, 6)           # pk, pv
        self._donate_verify = (8, 10, 11)          # keys, pk, pv
        if quant:
            self._donate_verify += (12, 13)
        on_cpu = jax.default_backend() == "cpu"
        self._draft_prefill_jit = jax.jit(
            draft_prefill_fn,
            donate_argnums=() if on_cpu else self._donate_draft_prefill)
        self._draft_step_jit = jax.jit(
            draft_step_fn,
            donate_argnums=() if on_cpu else self._donate_draft_step)
        self._verify_jit = jax.jit(
            verify_fn, donate_argnums=() if on_cpu else self._donate_verify)

    # -- lifecycle hooks (engine tick lock held) ---------------------------
    def on_activate(self, slot: int, req, first: int, pos: int):
        """A stream entered decode: rebuild its token history and chunk-
        prefill the draft's KV over positions ``0..pos-1`` through the
        slot's page table (shared/COW pages get identical values —
        harmless rewrites)."""
        import jax.numpy as jnp

        eng = self.engine
        hist = [int(t) for t in req.prefill_ids()] + [int(first)]
        assert len(hist) == pos + 1, (len(hist), pos)
        self._hist[slot] = hist
        self._draft_pos[slot] = 0
        seq = np.asarray(hist[:pos], np.int32)
        table = eng._page_tables[slot]
        start = 0
        while start < pos:
            rlen = min(pos - start, eng._chunk_limit)
            bucket = eng._chunk_bucket_for(rlen)
            ids = np.zeros((1, bucket), np.int32)
            ids[0, :rlen] = seq[start:start + rlen]
            guard = (contextlib.nullcontext()
                     if bucket in self._draft_traced_buckets
                     else self._draft_trace_lock)
            with guard:
                self._dpool_k, self._dpool_v = self._draft_prefill_jit(
                    self._draft_params, self._draft_buffers,
                    jnp.asarray(ids), jnp.asarray(np.int32(start)),
                    jnp.asarray(table), self._dpool_k, self._dpool_v)
            self._draft_traced_buckets.add(bucket)
            start += rlen
        self._draft_pos[slot] = pos

    def on_token(self, slot: int, token: int):
        """A token emitted OUTSIDE the spec path (plain-decode fallback
        tick): extend the history; the draft frontier lags and the next
        spec tick's catch-up loop closes the gap."""
        h = self._hist[slot]
        if h is not None:
            h.append(int(token))

    def on_free(self, slot: int):
        self._hist[slot] = None
        self._draft_pos[slot] = 0

    def reset(self):
        """Pool-loss / fail-pending recovery: every stream is gone, so
        drop all spec state and re-zero the draft pools (page content is
        meaningless once the engine pool was reset)."""
        import jax.numpy as jnp

        self._hist = [None] * self.engine.n_slots
        self._draft_pos[:] = 0
        self._dpool_k = jnp.zeros(self._draft_pool_shape,
                                  self.engine._cache_dtype)
        self._dpool_v = jnp.zeros(self._draft_pool_shape,
                                  self.engine._cache_dtype)

    # -- per-tick helpers --------------------------------------------------
    def _active_slots(self) -> List[int]:
        eng = self.engine
        return [i for i in range(eng.n_slots)
                if eng._active[i] and self._hist[i] is not None]

    def _run_draft_step(self, tok, pos, tables):
        import jax.numpy as jnp

        guard = (self._draft_trace_lock
                 if self.trace_counts["draft_step"] == 0
                 else contextlib.nullcontext())
        with guard:
            nxt, self._dpool_k, self._dpool_v = self._draft_step_jit(
                self._draft_params, self._draft_buffers,
                jnp.asarray(tok[:, None]), jnp.asarray(pos), tables,
                self._dpool_k, self._dpool_v)
        return np.asarray(nxt)

    def _catch_up(self, slots, tables):
        """Advance every lagging stream's draft frontier to ``pos`` with
        batched draft steps; caught-up rows run the idempotent rewrite
        ``(hist[pos-1], pos-1)`` (same token, same position — a no-op
        write) so the batch shape never changes."""
        eng = self.engine
        gaps = [int(eng._pos[i]) - int(self._draft_pos[i]) for i in slots]
        for _ in range(max(gaps, default=0)):
            tok = np.zeros((eng.n_slots,), np.int32)
            pos = np.zeros((eng.n_slots,), np.int32)
            for i in slots:
                h = self._hist[i]
                dp = int(self._draft_pos[i])
                p = int(eng._pos[i])
                if dp < p:
                    tok[i], pos[i] = h[dp], dp
                else:
                    tok[i], pos[i] = h[p - 1], p - 1
            self._run_draft_step(tok, pos, tables)
            for i in slots:
                if int(self._draft_pos[i]) < int(eng._pos[i]):
                    self._draft_pos[i] += 1

    def _propose(self, slots, tables) -> np.ndarray:
        """k greedy draft steps from each stream's last sampled token;
        returns drafts ``[n_slots, k]`` (garbage on inactive rows — the
        verify masks them)."""
        eng = self.engine
        drafts = np.zeros((eng.n_slots, self.k), np.int32)
        cur = np.zeros((eng.n_slots,), np.int32)
        base = np.zeros((eng.n_slots,), np.int32)
        for i in slots:
            cur[i] = self._hist[i][int(eng._pos[i])]
            base[i] = int(eng._pos[i])
        for j in range(self.k):
            nxt = self._run_draft_step(cur, base + j, tables)
            for i in slots:
                drafts[i, j] = int(nxt[i])
                cur[i] = int(nxt[i])
        for i in slots:
            self._draft_pos[i] = int(eng._pos[i]) + self.k
        return drafts

    def _ensure_lookahead_pages(self, slots) -> List[int]:
        """Verify writes positions ``pos..pos+k``: allocate the pages
        those positions need (clamped to the request's priced worst case
        so the admission gate's math stays an upper bound).  Exhaustion
        fails ONLY the victim stream — everyone else keeps going.
        Returns the slots still alive."""
        eng = self.engine
        ps = eng.page_size
        alive = []
        for i in slots:
            req = eng._slots[i]
            p = int(eng._pos[i])
            hi = min(p + self.k,
                     int(req.prompt.size) + int(req.max_new_tokens) - 1)
            ok = True
            for pi in range(p // ps, min(hi // ps + 1,
                                         eng.max_pages_per_slot)):
                if eng._page_tables[i, pi] != TRASH_PAGE:
                    continue
                try:
                    page = eng._alloc_pages(1, "spec_lookahead")[0]
                except Exception as e:
                    req._finish(
                        req.FAILED,
                        f"{PagesExhaustedError.error_type}: page pool "
                        f"exhausted in speculative lookahead after "
                        f"{len(req.tokens)} tokens: {e}",
                        error_type=PagesExhaustedError.error_type)
                    eng._free_paged_slot(i, req)
                    ok = False
                    break
                req._pages.append(page)
                eng._page_tables[i, pi] = page
            if ok:
                alive.append(i)
        return alive

    def _rollback_pages(self, slot: int, req, new_pos: int) -> int:
        """Release lookahead pages past the accepted frontier: any table
        entry at a page index strictly beyond ``new_pos // ps`` was
        allocated THIS tick (the pre-tick table never extends past the
        write frontier) and holds only rejected-suffix K/V."""
        eng = self.engine
        ps = eng.page_size
        dropped = 0
        for pi in range(new_pos // ps + 1, eng.max_pages_per_slot):
            page = int(eng._page_tables[slot, pi])
            if page == TRASH_PAGE:
                continue
            eng._page_tables[slot, pi] = TRASH_PAGE
            try:
                req._pages.remove(page)
            except ValueError:
                pass
            eng._pool.release([page])
            dropped += 1
        return dropped

    # -- the spec tick (engine tick lock held) -----------------------------
    def tick(self):
        """One speculative decode round for every active stream: draft
        catch-up -> k proposals -> ONE batched target verify -> host
        accept/rollback bookkeeping.  Replaces ``_decode_tick_plain``
        for the tick; falls back to it when the ``serving.spec.verify``
        seam faults a stream out."""
        import jax.numpy as jnp

        from ..profiler.scope import scope
        from ..resilience.inject import fire as _inject_fire

        eng = self.engine
        slots = self._active_slots()
        if not slots:
            # defensive: active slots whose history is gone (can only
            # happen after a partial reset) decode plainly
            eng._decode_tick_plain()
            return
        t_tick = time.perf_counter()
        # fault seam: a raise-kind fault fails ONLY the matched streams;
        # the survivors decode plainly this tick (certificate: two runs
        # with the same schedule produce identical fired logs)
        faulted = False
        for i in list(slots):
            req = eng._slots[i]
            try:
                _inject_fire("serving.spec.verify",
                             request_id=req.request_id, slot=i)
            except Exception as e:
                req._finish(
                    req.FAILED,
                    f"speculative verify failed: {type(e).__name__}: {e}",
                    error_type=type(e).__name__)
                eng._free_paged_slot(i, req)
                slots.remove(i)
                faulted = True
        if faulted:
            eng.metrics.on_spec_fallback()
            if eng._active.any():
                eng._decode_tick_plain()
            return
        # pages BEFORE the draft runs: propose writes draft K/V at
        # positions pos..pos+k-1 and verify writes target K/V at
        # pos..pos+k — both through the same lookahead pages
        slots = self._ensure_lookahead_pages(slots)
        if not slots:
            return
        tables = eng._decode_tables()
        with scope("serving.spec_draft"):
            self._catch_up(slots, tables)
            drafts = self._propose(slots, tables)
        # the verify batch: toks[:, 0] = last sampled token, 1..k drafts
        toks = np.zeros((eng.n_slots, self.k + 1), np.int32)
        for i in slots:
            toks[i, 0] = self._hist[i][int(eng._pos[i])]
            toks[i, 1:] = drafts[i]
        active = np.zeros((eng.n_slots,), bool)
        for i in slots:
            active[i] = True
        before = self.trace_counts["verify"]
        guard = (eng._trace_lock if before == 0
                 else contextlib.nullcontext())
        args = (eng._params, eng._buffers, jnp.asarray(toks),
                jnp.asarray(eng._pos), jnp.asarray(active),
                jnp.asarray(eng._temp), jnp.asarray(eng._topk),
                jnp.asarray(eng._topp), jnp.asarray(eng._keys),
                tables, eng._pool_k, eng._pool_v)
        if eng._kv_quant:
            args += (eng._scale_k, eng._scale_v)
        with scope("serving.spec_verify"), guard:
            if eng._kv_quant:
                (out, counts, keys, eng._pool_k, eng._pool_v,
                 eng._scale_k, eng._scale_v) = self._verify_jit(*args)
            else:
                out, counts, keys, eng._pool_k, eng._pool_v = \
                    self._verify_jit(*args)
        out = np.asarray(out)
        counts = np.asarray(counts)
        keys = np.array(keys)
        step_s = time.perf_counter() - t_tick
        eng.metrics.on_step(self.trace_counts["verify"] > before)
        emitted_total = 0
        for i in slots:
            req = eng._slots[i]
            e = int(counts[i])            # tokens the device emitted
            h = self._hist[i]
            p = int(eng._pos[i])
            appended = 0
            finished = False
            for j in range(e):
                token = int(out[i, j])
                req._append(token)
                h.append(token)
                appended += 1
                if eng._request_finished(req, token):
                    finished = True
                    break
            emitted_total += appended
            eng.metrics.on_spec_verify(proposed=self.k, accepted=e - 1,
                                       emitted=appended)
            if finished:
                # the device chain advanced e splits but the stream ends
                # here — the slot retires and its chain is discarded, so
                # the truncation is unobservable (exactly like eos in
                # the plain engine)
                eng._retire(i, req)
                eng._slots[i] = None
                eng._active[i] = False
                continue
            new_pos = p + appended
            eng._pos[i] = new_pos
            eng._tok[i] = int(out[i, appended - 1])
            eng._keys[i] = keys[i]
            # draft K/V is valid exactly through the accepted prefix
            self._draft_pos[i] = p + min(appended, self.k)
            dropped = self._rollback_pages(i, req, new_pos)
            if dropped:
                eng.metrics.on_spec_rollback(dropped)
        eng.metrics.on_tokens(emitted_total, step_seconds=step_s)
