"""paddle_tpu.serving — continuous-batching inference serving.

Parity role: the reference's production serving plane (AnalysisPredictor /
ZeroCopyRun + Paddle Serving's batching HTTP front-end), rebuilt TPU-native:
iteration-level (Orca-style) slot scheduling over ONE fixed-shape jitted
decode step and a bounded bucketed-prefill compile cache, instead of a
dynamic-batching executor over paged GPU kernels.

    engine    — slot-based continuous batcher (fixed [n_slots, S] KV cache)
    scheduler — bounded FCFS admission, power-of-2 prefill buckets, drain
    server    — threaded HTTP submit/poll/stream front-end + retrying client
    metrics   — TTFT / token latency / throughput / occupancy / compile stats
"""
from .engine import ContinuousBatchingEngine  # noqa: F401
from .metrics import ServingMetrics  # noqa: F401
from .scheduler import (  # noqa: F401
    FCFSScheduler,
    QueueFullError,
    Request,
    SchedulerClosed,
    power_of_two_buckets,
)
from .server import ServingClient, ServingServer  # noqa: F401

__all__ = [
    "ContinuousBatchingEngine",
    "ServingMetrics",
    "FCFSScheduler",
    "QueueFullError",
    "Request",
    "SchedulerClosed",
    "power_of_two_buckets",
    "ServingClient",
    "ServingServer",
]
