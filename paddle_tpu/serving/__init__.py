"""paddle_tpu.serving — continuous-batching inference serving.

Parity role: the reference's production serving plane (AnalysisPredictor /
ZeroCopyRun + Paddle Serving's batching HTTP front-end), rebuilt TPU-native:
iteration-level (Orca-style) slot scheduling over ONE fixed-shape jitted
decode step and a bounded bucketed-prefill compile cache, instead of a
dynamic-batching executor over paged GPU kernels.

    engine    — continuous batcher over a block-paged KV pool (fixed
                [L, n_pages, H, page_size, D] pool + per-slot page tables,
                radix prefix sharing, chunked prefill; the r8 slot cache
                stays behind kv_layout="slot" as the bit-comparison
                fallback)
    paged     — host-side page allocator (refcounts, trash page) + radix
                prefix tree (match/insert/LRU-evict)
    scheduler — bounded FCFS admission, power-of-2 prefill buckets, drain
    server    — threaded HTTP submit/poll/stream front-end + retrying client
    metrics   — TTFT / token latency / throughput / occupancy / compile stats
    router    — N-replica least-loaded failover (health checks, circuit
                breaker, resubmit of never-started requests, drain-aware
                takedown)
    admission — memory-aware admission gate (r10 liveness estimator as a
                runtime component), deadline propagation, and the
                goodput-preserving overload shed policy
    spec_decode — speculative decoding: a draft model proposes k tokens
                per tick, the target verifies them in one batched step
                (greedy output token-for-token identical to the plain
                engine); rides the paged pool + COW + continuation joins
"""
from .admission import (  # noqa: F401
    AdmissionGate,
    AdmissionRejected,
    DeadlineExceededError,
    LoadShedPolicy,
)
from .engine import (  # noqa: F401
    MIGRATED_ERROR_TYPE,
    ContinuousBatchingEngine,
    make_continuation_record,
    verify_continuation_record,
)
from .metrics import ServingMetrics  # noqa: F401
from .paged import (  # noqa: F401
    PagePool,
    PagesExhaustedError,
    RadixCache,
)
from .scheduler import (  # noqa: F401
    FCFSScheduler,
    QueueFullError,
    Request,
    SchedulerClosed,
    power_of_two_buckets,
)
from .router import (  # noqa: F401
    NoReplicaAvailable,
    ResurrectionFailedError,
    RoutedRequest,
    ServingRouter,
)
from .server import (  # noqa: F401
    RequestFailedError,
    ServingClient,
    ServingServer,
    StreamIncompleteError,
)
from .spec_decode import (  # noqa: F401
    SpecDecodeConfig,
    SpecDecodeState,
)

__all__ = [
    "ContinuousBatchingEngine",
    "ServingMetrics",
    "FCFSScheduler",
    "QueueFullError",
    "Request",
    "SchedulerClosed",
    "power_of_two_buckets",
    "ServingClient",
    "ServingServer",
    "RequestFailedError",
    "StreamIncompleteError",
    "ServingRouter",
    "RoutedRequest",
    "NoReplicaAvailable",
    "ResurrectionFailedError",
    "MIGRATED_ERROR_TYPE",
    "make_continuation_record",
    "verify_continuation_record",
    "AdmissionGate",
    "AdmissionRejected",
    "DeadlineExceededError",
    "LoadShedPolicy",
    "PagePool",
    "RadixCache",
    "PagesExhaustedError",
    "SpecDecodeConfig",
    "SpecDecodeState",
]
