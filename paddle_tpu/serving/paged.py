"""Host-side bookkeeping for the block-paged KV cache (ISSUE 11).

Parity: Paddle Inference's ``memory_optimize`` pass reuses activation
buffers by liveness analysis at graph-build time; vLLM's PagedAttention
(Kwon et al., SOSP 2023) applies the same idea to serving KV state at
RUNTIME — a fixed pool of fixed-size pages, a page table per sequence,
refcounted sharing. SGLang's RadixAttention (Zheng et al., 2024) adds a
radix tree over prompt prefixes so identical system prompts are prefilled
ONCE. This module is the host half of that design, TPU-native: the device
side stays one fixed ``[L, n_pages, H, page_size, D]`` pool array and a
padded page-table tensor (static shapes, bounded compile cache — no
dynamic paged kernels), while everything that is actually *dynamic*
(allocation, refcounts, prefix matching, eviction) lives here as plain
deterministic Python:

* :class:`PagePool` — free-list allocator over page ids with refcounts.
  Page 0 is the reserved TRASH page: padded page-table entries point at
  it, so masked/pad writes land somewhere harmless that nothing ever
  reads. Exhaustion raises :class:`PagesExhaustedError` after an optional
  eviction callback (the radix cache releasing cold prefixes).
* :class:`RadixCache` — a radix tree keyed by full ``page_size``-token
  chunks of prompt token ids. ``match`` returns (and refcounts) the
  longest resident full-page prefix; ``insert`` registers a finished
  prompt's full pages for future sharing (the tree holds its own
  reference, so prefixes stay resident across requests); ``evict``
  releases least-recently-used leaves under pool pressure.

Determinism: allocation is FIFO over a deque and matching/eviction are
pure functions of the call sequence, so a replayed workload (the r13
fault-injection twins) sees bit-identical page assignments.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["PagePool", "RadixCache", "PagesExhaustedError", "TRASH_PAGE"]

#: page id 0 is never allocated: padded page-table entries and masked pad
#: writes target it, so garbage lands where no gather is ever unmasked
TRASH_PAGE = 0


class PagesExhaustedError(RuntimeError):
    """The page pool cannot satisfy an allocation even after eviction —
    the over-committed victim request is failed (visibly, typed) and its
    pages are released; everything else keeps decoding."""

    http_status = 503
    error_type = "PagesExhaustedError"


class PagePool:
    """Refcounted free-list allocator over ``n_pages`` fixed-size pages.

    ``page_bytes`` is the per-page K+V footprint (both cache halves, all
    layers) used for gauges and admission pricing; the pool itself only
    tracks ids. Thread-safe: the engine allocates under its tick lock but
    the admission gate reads occupancy from server threads.
    """

    def __init__(self, n_pages: int, page_bytes: int = 0):
        if n_pages < 2:
            raise ValueError("need at least 2 pages (trash + 1 usable)")
        self.n_pages = int(n_pages)
        self.page_bytes = int(page_bytes)
        self._lock = threading.Lock()
        self._refs = [0] * self.n_pages      # guarded-by: self._lock
        self._refs[TRASH_PAGE] = -1  # reserved, never allocated/released
        # guarded-by: self._lock
        self._free: deque = deque(range(1, self.n_pages))

    # -- capacity -------------------------------------------------------
    @property
    def capacity(self) -> int:
        """Allocatable pages (the trash page is never handed out)."""
        return self.n_pages - 1

    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    def used_count(self) -> int:
        with self._lock:
            return self.capacity - len(self._free)

    def shared_count(self) -> int:
        """Pages referenced more than once (prefix sharing in effect)."""
        with self._lock:
            return sum(1 for r in self._refs[1:] if r >= 2)

    # -- allocation -----------------------------------------------------
    def alloc(self, n: int, evict=None) -> List[int]:
        """Allocate ``n`` pages (refcount 1 each), FIFO for replay
        determinism. ``evict(n_missing)`` is called once under pressure
        (the radix cache's LRU release); still short afterwards raises
        :class:`PagesExhaustedError`."""
        n = int(n)
        if n <= 0:
            return []
        with self._lock:
            missing = n - len(self._free)
        if missing > 0 and evict is not None:
            evict(missing)
        with self._lock:
            if len(self._free) < n:
                raise PagesExhaustedError(
                    f"page pool exhausted: need {n} pages, "
                    f"{len(self._free)}/{self.capacity} free "
                    f"(refcounted prefix pages may be pinned by "
                    f"in-flight requests)")
            out = [self._free.popleft() for _ in range(n)]
            for p in out:
                self._refs[p] = 1
        return out

    def retain(self, pages: Sequence[int]):
        with self._lock:
            for p in pages:
                if p == TRASH_PAGE:
                    continue
                if self._refs[p] <= 0:
                    raise ValueError(f"retain of unallocated page {p}")
                self._refs[p] += 1

    def release(self, pages: Sequence[int]):
        """Drop one reference per page; pages hitting zero return to the
        free list (content is NOT erased — stale bytes are only ever
        reachable through a page table, and freed pages leave every
        table)."""
        with self._lock:
            for p in pages:
                if p == TRASH_PAGE:
                    continue
                if self._refs[p] <= 0:
                    raise ValueError(f"release of unallocated page {p}")
                self._refs[p] -= 1
                if self._refs[p] == 0:
                    self._free.append(p)

    def refcount(self, page: int) -> int:
        with self._lock:
            return self._refs[page]

    def reset(self):
        """Forget every allocation (tick-failure containment: the pool
        array was reallocated, so all page content is gone)."""
        with self._lock:
            self._refs = [0] * self.n_pages
            self._refs[TRASH_PAGE] = -1
            self._free = deque(range(1, self.n_pages))

    def state(self) -> Dict[str, int]:
        with self._lock:
            free = len(self._free)
            shared = sum(1 for r in self._refs[1:] if r >= 2)
        return {
            "capacity": self.capacity,
            "free": free,
            "used": self.capacity - free,
            "shared": shared,
            "page_bytes": self.page_bytes,
        }


class _RadixNode:
    __slots__ = ("children", "page", "stamp")

    def __init__(self, page: int, stamp: int):
        self.children: Dict[Tuple[int, ...], "_RadixNode"] = {}
        self.page = page
        self.stamp = stamp


class RadixCache:
    """Radix tree over full ``page_size``-token prompt chunks → page ids.

    Granularity is one PAGE per edge: only prompts sharing an entire
    page-aligned chunk share its KV (a divergence inside a page keeps
    that page private — the engine copy-on-writes only when a request's
    next WRITE would land in a shared page, i.e. the whole-prompt-match
    case). The tree holds one reference per resident page so prefixes
    outlive the request that prefilled them; ``evict`` releases
    least-recently-used leaves whose only reference is the tree's.
    """

    def __init__(self, pool: PagePool, page_size: int):
        self.pool = pool
        self.page_size = int(page_size)
        # the tree mutates on the engine thread (match/insert/evict under
        # the tick) but peek() prices admissions from SERVER threads and
        # the hit gauges feed /metrics — a bare dict walk racing a del
        # mid-evict reads torn state. Ordering: RadixCache._lock is taken
        # BEFORE PagePool._lock (retain/release inside), never after.
        self._lock = threading.RLock()
        # guarded-by: self._lock
        self._root: Dict[Tuple[int, ...], _RadixNode] = {}
        self._clock = 0      # guarded-by: self._lock
        self.hits = 0        # guarded-by: self._lock
        self.queries = 0     # guarded-by: self._lock
        self.hit_tokens = 0  # guarded-by: self._lock

    def _chunks(self, tokens) -> List[Tuple[int, ...]]:
        ps = self.page_size
        n_full = len(tokens) // ps
        return [tuple(int(t) for t in tokens[i * ps:(i + 1) * ps])
                for i in range(n_full)]

    # -- lookup ---------------------------------------------------------
    def match(self, tokens) -> List[int]:
        """Longest resident full-page prefix of ``tokens``; the returned
        pages are RETAINED for the caller (release when the request
        terminates)."""
        with self._lock:
            self._clock += 1
            self.queries += 1
            pages: List[int] = []
            level = self._root
            for chunk in self._chunks(tokens):
                node = level.get(chunk)
                if node is None:
                    break
                node.stamp = self._clock
                pages.append(node.page)
                level = node.children
            if pages:
                self.pool.retain(pages)
                self.hits += 1
                self.hit_tokens += len(pages) * self.page_size
            return pages

    def peek(self, tokens) -> int:
        """Number of full pages a :meth:`match` would return, without
        retaining (admission-gate watermark prediction)."""
        with self._lock:
            n = 0
            level = self._root
            for chunk in self._chunks(tokens):
                node = level.get(chunk)
                if node is None:
                    break
                n += 1
                level = node.children
            return n

    # -- registration ---------------------------------------------------
    def insert(self, tokens, pages: Sequence[int]):
        """Register a prefilled prompt's FULL pages (``pages[i]`` holds
        chunk i's KV). Existing nodes keep their original page (the new
        request's private copy stays private); new nodes retain one tree
        reference on their page."""
        with self._lock:
            self._clock += 1
            level = self._root
            for chunk, page in zip(self._chunks(tokens), pages):
                node = level.get(chunk)
                if node is None:
                    node = _RadixNode(int(page), self._clock)
                    self.pool.retain([int(page)])
                    level[chunk] = node
                else:
                    node.stamp = self._clock
                level = node.children

    # -- eviction -------------------------------------------------------
    # hostrace: requires(self._lock)
    def _leaves(self):
        out = []

        def walk(level):
            for key, node in level.items():
                if node.children:
                    walk(node.children)
                if not node.children:
                    out.append((level, key, node))

        walk(self._root)
        return out

    def evict(self, n: int) -> int:
        """Release up to ``n`` least-recently-used leaf pages whose ONLY
        reference is the tree's (pages pinned by in-flight requests are
        never evicted). Cascades: a parent whose children were all
        evicted becomes a leaf candidate in the next round."""
        freed = 0
        with self._lock:
            while freed < n:
                candidates = [(level, key, node)
                              for level, key, node in self._leaves()
                              if self.pool.refcount(node.page) == 1]
                if not candidates:
                    break
                candidates.sort(key=lambda c: c[2].stamp)
                for level, key, node in candidates:
                    if freed >= n:
                        break
                    self.pool.release([node.page])
                    del level[key]
                    freed += 1
        return freed

    def resident_pages(self) -> int:
        n = 0

        def walk(level):
            nonlocal n
            for node in level.values():
                n += 1
                walk(node.children)

        with self._lock:
            walk(self._root)
        return n

    def clear(self):
        """Drop every tree reference (engine reset after pool loss)."""

        def walk(level):
            for node in level.values():
                walk(node.children)
                self.pool.release([node.page])

        with self._lock:
            walk(self._root)
            self._root = {}

    def hit_rate(self) -> Optional[float]:
        with self._lock:
            if not self.queries:
                return None
            return self.hits / self.queries
