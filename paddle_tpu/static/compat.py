"""Static-graph compatibility surface completing paddle.static parity:
BuildStrategy/ExecutionStrategy/CompiledProgram/ParallelExecutor shims,
scope/name/device guards, Print/py_func, program-state save/load and
serialization, EMA, and static metric wrappers.

Parity: python/paddle/static/__init__.py of the reference over
fluid/compiler.py (CompiledProgram, BuildStrategy pybind.cc:2692),
fluid/executor.py scope machinery, fluid/io.py (save/load:1847,1955,
load_program_state:2151, save_vars:286), fluid/optimizer.py EMA:3927.

TPU-native: the strategy objects record the toggles the reference feeds to
its SSA-graph builder — XLA owns fusion/placement, so they are accepted,
stored and surfaced for inspection; CompiledProgram/ParallelExecutor thinly
delegate to the whole-program-jit Executor.
"""
from __future__ import annotations

import contextlib
import os
import pickle
from typing import Optional

import numpy as np

from ..tensor import Tensor
from .executor import Executor, global_scope
from .program import Program, Variable, default_main_program

__all__ = [
    "BuildStrategy", "ExecutionStrategy", "CompiledProgram",
    "ParallelExecutor", "Scope", "scope_guard", "name_scope", "device_guard",
    "Print", "py_func", "accuracy", "auc", "create_parameter",
    "create_global_var", "save", "load", "save_vars", "load_vars",
    "load_program_state", "set_program_state", "serialize_program",
    "deserialize_program", "serialize_persistables",
    "deserialize_persistables", "save_to_file", "load_from_file",
    "normalize_program", "ExponentialMovingAverage", "WeightNormParamAttr",
    "npu_places",
]


class BuildStrategy:
    """Graph-build toggles (pybind.cc:2692 parity). XLA performs the fusion
    and scheduling these flags used to steer; values are recorded so strategy
    code ports and can be introspected."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.fuse_elewise_add_act_ops = False
        self.fuse_bn_act_ops = False
        self.fuse_all_reduce_ops = False
        self.fuse_all_optimizer_ops = False
        self.enable_inplace = True
        self.memory_optimize = None
        self.sync_batch_norm = False
        self.num_trainers = 1
        self.trainer_id = 0

    def __repr__(self):
        flags = {k: v for k, v in self.__dict__.items()}
        return f"BuildStrategy({flags})"


class ExecutionStrategy:
    """Executor toggles (pybind.cc:2530 parity) — recorded only."""

    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 100
        self.num_iteration_per_run = 1
        self.use_thread_barrier = False


class CompiledProgram:
    """fluid.compiler.CompiledProgram parity: wraps a Program; under XLA the
    'compilation' already happens in Executor.run's whole-program jit, so
    this is a labeled pass-through that keeps strategy objects."""

    def __init__(self, program_or_graph, build_strategy: Optional[BuildStrategy] = None):
        self._program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()
        self._exec_strategy = None
        self._places = None

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        """Reference API: builds a multi-device SSA graph. Here DP comes from
        the mesh (paddle_tpu.distributed); the call records its config and
        returns self so legacy scripts run unchanged on one chip."""
        if build_strategy is not None:
            self._build_strategy = build_strategy
        self._exec_strategy = exec_strategy
        self._places = places
        return self

    # Executor.run unwraps CompiledProgram via this hook
    @property
    def program(self):
        return self._program


class ParallelExecutor:
    """Legacy ParallelExecutor (parallel_executor.cc:639 parity) as a shim
    over the whole-program-jit Executor."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None, build_strategy=None,
                 num_trainers=1, trainer_id=0, scope=None):
        self._exe = Executor()
        self._program = main_program or default_main_program()
        self._loss_name = loss_name

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        feed = feed if feed is not None else feed_dict
        return self._exe.run(self._program, feed=feed, fetch_list=fetch_list,
                             return_numpy=return_numpy)


class Scope:
    """Host-side scope (framework/scope.h:52 parity)."""

    def __init__(self):
        self._vars = {}

    def var(self, name):
        self._vars.setdefault(name, None)
        return self._vars[name]

    def find_var(self, name):
        return self._vars.get(name)


_scope_stack = []


@contextlib.contextmanager
def scope_guard(scope):
    """executor.scope_guard parity."""
    _scope_stack.append(scope)
    try:
        yield
    finally:
        _scope_stack.pop()


@contextlib.contextmanager
def name_scope(prefix):
    """fluid.name_scope parity: prefixes recorded op names for debugging.
    Tracing labels live in the profiler; this guard is a lightweight tag."""
    from ..profiler import RecordEvent

    with RecordEvent(f"name_scope/{prefix}"):
        yield


@contextlib.contextmanager
def device_guard(device=None):
    """fluid.device_guard parity: the reference pins ops to a device for
    pipeline splitting; mesh shardings own placement here, so the guard
    records the hint for PipelineLayer-style segmenters."""
    from . import program as _prog

    prev = getattr(_prog, "_current_device_hint", None)
    _prog._current_device_hint = device
    try:
        yield
    finally:
        _prog._current_device_hint = prev


def Print(input, first_n=-1, message=None, summarize=20, print_tensor_name=True,  # noqa: A002,N802
          print_tensor_type=True, print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """print op parity: eager host print; identity pass-through."""
    arr = input._data if isinstance(input, Tensor) else input
    head = message or ""
    if print_tensor_name and getattr(input, "name", None):
        head += f" {input.name}"
    try:
        vals = np.asarray(arr).reshape(-1)[:summarize]
        print(f"{head} shape={getattr(arr, 'shape', None)} values={vals}")
    except Exception:
        print(f"{head} <symbolic {arr}>")
    return input


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """py_func op parity: run a host python function eagerly on tensors."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    res = func(*[np.asarray(t._data) if isinstance(t, Tensor) else t for t in xs])
    outs = out if isinstance(out, (list, tuple)) else [out]
    ress = res if isinstance(res, (list, tuple)) else [res]
    import jax.numpy as jnp

    for o, r in zip(outs, ress):
        o._set_data(jnp.asarray(np.asarray(r)))
    return out


def accuracy(input, label, k=1, correct=None, total=None):  # noqa: A002
    from ..metric import accuracy as _acc

    return _acc(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1, slide_steps=1):  # noqa: A002
    """Static AUC wrapper over the streaming Auc metric (single batch)."""
    from ..metric import Auc

    m = Auc(num_thresholds=min(num_thresholds, 4095))
    m.update(np.asarray(input._data), np.asarray(label._data))
    import jax.numpy as jnp

    return Tensor(jnp.asarray(np.float32(m.accumulate())))


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..ops.creation import create_parameter as _cp

    t = _cp(shape, dtype=dtype, default_initializer=default_initializer)
    if name:
        t.name = name
    return t


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False,
                      name=None):
    import jax.numpy as jnp

    from ..dtype import to_jax_dtype

    t = Tensor(jnp.full(tuple(shape), value, to_jax_dtype(dtype)))
    t.persistable = persistable
    if name:
        t.name = name
    return t


# ---------------------------------------------------------------------------
# program state save/load (fluid/io.py parity)
# ---------------------------------------------------------------------------

def _program_state(program: Program) -> dict:
    return {
        (v.name or f"param_{i}"): np.asarray(t._data)
        for i, (t, v) in enumerate(program.captures())
    }


def save(program: Program, model_path: str, protocol: int = 4):
    """paddle.static.save parity: params -> .pdparams, (optimizer state is
    owned by the attached optimizer) -> .pdopt, program -> .pdmodel."""
    import jax

    os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(_program_state(program), f, protocol=protocol)
    # static optimizer slots live functionally on the program (_opt_state
    # pytree fed through the jitted step), not in eager accumulators
    opt_state = getattr(program, "_opt_state", None)
    blob = (jax.tree_util.tree_map(np.asarray, opt_state)
            if opt_state is not None else None)
    with open(model_path + ".pdopt", "wb") as f:
        pickle.dump(blob, f, protocol=protocol)
    with open(model_path + ".pdmodel", "wb") as f:
        pickle.dump({"feeds": [v.name for v in getattr(program, "_feeds", [])]}, f)


def load(program: Program, model_path: str, executor=None, var_list=None):
    """paddle.static.load parity: restore parameter values by name, plus the
    attached optimizer's accumulators/step from the .pdopt file."""
    state = load_program_state(model_path)
    set_program_state(program, state)
    opt_path = model_path + ".pdopt"
    if os.path.exists(opt_path):
        with open(opt_path, "rb") as f:
            opt_state = pickle.load(f)
        if opt_state is not None:
            import jax
            import jax.numpy as jnp

            program._opt_state = jax.tree_util.tree_map(jnp.asarray, opt_state)


def load_program_state(model_path: str, var_list=None) -> dict:
    path = model_path + ".pdparams" if not model_path.endswith(".pdparams") else model_path
    with open(path, "rb") as f:
        return pickle.load(f)


def set_program_state(program: Program, state_dict: dict):
    for i, (t, v) in enumerate(program.captures()):
        key = v.name or f"param_{i}"
        if key in state_dict:
            import jax.numpy as jnp

            t._set_data(jnp.asarray(state_dict[key]))


def save_vars(executor, dirname, main_program=None, vars=None, predicate=None,  # noqa: A002
              filename=None):
    prog = main_program or default_main_program()
    state = _program_state(prog)
    os.makedirs(dirname, exist_ok=True)
    if filename:
        with open(os.path.join(dirname, filename), "wb") as f:
            pickle.dump(state, f)
    else:
        for k, v in state.items():
            with open(os.path.join(dirname, k.replace("/", "_")), "wb") as f:
                pickle.dump(v, f)


def load_vars(executor, dirname, main_program=None, vars=None, predicate=None,  # noqa: A002
              filename=None):
    prog = main_program or default_main_program()
    if filename:
        with open(os.path.join(dirname, filename), "rb") as f:
            set_program_state(prog, pickle.load(f))
        return
    state = {}
    for i, (t, v) in enumerate(prog.captures()):
        key = v.name or f"param_{i}"
        p = os.path.join(dirname, key.replace("/", "_"))
        if os.path.exists(p):
            with open(p, "rb") as f:
                state[key] = pickle.load(f)
    set_program_state(prog, state)


def serialize_program(feed_vars, fetch_vars, **kwargs) -> bytes:
    prog = feed_vars[0]._program if feed_vars else default_main_program()
    return pickle.dumps({
        "feeds": [v.name for v in feed_vars],
        "fetches": [v.name for v in fetch_vars],
        "n_captures": len(prog.captures()),
    })


def deserialize_program(data: bytes):
    return pickle.loads(data)


def serialize_persistables(feed_vars, fetch_vars, executor=None, **kwargs) -> bytes:
    prog = feed_vars[0]._program if feed_vars else default_main_program()
    return pickle.dumps(_program_state(prog))


def deserialize_persistables(program, data: bytes, executor=None):
    set_program_state(program, pickle.loads(data))


def save_to_file(path: str, content: bytes):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def normalize_program(program, feed_vars, fetch_vars):
    """Reference: prunes/normalizes for inference export. The recorded
    Program is already minimal (pure closures); returns it unchanged."""
    return program


class ExponentialMovingAverage:
    """EMA of parameter values (fluid/optimizer.py EMA:3927 parity):
    ``update()`` after each step, ``apply()`` context swaps EMA values in,
    ``restore()`` swaps back."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._ema = {}
        self._backup = {}
        self._step = 0
        self._params = []

    def update(self, parameters=None):
        params = parameters if parameters is not None else [
            t for (t, v) in default_main_program().captures() if v.trainable]
        self._step += 1
        for i, p in enumerate(params):
            arr = np.asarray(p._data)
            key = getattr(p, "name", None) or f"p{i}"
            if key not in self._ema:
                self._ema[key] = arr.copy()
            else:
                d = self._decay
                self._ema[key] = d * self._ema[key] + (1 - d) * arr
        self._params = list(params)

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        import jax.numpy as jnp

        for i, p in enumerate(self._params):
            key = getattr(p, "name", None) or f"p{i}"
            self._backup[key] = np.asarray(p._data)
            p._set_data(jnp.asarray(self._ema[key]))
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        import jax.numpy as jnp

        for i, p in enumerate(self._params):
            key = getattr(p, "name", None) or f"p{i}"
            if key in self._backup:
                p._set_data(jnp.asarray(self._backup[key]))
        self._backup = {}


class WeightNormParamAttr:
    """ParamAttr requesting weight normalization (parity:
    paddle.static.WeightNormParamAttr). Consumed by layers that call
    nn.utils.weight_norm on their weight."""

    def __init__(self, dim=None, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=False,
                 need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable


def npu_places(device_ids=None):
    """NPU is out of scope on this build; mirrors the accelerator list
    (reference static.npu_places)."""
    from . import cuda_places

    return cuda_places(device_ids)
