"""Static-graph Executor: whole-program jit replay.

Parity: ``paddle.static.Executor`` (reference python/paddle/fluid/executor.py
:1065 Executor.run → C++ framework/executor.cc:170 per-op interpretation, and
the new_executor/InterpreterCore async interpreter).

TPU-native redesign: instead of interpreting ops one by one, ``run`` compiles
the WHOLE program — forward replay, ``jax.grad`` backward, optimizer update,
state writes — into a single XLA executable, cached per (program version,
feed shapes, fetch set). Op-dispatch overhead (the reference's hot-loop cost,
operator.cc:1081) is zero; scheduling/fusion belong to XLA, which replaces the
SSA-graph executors and the BuildStrategy pass pipeline wholesale.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor
from .program import OpRecord, Program, Variable, default_main_program

__all__ = ["Executor", "global_scope"]


class _Scope:
    """Host-side name->value view over a program's captured state (parity:
    the C++ global Scope; here state lives on the source Tensors)."""

    def find_var(self, name: str):
        return None

    def var(self, name: str):
        return None


_global_scope = _Scope()


def global_scope():
    return _global_scope


def _replay(program: Program, env: Dict[str, Any]):
    """Execute the recorded op list over concrete (traced) arrays."""
    for op in program.ops:
        flat2 = []
        for x in op.flat_args:
            if isinstance(x, Variable):
                flat2.append(env[x.name])
            else:
                flat2.append(x)
        a2, k2 = jax.tree_util.tree_unflatten(op.treedef, flat2)
        out = op.fn(*a2, **k2)
        out_flat = jax.tree_util.tree_flatten(out)[0]
        for v, a in zip(op.out_vars, out_flat):
            env[v.name] = a
    return env


class Executor:
    """paddle.static.Executor parity; ``place`` is accepted and ignored
    (PJRT owns placement)."""

    def __init__(self, place=None):
        self.place = place

    # -- public API -----------------------------------------------------
    def run(
        self,
        program: Optional[Program] = None,
        feed: Optional[Dict[str, Any]] = None,
        fetch_list: Optional[Sequence] = None,
        return_numpy: bool = True,
    ):
        program = program if program is not None else default_main_program()
        # CompiledProgram (compat.py) wraps the recorded Program
        if hasattr(program, "program") and not isinstance(program, Program):
            program = program.program
        feed = feed or {}
        fetch_list = list(fetch_list or [])

        # deserialized inference programs execute their StableHLO directly
        if hasattr(program, "_exported"):
            return program.run(feed)

        # startup programs / empty programs: nothing to do
        if not program.ops and not fetch_list:
            return []

        fetch_vars = [self._resolve_fetch(program, f) for f in fetch_list]

        feed_names = sorted(n for n in program.feed_vars if n != "__rng_key__")
        missing = [n for n in feed_names if n not in feed]
        if missing:
            raise ValueError(f"missing feed entries: {missing}")
        feed_arrays = [jnp.asarray(self._feed_value(feed[n])) for n in feed_names]

        captures = program.captures()
        capture_arrays = [t._data for (t, _) in captures]

        key = (
            len(program.ops),
            tuple(feed_names),
            tuple((tuple(a.shape), str(a.dtype)) for a in feed_arrays),
            tuple(v.name for v in fetch_vars),
            program.optimizer is not None,
            bool(program.grad_sources),
        )
        compiled = program._exec_cache.get(key)
        if compiled is None:
            compiled = self._compile(program, feed_names, fetch_vars, captures)
            program._exec_cache[key] = compiled

        rng_args = ()
        if program.rng_used:
            from ..random import split_key

            rng_args = (split_key(),)

        if program.optimizer is not None:
            if program._opt_state is None:
                param_arrays = [p._data for p in program.opt_params]
                program._opt_state = program.optimizer.init_state(param_arrays)
            lr = jnp.asarray(program.optimizer.get_lr(), jnp.float32)
            fetches, new_params, new_state, new_writes = compiled(
                feed_arrays, capture_arrays, program._opt_state, lr, *rng_args
            )
            program._opt_state = new_state
            for p, a in zip(program.opt_params, new_params):
                p._set_data(a)
            program.optimizer._on_static_step()
        else:
            fetches, new_writes = compiled(feed_arrays, capture_arrays, *rng_args)

        for (target, _), arr in zip(program.state_writes.values(), new_writes):
            target._set_data(arr)

        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [Tensor(f) for f in fetches]

    # -- internals ------------------------------------------------------
    @staticmethod
    def _feed_value(v):
        if isinstance(v, Tensor):
            return v._data
        return np.asarray(v)

    @staticmethod
    def _resolve_fetch(program: Program, f):
        if isinstance(f, Variable):
            return f
        if isinstance(f, str):
            v = program.vars.get(f)
            if v is None:
                raise KeyError(f"fetch target '{f}' not found in program")
            return v
        raise TypeError(f"bad fetch target: {f!r}")

    def _compile(self, program: Program, feed_names, fetch_vars, captures):
        capture_names = [v.name for (_, v) in captures]
        write_items = list(program.state_writes.values())
        grad_requested = bool(program.grad_sources) or program.optimizer is not None

        # differentiation sources: captures (parameters) and/or feed vars
        cap_index_by_id = {id(t): i for i, (t, _) in enumerate(captures)}
        feed_index = {n: i for i, n in enumerate(feed_names)}
        diff_entries = []  # (kind, index, name) with kind in {"cap", "feed"}
        sources = []
        if grad_requested:
            # grad_sources is the merged set (append_backward/gradients +
            # optimizer params); differentiate all of it so every registered
            # @GRAD fetch resolves, then update only the optimizer's params
            sources = program.grad_sources or program.opt_params
            for s in sources:
                if isinstance(s, Variable) and s._role == "feed":
                    diff_entries.append(("feed", feed_index[s.name], s.name))
                elif id(s) in cap_index_by_id:
                    i = cap_index_by_id[id(s)]
                    diff_entries.append(("cap", i, capture_names[i]))
                else:
                    raise ValueError(
                        f"cannot differentiate w.r.t. {getattr(s, 'name', s)!r}: "
                        "not a program input (parameter capture or feed)"
                    )

        def forward_env(feed_arrays, capture_arrays, rng_key=None):
            env = {}
            for n, a in zip(feed_names, feed_arrays):
                env[n] = a
            for n, a in zip(capture_names, capture_arrays):
                env[n] = a
            if rng_key is not None:
                env["__rng_key__"] = rng_key
            return _replay(program, env)

        def harvest(env, grads_by_capture_name=None):
            fetches = []
            for v in fetch_vars:
                if grads_by_capture_name is not None and v.name.endswith("@GRAD"):
                    src = v.name[: -len("@GRAD")]
                    if src in grads_by_capture_name:
                        fetches.append(grads_by_capture_name[src])
                        continue
                if v.name not in env:
                    raise KeyError(
                        f"fetch '{v.name}' was never produced (is it a @GRAD "
                        "var without append_backward/minimize?)"
                    )
                fetches.append(env[v.name])
            writes = [env[wv.name] for (_, wv) in write_items]
            return fetches, writes

        if not grad_requested:

            def step_fwd(feed_arrays, capture_arrays, *rng):
                env = forward_env(feed_arrays, capture_arrays, *rng)
                return harvest(env)

            return jax.jit(step_fwd)

        loss_var = program.loss_var
        if loss_var is None:
            raise RuntimeError("gradients requested but no loss was set")

        opt = program.optimizer
        pos_by_id = {id(s): j for j, s in enumerate(sources)}
        opt_positions = [pos_by_id[id(p)] for p in program.opt_params] if opt else []

        def step_train(feed_arrays, capture_arrays, opt_state, lr, *rng):
            def loss_fn(diff_arrays):
                cap = list(capture_arrays)
                fd = list(feed_arrays)
                for (kind, i, _), a in zip(diff_entries, diff_arrays):
                    (cap if kind == "cap" else fd)[i] = a
                env = forward_env(fd, cap, *rng)
                loss = env[loss_var.name]
                return loss.astype(jnp.float32).sum(), env

            diff_arrays = [
                (capture_arrays if kind == "cap" else feed_arrays)[i]
                for (kind, i, _) in diff_entries
            ]
            grads, env = jax.grad(loss_fn, has_aux=True)(diff_arrays)
            grads_by_name = {
                name: g for (_, _, name), g in zip(diff_entries, grads)
            }
            fetches, writes = harvest(env, grads_by_name)
            if opt is None:
                return fetches, diff_arrays, opt_state, writes
            opt_arrays = [diff_arrays[j] for j in opt_positions]
            opt_grads = [grads[j] for j in opt_positions]
            new_params, new_state = opt.apply_gradients(
                opt_arrays, opt_grads, opt_state, lr=lr
            )
            return fetches, new_params, new_state, writes

        if program.optimizer is not None:
            return jax.jit(step_train)

        # grads requested (append_backward) but no optimizer: reuse the train
        # path with a dummy opt state and identity update
        def step_grads(feed_arrays, capture_arrays, *rng):
            fetches, _, _, writes = step_train(
                feed_arrays, capture_arrays, None, jnp.float32(0), *rng
            )
            return fetches, writes

        return jax.jit(step_grads)
