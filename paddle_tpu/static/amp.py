"""Static-graph mixed precision.

Parity: the reference's static AMP (fluid/contrib/mixed_precision/
decorator.py:37 OptimizerWithMixedPrecision — O1 ``rewrite_program`` inserts
casts by white/black lists, O2 ``cast_model_to_fp16``:188; fp16_lists.py).

TPU-native: cast insertion happens at record time — building the program
inside ``amp.auto_cast`` (or after ``enable_operators``) bakes bf16 casts
into the recorded closures; there is no separate rewrite pass. Loss scaling
is generally unnecessary in bf16 (same exponent range as fp32 — the
reference's fp16-driven scaling state machine is kept only for the fp16
path via ``decorate(..., init_loss_scaling)``).
"""
from __future__ import annotations

import contextlib

from ..amp.auto_cast import amp_state, auto_cast

__all__ = ["decorate", "amp_guard", "CustomOpLists"]


def CustomOpLists(custom_white_list=None, custom_black_list=None):
    """Parity: AutoMixedPrecisionLists (fp16_lists.py)."""
    return {"white": set(custom_white_list or ()),
            "black": set(custom_black_list or ())}


@contextlib.contextmanager
def amp_guard(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    """Build-time autocast context for static programs."""
    with auto_cast(enable, custom_white_list, custom_black_list, level, dtype):
        yield


class OptimizerWithMixedPrecision:
    """Wraps an optimizer for static AMP training (decorator.py:37 parity).

    ``minimize`` enables autocast while the *caller-supplied builder* records
    — but since in this framework the forward is usually already recorded by
    the time minimize is called, the recommended flow is::

        with paddle.static.amp.amp_guard(level="O2"):
            out = net(x); loss = ...
        opt = paddle.static.amp.decorate(paddle.optimizer.AdamW(...))
        opt.minimize(loss)

    Loss scaling: bf16 needs none (scale fixed at 1); an explicit
    ``init_loss_scaling`` multiplies the loss and un-scales grads inside the
    compiled step via the optimizer's grad hook.
    """

    def __init__(self, optimizer, amp_lists=None, init_loss_scaling=1.0,
                 use_dynamic_loss_scaling=False, level="O1", dtype="bfloat16"):
        import warnings

        self._inner = optimizer
        self._loss_scale = float(init_loss_scaling)
        self.level = level
        self.dtype = dtype
        self._amp_lists = amp_lists
        self._wrapped = False
        if amp_lists:
            warnings.warn(
                "static.amp.decorate: pass custom white/black lists to "
                "amp_guard(custom_white_list=..., custom_black_list=...) — "
                "casting happens at record time, not in minimize",
                stacklevel=3,
            )
        if use_dynamic_loss_scaling:
            warnings.warn(
                "static.amp.decorate: dynamic loss scaling is not implemented "
                "for the static path (bf16 needs none); using the fixed "
                f"init_loss_scaling={init_loss_scaling}",
                stacklevel=3,
            )

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        if self._loss_scale != 1.0:
            import jax

            loss = loss * self._loss_scale
            if not self._wrapped:  # idempotent: never stack unscaling twice
                scale = self._loss_scale
                inner_apply = self._inner.apply_gradients

                def unscaling_apply(params, grads, state, lr=None):
                    grads = jax.tree_util.tree_map(lambda g: g / scale, grads)
                    return inner_apply(params, grads, state, lr=lr)

                # instance-bound: the static Executor routes updates through
                # apply_gradients inside the compiled step
                self._inner.apply_gradients = unscaling_apply
                self._wrapped = True
        return self._inner.minimize(loss, startup_program=startup_program,
                                    parameters=parameters, no_grad_set=no_grad_set)

    def get_loss_scaling(self):
        return self._loss_scale

    def __getattr__(self, name):
        return getattr(self._inner, name)


def decorate(optimizer, amp_lists=None, init_loss_scaling=1.0,
             use_dynamic_loss_scaling=False, level="O1", dtype="bfloat16",
             **kw):
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists, init_loss_scaling, use_dynamic_loss_scaling,
        level, dtype,
    )
