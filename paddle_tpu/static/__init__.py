"""paddle_tpu.static — the static-graph ("fluid") paradigm.

Parity: paddle.static (reference python/paddle/static/__init__.py;
Program/Executor machinery python/paddle/fluid/framework.py + executor.py:1065;
append_backward python/paddle/fluid/backward.py:1406;
save/load_inference_model python/paddle/fluid/io.py:1246).

TPU-native redesign notes live in program.py / executor.py: Program = recorded
trace of pure-jax closures; Executor = whole-program jit with jax.grad
backward; save_inference_model = StableHLO export.
"""
from __future__ import annotations

import json
from typing import List, Optional, Sequence

import jax
import jax.export  # noqa: F401  (0.4.x: lazy submodule, not an attribute)
import jax.numpy as jnp
import numpy as np

from ..jit.input_spec import InputSpec  # noqa: F401  (paddle.static.InputSpec)
from ..tensor import Tensor
from .executor import Executor, global_scope  # noqa: F401
from .program import (  # noqa: F401
    Program,
    Variable,
    data,
    default_main_program,
    default_startup_program,
    dygraph_guard,
    program_guard,
)

__all__ = [
    "InputSpec",
    "Program",
    "Variable",
    "Executor",
    "global_scope",
    "data",
    "default_main_program",
    "default_startup_program",
    "program_guard",
    "append_backward",
    "gradients",
    "save_inference_model",
    "load_inference_model",
    "cpu_places",
    "cuda_places",
    "xpu_places",
    "nn",
]


def append_backward(loss: Variable, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Register gradient computation for ``loss`` (parity: fluid/backward.py
    append_backward:1406). Returns ``[(param_var, grad_var)]``.

    TPU-native: no grad ops are built — the Executor derives the backward with
    ``jax.grad`` over the whole recorded program at compile time; this call
    just marks sources and names the ``@GRAD`` fetch targets.
    """
    prog = loss._program
    prog.loss_var = loss

    if parameter_list:
        sources = []
        for p in parameter_list:
            if isinstance(p, str):
                v = prog.vars.get(p)
                if v is None:
                    raise KeyError(f"unknown parameter '{p}'")
                # find the concrete tensor backing this capture
                src = next(
                    (t for (t, cv) in prog.captures() if cv is v), v
                )
                sources.append(src)
            else:
                sources.append(p)
    else:
        sources = [t for (t, _) in prog.captures() if t.trainable]

    # merge with any previously registered sources (same rule as minimize)
    merged = list(prog.grad_sources)
    seen = {id(s) for s in merged}
    for s in sources:
        if id(s) not in seen:
            merged.append(s)
            seen.add(id(s))
    prog.grad_sources = merged
    prog._exec_cache.clear()

    pairs = []
    for s in sources:
        v = s if isinstance(s, Variable) else prog.capture(s)
        pairs.append((v, prog.grad_var_for(v)))
    return pairs


def gradients(targets, inputs, target_gradients=None, no_grad_set=None) -> List[Variable]:
    """paddle.static.gradients parity: grads of ``targets`` w.r.t. ``inputs``
    (parameters or feed Variables)."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if len(targets) != 1:
        # multiple targets sum their gradients; model by summing losses
        raise NotImplementedError("gradients() supports a single target in v1")
    pairs = append_backward(targets[0], parameter_list=list(inputs))
    return [g for (_, g) in pairs]


# ---------------------------------------------------------------------------
# inference export (parity: fluid/io.py save_inference_model:1246)
# ---------------------------------------------------------------------------
MODEL_SUFFIX = ".pdmodel"
PARAMS_SUFFIX = ".pdiparams"
META_SUFFIX = ".pdmeta"


def save_inference_model(path_prefix: str, feed_vars: Sequence[Variable],
                         fetch_vars: Sequence[Variable], executor: Executor,
                         program: Optional[Program] = None, **kwargs):
    """Serialize the feed→fetch slice of ``program`` as StableHLO + params."""
    from .executor import _replay

    feed_vars = list(feed_vars)
    fetch_vars = list(fetch_vars)
    program = program if program is not None else feed_vars[0]._program

    # prune to the feed->fetch slice (parity: fluid io.py prunes the program
    # before export — the loss/optimizer branch and its label feeds drop out)
    needed = {v.name for v in fetch_vars}
    pruned_ops = []
    for op in reversed(program.ops):
        if any(v.name in needed for v in op.out_vars):
            pruned_ops.append(op)
            for x in op.flat_args:
                if isinstance(x, Variable):
                    needed.add(x.name)
    pruned_ops.reverse()

    captures = program.captures()
    capture_names = [v.name for (_, v) in captures]
    capture_arrays = [t._data for (t, _) in captures]
    feed_names = [v.name for v in feed_vars]
    rng_used = program.rng_used

    class _PrunedView:
        ops = pruned_ops

    def infer_fn(capture_arrays, rng_key, *feed_arrays):
        env = dict(zip(capture_names, capture_arrays))
        env.update(zip(feed_names, feed_arrays))
        env["__rng_key__"] = rng_key
        env = _replay(_PrunedView, env)
        return [env[v.name] for v in fetch_vars]

    # symbolic dims exactly where the user declared None/-1 in static.data;
    # all symbols must share one scope, so mint them in a single call
    declared_shapes = [
        getattr(v, "_declared_shape", None) or list(v._data.shape)
        for v in feed_vars
    ]
    n_sym = sum(1 for d in declared_shapes for s in d if s is None)
    syms = iter(
        jax.export.symbolic_shape(",".join(f"b{i}" for i in range(n_sym)))
        if n_sym else ()
    )
    specs = []
    for v, declared in zip(feed_vars, declared_shapes):
        shape = tuple(next(syms) if s is None else int(s) for s in declared)
        specs.append(jax.ShapeDtypeStruct(shape, v._data.dtype))
    cap_specs = [jax.ShapeDtypeStruct(tuple(a.shape), a.dtype) for a in capture_arrays]
    key = jax.random.PRNGKey(0)  # raw uint32 key: typed key dtypes don't serialize through 0.4.x jax.export
    key_spec = jax.ShapeDtypeStruct(key.shape, key.dtype)

    exported = jax.export.export(jax.jit(infer_fn))(cap_specs, key_spec, *specs)
    with open(path_prefix + MODEL_SUFFIX, "wb") as f:
        f.write(exported.serialize())
    with open(path_prefix + PARAMS_SUFFIX, "wb") as f:
        np.savez(f, **{f"c{i}": np.asarray(a) for i, a in enumerate(capture_arrays)})
    with open(path_prefix + META_SUFFIX, "w") as f:
        json.dump({"feed_names": feed_names,
                   "fetch_names": [v.name for v in fetch_vars],
                   "n_captures": len(capture_arrays)}, f)


class LoadedProgram:
    """Deserialized inference program; Executor.run dispatches to it."""

    def __init__(self, exported, capture_arrays, feed_names, fetch_names):
        self._exported = exported
        self._captures = capture_arrays
        self.feed_names = feed_names
        self.fetch_names = fetch_names

    def run(self, feed: dict):
        feeds = [jnp.asarray(feed[n]._data if isinstance(feed[n], Tensor) else feed[n])
                 for n in self.feed_names]
        outs = self._exported.call(self._captures, jax.random.PRNGKey(0), *feeds)
        return [np.asarray(o) for o in outs]


def load_inference_model(path_prefix: str, executor: Executor, **kwargs):
    """Returns ``[program, feed_target_names, fetch_targets]`` (reference
    contract); run via ``program.run(feed_dict)`` or ``exe.run(program, ...)``."""
    with open(path_prefix + MODEL_SUFFIX, "rb") as f:
        exported = jax.export.deserialize(f.read())
    with open(path_prefix + META_SUFFIX) as f:
        meta = json.load(f)
    arrs = np.load(path_prefix + PARAMS_SUFFIX)
    captures = [jnp.asarray(arrs[f"c{i}"]) for i in range(meta["n_captures"])]
    prog = LoadedProgram(exported, captures, meta["feed_names"], meta["fetch_names"])
    return [prog, meta["feed_names"], meta["fetch_names"]]


# place helpers (parity: paddle.static.cpu_places/cuda_places; TPU chips here)
def cpu_places(device_count=None):
    from .. import device as device_mod

    n = device_count or 1
    return [device_mod.CPUPlace(i) for i in range(n)]


def cuda_places(device_ids=None):
    from .. import device as device_mod

    ids = device_ids if device_ids is not None else [0]
    return [device_mod.TPUPlace(i) for i in ids]


xpu_places = cuda_places


# static nn helpers (parity: paddle.static.nn.fc/batch_norm/conv2d/embedding)
from . import nn  # noqa: E402,F401
# static mixed precision (parity: fluid/contrib/mixed_precision)
from . import amp  # noqa: E402,F401


# compatibility surface (BuildStrategy/CompiledProgram/scope guards/EMA/
# program-state io) — see compat.py
from .compat import *  # noqa: E402,F401,F403
from .compat import __all__ as _compat_all  # noqa: E402

__all__ = list(__all__) + list(_compat_all)
