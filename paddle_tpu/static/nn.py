"""paddle.static.nn parity — thin wrappers building nn layers inside a
recorded Program (reference python/paddle/static/nn/__init__.py → fluid
layers fc/conv2d/batch_norm/embedding).

Each helper instantiates the matching ``paddle_tpu.nn`` Layer (parameters are
created eagerly under ``dygraph_guard`` — the startup-program role) and calls
it, which records into the current main program.
"""
from __future__ import annotations

from .program import dygraph_guard

__all__ = ["fc", "conv2d", "batch_norm", "embedding", "cond", "while_loop"]


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    from .. import nn
    from ..ops import manipulation

    with dygraph_guard():
        in_dim = 1
        for s in x.shape[num_flatten_dims:]:
            in_dim *= int(s)
        layer = nn.Linear(in_dim, size, weight_attr=weight_attr, bias_attr=bias_attr)
    if len(x.shape) > num_flatten_dims + 1:
        # dim 0 may be symbolic (meta value 1): let reshape infer it with -1
        target = [-1] + list(x.shape[1:num_flatten_dims]) + [in_dim]
        x = manipulation.reshape(x, target)
    out = layer(x)
    if activation:
        from ..nn import functional as F

        out = getattr(F, activation)(out)
    return out


def conv2d(x, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None, name=None,
           data_format="NCHW"):
    from .. import nn

    with dygraph_guard():
        layer = nn.Conv2D(int(x.shape[1]), num_filters, filter_size,
                          stride=stride, padding=padding, dilation=dilation,
                          groups=groups, weight_attr=param_attr,
                          bias_attr=bias_attr, data_format=data_format)
    out = layer(x)
    if act:
        from ..nn import functional as F

        out = getattr(F, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, param_attr=None,
               bias_attr=None, data_layout="NCHW", is_test=False, name=None,
               **kwargs):
    from .. import nn

    with dygraph_guard():
        layer = nn.BatchNorm2D(int(input.shape[1]), momentum=momentum,
                               epsilon=epsilon, data_format=data_layout)
        if is_test:
            layer.eval()
    out = layer(input)
    if act:
        from ..nn import functional as F

        out = getattr(F, act)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None, param_attr=None,
              dtype="float32", name=None):
    from .. import nn

    with dygraph_guard():
        layer = nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                             weight_attr=param_attr)
    return layer(input)


def cond(pred, true_fn=None, false_fn=None):
    raise NotImplementedError(
        "static control flow is not supported in v1; use @to_static over "
        "python control flow (jax.lax.cond under jit) instead"
    )


while_loop = cond
