"""paddle.static.nn parity — thin wrappers building nn layers inside a
recorded Program (reference python/paddle/static/nn/__init__.py → fluid
layers fc/conv2d/batch_norm/embedding).

Each helper instantiates the matching ``paddle_tpu.nn`` Layer (parameters are
created eagerly under ``dygraph_guard`` — the startup-program role) and calls
it, which records into the current main program.
"""
from __future__ import annotations

from .program import dygraph_guard

__all__ = ["fc", "conv2d", "batch_norm", "embedding", "cond", "while_loop"]


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    from .. import nn
    from ..ops import manipulation

    with dygraph_guard():
        in_dim = 1
        for s in x.shape[num_flatten_dims:]:
            in_dim *= int(s)
        layer = nn.Linear(in_dim, size, weight_attr=weight_attr, bias_attr=bias_attr)
    if len(x.shape) > num_flatten_dims + 1:
        # dim 0 may be symbolic (meta value 1): let reshape infer it with -1
        target = [-1] + list(x.shape[1:num_flatten_dims]) + [in_dim]
        x = manipulation.reshape(x, target)
    out = layer(x)
    if activation:
        from ..nn import functional as F

        out = getattr(F, activation)(out)
    return out


def conv2d(x, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None, name=None,
           data_format="NCHW"):
    from .. import nn

    with dygraph_guard():
        layer = nn.Conv2D(int(x.shape[1]), num_filters, filter_size,
                          stride=stride, padding=padding, dilation=dilation,
                          groups=groups, weight_attr=param_attr,
                          bias_attr=bias_attr, data_format=data_format)
    out = layer(x)
    if act:
        from ..nn import functional as F

        out = getattr(F, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, param_attr=None,
               bias_attr=None, data_layout="NCHW", is_test=False, name=None,
               **kwargs):
    from .. import nn

    with dygraph_guard():
        layer = nn.BatchNorm2D(int(input.shape[1]), momentum=momentum,
                               epsilon=epsilon, data_format=data_layout)
        if is_test:
            layer.eval()
    out = layer(input)
    if act:
        from ..nn import functional as F

        out = getattr(F, act)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None, param_attr=None,
              dtype="float32", name=None):
    from .. import nn

    with dygraph_guard():
        layer = nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                             weight_attr=param_attr)
    return layer(input)


def _trace_into_sub(outer, fn, args=(), placeholder_avals=None,
                    ph_prefix="__loop_var"):
    """Trace ``fn`` into a fresh sub-Program (reference: sub-block
    construction in conditional_block_op.cc / while_op.cc).

    ``placeholder_avals``: when given, fresh placeholder Variables with those
    avals are created and passed as ``fn(*placeholders)`` (the while-loop
    carry); otherwise ``fn(*args)`` runs directly.

    Returns ``(sub, out_vars, out_tree, free, ph_names)`` where ``free`` maps
    sub-scope variable names to the OUTER Variables the body closes over.
    The sub-program continues the outer name sequence so a branch-local op
    output can never shadow an enclosing-scope variable with the same
    auto-generated name.
    """
    import jax

    from ..tensor import Tensor
    from .program import Program, Variable, program_guard

    sub = Program()
    sub.vars.update(outer.vars)  # allow references to enclosing-scope vars
    sub._name_counter = outer._name_counter

    phs, ph_names = [], []
    if placeholder_avals is not None:
        for i, aval in enumerate(placeholder_avals):
            name = f"{ph_prefix}_{i}__"
            ph = Variable(aval, name, sub, role="feed")
            sub._register(ph)
            phs.append(ph)
            ph_names.append(name)
        call_args = phs
    else:
        call_args = args

    with program_guard(sub, Program()):
        outs = fn(*call_args)
    # later outer names must not collide with branch-internal ones either
    outer._name_counter = max(outer._name_counter, sub._name_counter)

    flat_outs, out_tree = jax.tree_util.tree_flatten(
        outs, is_leaf=lambda x: isinstance(x, Tensor))
    out_vars = []
    for leaf in flat_outs:
        if isinstance(leaf, Variable):
            out_vars.append(leaf)
        elif isinstance(leaf, Tensor):
            out_vars.append(sub.capture(leaf))
        else:
            raise TypeError(f"control-flow fn returned a non-tensor leaf: {leaf!r}")

    produced = {v.name for op in sub.ops for v in op.out_vars}
    skip = set(ph_names)
    free = {}

    def note(v):
        if v.name in produced or v.name in skip or v.name in free:
            return
        src = next((t for (t, cv) in sub._captures.values() if cv is v), None)
        free[v.name] = outer.capture(src) if src is not None else v

    for op in sub.ops:
        for x in op.flat_args:
            if isinstance(x, Variable):
                note(x)
    for v in out_vars:
        note(v)
    return sub, out_vars, out_tree, free, ph_names


def _branch_runner(sub, out_vars, names, ph_names=()):
    """Pure function replaying the sub-program over bound arrays."""
    from .executor import _replay

    def run(closure_arrs, carry=()):
        env = dict(zip(names, closure_arrs))
        env.update(zip(ph_names, carry))
        _replay(sub, env)
        return tuple(env[v.name] for v in out_vars)

    return run


def cond(pred, true_fn=None, false_fn=None, name=None):
    """paddle.static.nn.cond parity (reference conditional_block_op.cc +
    fluid/layers/control_flow.py cond): both branches are traced into
    sub-programs and lowered to ``lax.cond`` inside the Program jit.

    Branches must be side-effect free (no dropout/BN-stat writes inside a
    branch) and return matching structures — the XLA requirement that both
    arms produce identical shapes/dtypes."""
    import jax
    import jax.numpy as jnp

    from ..tensor import Tensor
    from .program import default_main_program, record_op, recording_active

    if true_fn is None or false_fn is None:
        raise ValueError("cond requires both true_fn and false_fn")
    if not recording_active():
        # dygraph: plain python dispatch (reference cond eager path)
        import numpy as _np

        p = pred._data if isinstance(pred, Tensor) else pred
        return true_fn() if bool(_np.asarray(p).reshape(())) else false_fn()

    outer = default_main_program()
    t_sub, t_outs, t_tree, t_free, _ = _trace_into_sub(outer, true_fn)
    f_sub, f_outs, f_tree, f_free, _ = _trace_into_sub(outer, false_fn)

    # operand union: lax.cond passes the same operands to both arms
    free = dict(t_free)
    for n, v in f_free.items():
        free.setdefault(n, v)
    names = list(free)
    inputs = [free[n] for n in names]
    t_run = _branch_runner(t_sub, t_outs, names)
    f_run = _branch_runner(f_sub, f_outs, names)

    def fn(pred_arr, *arrs):
        b = pred_arr.reshape(()).astype(jnp.bool_)
        return jax.lax.cond(b, t_run, f_run, arrs)

    outs = record_op(fn, "cond", (pred, *inputs), {})
    flat = jax.tree_util.tree_flatten(outs, is_leaf=lambda x: isinstance(x, Tensor))[0]
    return jax.tree_util.tree_unflatten(t_tree, flat)


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """paddle.static.nn.while_loop parity (reference while_op.cc +
    layers/control_flow.py while_loop): condition and body are traced into
    sub-programs and lowered to ``lax.while_loop`` inside the Program jit.
    ``loop_vars`` shapes/dtypes must be loop-invariant (XLA's while
    contract — matching the reference's requirement that the block's
    outputs mirror its inputs)."""
    import jax
    import jax.numpy as jnp
    import numpy as _np

    from ..tensor import Tensor
    from .program import default_main_program, record_op, recording_active

    if not recording_active():
        vars_ = list(loop_vars)
        while True:
            p = cond_fn(*vars_)
            if not bool(_np.asarray(p._data if isinstance(p, Tensor) else p).reshape(())):
                break
            out = body_fn(*vars_)
            vars_ = list(out) if isinstance(out, (list, tuple)) else [out]
        return vars_

    outer = default_main_program()
    n_loop = len(loop_vars)
    avals = []
    for lv in loop_vars:
        d = lv._data
        avals.append(d if isinstance(d, jax.ShapeDtypeStruct)
                     else jax.ShapeDtypeStruct(tuple(d.shape), d.dtype))

    c_sub, c_outs, _, c_free, ph_names = _trace_into_sub(
        outer, cond_fn, placeholder_avals=avals)
    b_sub, b_outs, _, b_free, _ = _trace_into_sub(
        outer, body_fn, placeholder_avals=avals)
    if len(b_outs) != n_loop:
        raise ValueError(
            f"body_fn returned {len(b_outs)} vars, expected {n_loop}")

    free = dict(c_free)
    for n, v in b_free.items():
        free.setdefault(n, v)
    names = list(free)
    inputs = [free[n] for n in names]
    c_run = _branch_runner(c_sub, c_outs, names, ph_names)
    b_run = _branch_runner(b_sub, b_outs, names, ph_names)

    def fn(*args):
        init = args[:n_loop]
        closure = args[n_loop:]

        def cond_f(carry):
            (out,) = c_run(closure, carry)
            return out.reshape(()).astype(jnp.bool_)

        def body_f(carry):
            return b_run(closure, carry)

        return jax.lax.while_loop(cond_f, body_f, tuple(init))

    outs = record_op(fn, "while", (*loop_vars, *inputs), {})
    return list(outs) if isinstance(outs, (list, tuple)) else [outs]
