"""Static-graph Program: the second execution paradigm.

Parity: the reference's ``fluid`` static graph — Python builds a ``ProgramDesc``
IR (/root/reference/paddle/fluid/framework/framework.proto:50-80; Program/
Block/Variable/Operator in python/paddle/fluid/framework.py) which a C++
``Executor`` interprets op-by-op (framework/executor.cc:170).

TPU-native redesign: a Program is a *recorded trace*, not a protobuf IR. Ops
are captured as pure-jax closures at build time (the same ``primitive``
functions the eager path runs); the Executor replays the whole list inside ONE
``jax.jit`` so XLA sees — and fuses — the entire step, including the backward
pass (derived with ``jax.grad`` over the replay, replacing the reference's
symbolic ``append_backward`` op-by-op grad construction,
python/paddle/fluid/backward.py:1406) and the optimizer update. This is
strictly more aggressive than the reference's per-op interpreter with fusion
passes: the "pass pipeline" is XLA itself.
"""
from __future__ import annotations

import contextlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..dtype import to_jax_dtype
from ..tensor import Tensor

__all__ = [
    "Variable",
    "Program",
    "program_guard",
    "default_main_program",
    "default_startup_program",
    "data",
    "record_op",
    "record_rng_op",
    "recording_active",
    "dygraph_guard",
]


class Variable(Tensor):
    """A symbolic tensor inside a Program (parity: fluid.framework.Variable).

    ``_data`` holds a ``jax.ShapeDtypeStruct`` — metadata only; values exist
    only during Executor replay.
    """

    __slots__ = ("_program", "_role", "_declared_shape")

    def __init__(self, aval, name: str, program: "Program", role: str = "op_out",
                 stop_gradient: bool = True):
        # bypass Tensor.__init__: _data is an aval, not an array
        self._data = aval
        self.stop_gradient = stop_gradient
        self.grad = None
        self._node = None
        self._out_idx = 0
        self._retain_grad = False
        self.name = name
        self.persistable = role in ("param",)
        self.trainable = not stop_gradient
        self._hooks = None
        self._program = program
        self._role = role
        self._declared_shape = None  # user shape incl. None dims (feeds only)

    @property
    def place(self):
        from .. import device as device_mod

        return device_mod.CPUPlace(0)

    def numpy(self):
        raise RuntimeError(
            f"Variable '{self.name}' has no value at graph-build time; fetch it "
            "through Executor.run(fetch_list=[...])"
        )

    def __repr__(self):
        return (
            f"Variable(name={self.name}, shape={list(self._data.shape)}, "
            f"dtype={self._data.dtype}, role={self._role})"
        )


class OpRecord:
    """One recorded op: a pure-jax closure plus its (symbolic) arg structure."""

    __slots__ = ("fn", "name", "flat_args", "treedef", "out_tree", "out_vars",
                 "rng", "tags")

    def __init__(self, fn, name, flat_args, treedef, out_tree, out_vars, rng=False):
        self.fn = fn
        self.name = name
        self.flat_args = flat_args      # leaves: Variable | literal
        self.treedef = treedef
        self.out_tree = out_tree
        self.out_vars = out_vars        # flat list of Variables
        self.rng = rng                  # if True, fn takes a leading PRNG key
        self.tags = None                # op-kind markers for clone(for_test)

    def copy(self) -> "OpRecord":
        rec = OpRecord(self.fn, self.name, list(self.flat_args), self.treedef,
                       self.out_tree, list(self.out_vars), self.rng)
        rec.tags = dict(self.tags) if self.tags else None
        return rec


class Program:
    """Recorded op list + captured state (parity: fluid.Program).

    Captures (concrete Tensors touched by recorded ops — parameters, buffers)
    play the role of the reference's persistable variables in the global Scope.
    """

    _counter = 0

    def __init__(self):
        Program._counter += 1
        self.idx = Program._counter
        self.ops: List[OpRecord] = []
        self.vars: Dict[str, Variable] = {}
        self.feed_vars: Dict[str, Variable] = {}
        self._name_counter = 0
        # id(source Tensor) -> (source Tensor, capture Variable)
        self._captures: Dict[int, Tuple[Tensor, Variable]] = {}
        # recorded writes to captured state (BN stats etc.):
        # id(target) -> (target Tensor, value Variable)
        self.state_writes: Dict[int, Tuple[Tensor, Variable]] = {}
        # grads: capture Variable name -> grad Variable (append_backward)
        self.grad_map: Dict[str, Variable] = {}
        self.grad_sources: List[Tensor] = []   # param Tensors to differentiate
        self.loss_var: Optional[Variable] = None
        # optimizer attachment (minimize): (optimizer, loss_var, [param Tensor])
        self.optimizer = None
        self.opt_params: List[Tensor] = []
        self._opt_state = None
        self.rng_used = False
        self._exec_cache: Dict[Any, Any] = {}

    # -- naming ---------------------------------------------------------
    def _unique_name(self, prefix: str) -> str:
        self._name_counter += 1
        return f"{prefix}_{self._name_counter}"

    def _register(self, var: Variable):
        self.vars[var.name] = var
        return var

    # -- capture --------------------------------------------------------
    def capture(self, t: Tensor) -> Variable:
        """Map a concrete Tensor (parameter/buffer/constant) to a stable
        capture Variable; executor feeds its live value every run."""
        hit = self._captures.get(id(t))
        if hit is not None:
            return hit[1]
        name = t.name or self._unique_name("capture")
        v = Variable(
            jax.ShapeDtypeStruct(tuple(t._data.shape), t._data.dtype),
            name, self, role="param", stop_gradient=t.stop_gradient,
        )
        self._captures[id(t)] = (t, v)
        self._register(v)
        return v

    def captures(self) -> List[Tuple[Tensor, Variable]]:
        return list(self._captures.values())

    # -- mutation hooks -------------------------------------------------
    def record_state_write(self, target: Tensor, value: Variable):
        self.capture(target)  # ensure the old value is an input
        self.state_writes[id(target)] = (target, value)
        self._exec_cache.clear()

    def grad_var_for(self, v: Variable) -> Variable:
        """The ``<name>@GRAD`` Variable for a differentiation source."""
        g = self.grad_map.get(v.name)
        if g is None:
            g = Variable(v._data, f"{v.name}@GRAD", self, role="grad")
            self.grad_map[v.name] = g
            self._register(g)
        return g

    def _set_optimizer(self, optimizer, loss: Variable, params: Sequence[Tensor]):
        self.optimizer = optimizer
        self.loss_var = loss
        # accept capture Variables (e.g. program.all_parameters()) by mapping
        # them back to their concrete source Tensors
        resolved = []
        for p in params:
            if isinstance(p, Variable):
                src = next((t for (t, cv) in self._captures.values() if cv is p), None)
                if src is None:
                    raise ValueError(
                        f"Variable {p.name!r} is not a parameter capture of this program"
                    )
                p = src
            resolved.append(p)
        self.opt_params = [p for p in resolved if not p.stop_gradient]
        self._exec_cache.clear()
        pairs = []
        for p in self.opt_params:
            pairs.append((self.capture(p), self.grad_var_for(self.capture(p))))
        # merge (not overwrite) earlier append_backward/gradients() sources so
        # their @GRAD fetches keep working during optimized training
        merged = list(self.grad_sources)
        seen = {id(s) for s in merged}
        for p in self.opt_params:
            if id(p) not in seen:
                merged.append(p)
        self.grad_sources = merged
        return None, pairs

    def global_block(self):
        return self

    def all_parameters(self):
        return [v for (_, v) in self._captures.values() if v.trainable]

    def list_vars(self):
        return list(self.vars.values())

    def clone(self, for_test: bool = False):
        """Copy the program (parity: Program.clone, fluid/framework.py).

        ``for_test=True`` additionally switches recorded dropout ops to
        identity and batch-norm ops to inference stats, and drops state
        writes / backward / optimizer — the reference's clone-for-test op
        attr rewrite."""
        p = Program()
        p.ops = [rec.copy() for rec in self.ops]
        p.vars = dict(self.vars)
        p.feed_vars = dict(self.feed_vars)
        p._name_counter = self._name_counter
        p._captures = dict(self._captures)
        p.state_writes = dict(self.state_writes)
        p.grad_map = dict(self.grad_map)
        p.grad_sources = list(self.grad_sources)
        p.loss_var = self.loss_var
        p.optimizer = self.optimizer
        p.opt_params = list(self.opt_params)
        p.rng_used = self.rng_used
        if for_test:
            p.optimizer = None
            p.opt_params = []
            p._opt_state = None
            p.loss_var = None
            p.grad_map = {}
            p.grad_sources = []
            p.state_writes = {}
            for rec in p.ops:
                tags = rec.tags or {}
                if "dropout" in tags:
                    if tags.get("mode") == "downscale_in_infer":
                        scale = 1.0 - tags.get("p", 0.0)
                        rec.fn = (lambda s: lambda key, arr: arr * s)(scale)
                    else:  # upscale_in_train: inference is identity
                        rec.fn = lambda key, arr: arr
                elif "bn" in tags:
                    # the only bare-bool literal in a bn record is `training`
                    rec.flat_args = [
                        (False if a is True else a) for a in rec.flat_args
                    ]
        return p


# ---------------------------------------------------------------------------
# current-program stack
# ---------------------------------------------------------------------------
_default_main: Optional[Program] = None
_default_startup: Optional[Program] = None
_program_stack: List[Tuple[Program, Program]] = []
_record_suspended = 0


def default_main_program() -> Program:
    global _default_main
    if _program_stack:
        return _program_stack[-1][0]
    if _default_main is None:
        _default_main = Program()
    return _default_main


def default_startup_program() -> Program:
    global _default_startup
    if _program_stack:
        return _program_stack[-1][1]
    if _default_startup is None:
        _default_startup = Program()
    return _default_startup


def _reset_default_programs():
    global _default_main, _default_startup
    _default_main = None
    _default_startup = None


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    sp = startup_program if startup_program is not None else Program()
    _program_stack.append((main_program, sp))
    try:
        yield
    finally:
        _program_stack.pop()


@contextlib.contextmanager
def dygraph_guard():
    """Suspend recording (initializers, host-side computation) even when
    static mode is enabled."""
    global _record_suspended
    _record_suspended += 1
    try:
        yield
    finally:
        _record_suspended -= 1


def recording_active() -> bool:
    if _record_suspended:
        return False
    import paddle_tpu as _pd

    return bool(getattr(_pd, "_static_mode", False))


# ---------------------------------------------------------------------------
# feed declaration
# ---------------------------------------------------------------------------
def data(name: str, shape: Sequence[Optional[int]], dtype: str = "float32",
         lod_level: int = 0) -> Variable:
    """Declare a feed Variable (parity: paddle.static.data). ``None``/-1 dims
    are symbolic (commonly the batch dim); replay re-traces per actual shape."""
    prog = default_main_program()
    jdt = to_jax_dtype(dtype)
    # metadata shape: unknown dims recorded as 1 (only used for eval_shape)
    meta_shape = tuple(1 if (s is None or s < 0) else int(s) for s in shape)
    v = Variable(jax.ShapeDtypeStruct(meta_shape, jdt), name, prog, role="feed")
    v._declared_shape = [None if (s is None or s < 0) else int(s) for s in shape]
    prog.feed_vars[name] = v
    prog._register(v)
    return v


# ---------------------------------------------------------------------------
# op recording (called from ops/_primitive.py when static mode is on)
# ---------------------------------------------------------------------------
def _is_tensor(x):
    return isinstance(x, Tensor)


def record_op(fn: Callable, op_name: str, args, kwargs):
    """Append an op to the current program; return symbolic outputs mirroring
    the eager wrapper's return structure."""
    prog = default_main_program()
    flat, treedef = jax.tree_util.tree_flatten((args, kwargs), is_leaf=_is_tensor)

    in_avals = []
    rec_flat = []
    for x in flat:
        if isinstance(x, Variable):
            if x._program is not prog and x.name not in prog.vars:
                # cross-program reference: capture by value is impossible for
                # symbolic vars — reject loudly (clones share var names, so
                # recording into a clone stays legal)
                raise RuntimeError(
                    f"Variable {x.name} belongs to a different Program"
                )
            rec_flat.append(x)
            in_avals.append(x._data)
        elif isinstance(x, Tensor):
            v = prog.capture(x)
            rec_flat.append(v)
            in_avals.append(v._data)
        else:
            rec_flat.append(x)

    var_pos = [i for i, x in enumerate(rec_flat) if isinstance(x, Variable)]

    def pure(*arrs):
        flat2 = list(rec_flat)
        for i, a in zip(var_pos, arrs):
            flat2[i] = a
        a2, k2 = jax.tree_util.tree_unflatten(treedef, flat2)
        return fn(*a2, **k2)

    out_shape = jax.eval_shape(pure, *in_avals)
    out_flat, out_tree = jax.tree_util.tree_flatten(out_shape)
    out_vars = [
        Variable(a, prog._unique_name(op_name), prog, role="op_out",
                 stop_gradient=False)
        for a in out_flat
    ]
    for v in out_vars:
        prog._register(v)
    prog.ops.append(OpRecord(fn, op_name, rec_flat, treedef, out_tree, out_vars))
    prog._exec_cache.clear()
    out = jax.tree_util.tree_unflatten(out_tree, out_vars)
    return out


def record_rng_op(fn_with_key: Callable, op_name: str, args=(), kwargs=None):
    """Record an op needing randomness. ``fn_with_key(key, *args, **kwargs)``
    gets a per-op, per-run PRNG key (the Executor feeds a fresh root key each
    run; parity with the reference's per-run dropout seeds)."""
    kwargs = kwargs or {}
    prog = default_main_program()
    prog.rng_used = True
    op_index = len(prog.ops)

    def fn(key, *a, **k):
        return fn_with_key(jax.random.fold_in(key, op_index), *a, **k)

    key_var = _rng_var(prog)
    return record_op(fn, op_name, (key_var,) + tuple(args), kwargs)


def _rng_var(prog: Program) -> Variable:
    v = prog.feed_vars.get("__rng_key__")
    if v is None:
        v = Variable(
            jax.ShapeDtypeStruct((), jax.random.key(0).dtype),
            "__rng_key__", prog, role="feed",
        )
        prog.feed_vars["__rng_key__"] = v
        prog._register(v)
    return v


def handle_state_write(target: Tensor, value) -> bool:
    """Called from Tensor.set_value/_set_data: if ``value`` is symbolic,
    record a state write instead of assigning. Returns True when handled."""
    if isinstance(value, Variable):
        value._program.record_state_write(target, value)
        return True
    return False
