"""Version info (parity: paddle/version.py generated at build time)."""
full_version = "0.1.0"
major = "0"
minor = "1"
patch = "0"
rc = "0"
istaged = False
commit = "tpu-native"
with_mkl = "OFF"
cuda_version = "False"  # TPU build
cudnn_version = "False"


def show():
    print(f"full_version: {full_version}")
    print(f"commit: {commit}")


def cuda():
    return cuda_version


def cudnn():
    return cudnn_version
