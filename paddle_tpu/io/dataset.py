"""Dataset types. Parity: python/paddle/io/dataloader/dataset.py."""
from __future__ import annotations

from typing import Iterable, List, Sequence

import numpy as np

__all__ = [
    "Dataset",
    "IterableDataset",
    "TensorDataset",
    "ComposeDataset",
    "ChainDataset",
    "ConcatDataset",
    "Subset",
    "random_split",
]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence):
        arrays = [np.asarray(t.numpy() if hasattr(t, "numpy") else t) for t in tensors]
        n = arrays[0].shape[0]
        if any(a.shape[0] != n for a in arrays):
            raise ValueError("all tensors must share dim 0")
        self.tensors = arrays

    def __getitem__(self, idx):
        return tuple(a[idx] for a in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets: Sequence[Dataset]):
        self.datasets = list(datasets)
        n = len(self.datasets[0])
        if any(len(d) != n for d in self.datasets):
            raise ValueError("all datasets must share length")

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (list, tuple)) else [item])
        return tuple(out)

    def __len__(self):
        return len(self.datasets[0])


class ChainDataset(IterableDataset):
    def __init__(self, datasets: Sequence[IterableDataset]):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets: Sequence[Dataset]):
        self.datasets = list(datasets)
        self.cumulative = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = int(np.searchsorted(self.cumulative, idx, side="right"))
        prev = 0 if ds_idx == 0 else self.cumulative[ds_idx - 1]
        return self.datasets[ds_idx][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset: Dataset, indices: Iterable[int]):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset: Dataset, lengths: Sequence[int], generator=None) -> List[Subset]:
    total = sum(lengths)
    if total != len(dataset):
        raise ValueError("sum of lengths must equal dataset length")
    from ..random import split_key
    import jax

    perm = np.asarray(jax.random.permutation(split_key(), total))
    out, off = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[off : off + n].tolist()))
        off += n
    return out
