"""paddle_tpu.io — Dataset / DataLoader.

Parity: python/paddle/io/ in the reference (Dataset, IterableDataset,
TensorDataset, Sampler family, BatchSampler, DataLoader with num_workers,
fluid/reader.py + C++ reader/buffered_reader.cc double-buffering).

TPU-native: worker processes produce numpy batches over a multiprocessing
queue; a background prefetch thread overlaps host→device transfer with
compute (the buffered_reader role). Device placement happens at iteration so
batches land on TPU ahead of the step that consumes them.
"""
from .dataset import (  # noqa: F401
    ChainDataset,
    ComposeDataset,
    ConcatDataset,
    Dataset,
    IterableDataset,
    Subset,
    TensorDataset,
    random_split,
)
from .sampler import (  # noqa: F401
    BatchSampler,
    DistributedBatchSampler,
    RandomSampler,
    Sampler,
    SequenceSampler,
    SubsetRandomSampler,
    WeightedRandomSampler,
)
from .dataloader import DataLoader, get_worker_info  # noqa: F401
from .dataset_channel import (  # noqa: F401
    FileListDataset,
    InMemoryDataset,
    ShuffleChannel,
)

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "ConcatDataset", "Subset", "random_split",
    "Sampler", "SequenceSampler", "RandomSampler", "SubsetRandomSampler",
    "WeightedRandomSampler", "BatchSampler", "DistributedBatchSampler",
    "DataLoader", "get_worker_info",
    "FileListDataset", "ShuffleChannel", "InMemoryDataset",
]
