"""DataLoader.

Parity: the reference's python/paddle/fluid/reader.py DataLoader +
fluid/dataloader/dataloader_iter.py (multiprocess workers over queues,
worker_init_fn, collate) + C++ reader/buffered_reader.cc (double-buffered
prefetch-to-device).

TPU-native: a feeder thread keeps a small queue of collated numpy batches;
``device_prefetch`` device_puts the next batch while the current step runs so
HBM transfer overlaps compute. A C++ pinned-pool/queue backend
(paddle_tpu/lib) accelerates this path when built; the Python path is the
portable fallback.
"""
from __future__ import annotations

import itertools
import multiprocessing as mp
import os
import pickle
import queue as queue_mod
import threading
from typing import Callable, Optional

import numpy as np

from ..tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler

__all__ = ["DataLoader", "get_worker_info"]

_worker_info = threading.local()
_ring_counter = itertools.count()


class WorkerInfo:
    def __init__(self, id_, num_workers, dataset, seed):  # noqa: A002
        self.id = id_
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed


def get_worker_info():
    return getattr(_worker_info, "info", None)


def default_collate_fn(batch):
    """Stack samples (parity: fluid/dataloader/collate.py)."""
    sample = batch[0]
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn([b[i] for b in batch]) for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(b.numpy()) for b in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.integer, np.floating)):
        return np.asarray(batch)
    if isinstance(sample, str):
        return list(batch)
    return np.asarray(batch)


def _worker_loop(dataset, index_queue, out_queue, collate_fn, wid, num_workers, seed,
                 ring_name=None):
    np.random.seed(seed + wid)
    _worker_info.info = WorkerInfo(wid, num_workers, dataset, seed + wid)
    ring = None
    if ring_name is not None:
        try:
            from ..core import ShmRing

            ring = ShmRing(ring_name, create=False)
        except Exception:
            ring = None
    while True:
        job = index_queue.get()
        if job is None:
            break
        batch_id, indices = job
        try:
            samples = [dataset[i] for i in indices]
            batch = collate_fn(samples)
            if ring is not None:
                payload = pickle.dumps((batch_id, batch), protocol=4)
                try:
                    ring.write(payload)
                    continue
                except ValueError:  # batch larger than one ring slot → pipe path
                    pass
            out_queue.put((batch_id, batch, None))
        except Exception as e:  # propagate worker errors
            out_queue.put((batch_id, None, e))
    if ring is not None:
        ring.destroy()  # attach side: munmap only, owner unlinks


def _iterable_worker_loop(dataset, out_queue, collate_fn, wid, num_workers,
                          seed, batch_size, drop_last, ring_name=None):
    """IterableDataset worker: the dataset's __iter__ consults
    get_worker_info() to pick its shard (e.g. FileListDataset's worker
    file stride — the data_feed.cc per-thread file pickup)."""
    np.random.seed(seed + wid)
    _worker_info.info = WorkerInfo(wid, num_workers, dataset, seed + wid)
    ring = None
    if ring_name is not None:
        try:
            from ..core import ShmRing

            ring = ShmRing(ring_name, create=False)
        except Exception:
            ring = None

    def emit(batch):
        # ring payloads are (bid, batch) 2-tuples (what _recv_batch decodes)
        if ring is not None:
            payload = pickle.dumps((wid, batch), protocol=4)
            try:
                ring.write(payload)
                return
            except ValueError:  # oversize → pipe path
                pass
        out_queue.put((wid, batch, None))

    sent = 0
    try:
        it = iter(dataset)
        while True:
            chunk = list(itertools.islice(it, batch_size))
            if not chunk:
                break
            if len(chunk) < batch_size and drop_last:
                break
            emit(collate_fn(chunk))
            sent += 1
    except Exception as e:  # propagate worker errors
        out_queue.put((wid, None, e))
    # EOF goes through the PIPE and carries the batch count: the parent
    # keeps draining (either channel) until every worker's count is met, so
    # ring-vs-pipe ordering races cannot drop trailing batches
    out_queue.put((-1, (wid, sent), None))
    if ring is not None:
        ring.destroy()


class DataLoader:
    def __init__(
        self,
        dataset: Dataset,
        feed_list=None,
        places=None,
        return_list: bool = True,
        batch_sampler: Optional[BatchSampler] = None,
        batch_size: int = 1,
        shuffle: bool = False,
        drop_last: bool = False,
        collate_fn: Optional[Callable] = None,
        num_workers: int = 0,
        use_buffer_reader: bool = True,
        prefetch_factor: int = 2,
        use_shared_memory: bool = True,
        timeout: float = 0,
        worker_init_fn: Optional[Callable] = None,
        device_prefetch: bool = True,
    ):
        self.dataset = dataset
        self.num_workers = max(0, int(num_workers))
        self.use_shared_memory = use_shared_memory
        self.collate_fn = collate_fn or default_collate_fn
        self.prefetch_factor = prefetch_factor
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.device_prefetch = device_prefetch and use_buffer_reader
        self.return_list = return_list
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last
            )

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    # ------------------------------------------------------------------
    def _batches_numpy(self):
        if self._iterable_mode:
            if self.num_workers > 0:
                yield from self._batches_multiprocess_iterable()
                return
            it = iter(self.dataset)
            while True:
                chunk = list(itertools.islice(it, self.batch_size))
                if not chunk:
                    return
                if len(chunk) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(chunk)
        elif self.num_workers == 0:
            for indices in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in indices])
        else:
            yield from self._batches_multiprocess()

    def _batches_multiprocess(self):
        ctx = mp.get_context("fork")
        index_queue = ctx.Queue()
        out_queue = ctx.Queue()
        seed = np.random.randint(0, 2**31 - 1)
        # shared-memory ring transport (native C++ core): workers write
        # pickled batches straight into a process-shared ring, skipping the
        # mp.Queue pipe + feeder thread (parity role: mmap_allocator.cc shm
        # path of the reference DataLoader). Oversized batches overflow to
        # the mp.Queue, so both channels are drained below.
        ring, ring_name = self._make_ring()
        workers = [
            ctx.Process(
                target=_worker_loop,
                args=(self.dataset, index_queue, out_queue, self.collate_fn, w,
                      self.num_workers, seed, ring_name),
                daemon=True,
            )
            for w in range(self.num_workers)
        ]
        for w in workers:
            w.start()
        try:
            batches = list(self.batch_sampler)
            inflight = 0
            next_submit = 0
            max_inflight = self.num_workers * self.prefetch_factor
            pending = {}
            next_yield = 0
            while next_yield < len(batches):
                while next_submit < len(batches) and inflight < max_inflight:
                    index_queue.put((next_submit, batches[next_submit]))
                    next_submit += 1
                    inflight += 1
                bid, data, err = self._recv_batch(ring, out_queue)
                inflight -= 1
                if err is not None:
                    raise err
                pending[bid] = data
                while next_yield in pending:
                    yield pending.pop(next_yield)
                    next_yield += 1
        finally:
            for _ in workers:
                index_queue.put(None)
            self._shutdown_workers(workers, ring)

    def _make_ring(self):
        """(ring, ring_name) for the shm transport, or (None, None)."""
        if not self.use_shared_memory:
            return None, None
        try:
            from ..core import ShmRing

            ring_name = f"/pt_dl_{os.getpid()}_{next(_ring_counter)}"
            ring = ShmRing(ring_name,
                           slot_size=self._shm_slot_size,
                           nslots=max(4, self.num_workers * self.prefetch_factor))
            return ring, ring_name
        except Exception:
            return None, None

    @staticmethod
    def _shutdown_workers(workers, ring):
        for w in workers:
            w.join(timeout=1)
            if w.is_alive():
                w.terminate()
        if ring is not None:
            ring.destroy()

    def _batches_multiprocess_iterable(self):
        """Parallel IterableDataset consumption (data_feed.cc per-thread
        channels): each worker iterates ITS shard (the dataset's __iter__
        reads get_worker_info) and streams batches; batches yield in
        arrival order until every worker EOFs."""
        ctx = mp.get_context("fork")
        out_queue = ctx.Queue()
        seed = np.random.randint(0, 2**31 - 1)
        ring, ring_name = self._make_ring()
        workers = [
            ctx.Process(
                target=_iterable_worker_loop,
                args=(self.dataset, out_queue, self.collate_fn, w,
                      self.num_workers, seed, self.batch_size, self.drop_last,
                      ring_name),
                daemon=True,
            )
            for w in range(self.num_workers)
        ]
        for w in workers:
            w.start()
        expected = {}   # wid -> batch count (from EOF sentinels)
        received = {w: 0 for w in range(self.num_workers)}
        try:
            while True:
                if (len(expected) == self.num_workers
                        and all(received[w] >= n for w, n in expected.items())):
                    break
                item = self._recv_batch_poll(ring, out_queue, workers,
                                             expected)
                bid, data, err = item
                if err is not None:
                    raise err
                if bid == -1:
                    wid, count = data
                    expected[wid] = count
                    continue
                received[bid] += 1
                yield data
        finally:
            self._shutdown_workers(workers, ring)

    def _recv_batch_poll(self, ring, out_queue, workers, expected):
        """_recv_batch with a liveness check: a worker that dies without
        its EOF sentinel (OOM-kill, segfaulting parser) must raise instead
        of hanging the feed loop forever. A dead worker gets one extra
        grace cycle so a sentinel still in the pipe's feeder buffer can
        drain before we declare it lost."""
        waited = 0.0
        suspects = set()
        while True:
            try:
                return out_queue.get(timeout=0.05 if ring is None else 0.001)
            except queue_mod.Empty:
                pass
            if ring is not None:
                payload = ring.read(timeout_ms=50)
                if payload is not None:
                    bid, data = pickle.loads(payload)
                    return bid, data, None
            waited += 0.05
            if waited >= 1.0 and waited % 1.0 < 0.05:
                for wid, w in enumerate(workers):
                    if w.is_alive() or wid in expected:
                        continue
                    if wid in suspects:
                        raise RuntimeError(
                            f"DataLoader worker {wid} (pid={w.pid}) died "
                            f"with exit code {w.exitcode} before finishing "
                            "its shard")
                    suspects.add(wid)
            if self.timeout and waited >= self.timeout:
                raise TimeoutError(
                    f"DataLoader worker timed out after {self.timeout}s")

    _shm_slot_size = 16 << 20

    def _recv_batch(self, ring, out_queue):
        """Next (batch_id, data, err) from the shm ring or the overflow
        pipe, whichever produces first."""
        if ring is None:
            return out_queue.get(timeout=self.timeout if self.timeout else None)
        waited = 0.0
        while True:
            # overflow/error pipe first: oversized batches and worker errors
            # must not pay the ring-read timeout on every iteration
            try:
                return out_queue.get_nowait()
            except queue_mod.Empty:
                pass
            payload = ring.read(timeout_ms=20)
            if payload is not None:
                bid, data = pickle.loads(payload)
                return bid, data, None
            waited += 0.02
            if self.timeout and waited >= self.timeout:
                raise TimeoutError(f"DataLoader worker timed out after {self.timeout}s")

    def __iter__(self):
        def to_tensors(batch):
            if isinstance(batch, (list, tuple)):
                return type(batch)(to_tensors(b) for b in batch)
            if isinstance(batch, dict):
                return {k: to_tensors(v) for k, v in batch.items()}
            if isinstance(batch, np.ndarray):
                return Tensor(batch)
            return batch

        if not self.device_prefetch:
            for b in self._batches_numpy():
                yield to_tensors(b)
            return

        # double-buffer: a feeder thread stages the next host batch and
        # begins its device transfer while the consumer computes
        q: queue_mod.Queue = queue_mod.Queue(maxsize=self.prefetch_factor)
        DONE, ERR = object(), object()

        def feeder():
            try:
                for b in self._batches_numpy():
                    q.put(to_tensors(b))
                q.put(DONE)
            except Exception as e:
                q.put((ERR, e))

        t = threading.Thread(target=feeder, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is DONE:
                return
            if isinstance(item, tuple) and len(item) == 2 and item[0] is ERR:
                raise item[1]
            yield item
