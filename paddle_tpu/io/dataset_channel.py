"""High-throughput dataset-channel feeding (pod-scale input).

Parity: the reference's C++ Dataset/DataFeed engine —
paddle/fluid/framework/data_set.cc (file list ownership, per-thread file
assignment, global/local shuffle through Channels) and data_feed.cc (the
largest framework file: file-sharded parsing into channel queues consumed
by trainer threads). SURVEY §2.1 DataFeed/Dataset row; VERDICT r3
missing #5.

TPU-native redesign: the channel machinery maps onto IterableDataset +
the existing multiprocess DataLoader (which already owns the shm-ring /
prefetch path):

- ``FileListDataset``   — owns a file list; shards FILES over
  (dist rank) x (dataloader worker) like data_set.cc hands files to
  DataFeed threads; a user ``parser(path) -> iter(samples)`` turns each
  file into a sample stream (MultiSlotDataFeed role).
- ``ShuffleChannel``    — bounded reservoir between producer and consumer:
  fill to capacity, then emit uniformly-random elements as new ones
  arrive ("local shuffle" channel semantics, data_set.cc
  LocalShuffle/Channel). Deterministic per (seed, epoch).
- ``InMemoryDataset``   — the reference's InMemoryDataset surface:
  load_into_memory() materializes parsed samples, local_shuffle() /
  global_shuffle() reorder them (global = one shared permutation every
  rank draws identically, then rank-strided — rank r sees slice r::world
  of ONE global order, ≙ the brpc shuffle-to-all exchange).
"""
from __future__ import annotations

import os
from typing import Callable, Iterable, List, Optional, Sequence

import numpy as np

from .dataset import IterableDataset

__all__ = ["FileListDataset", "ShuffleChannel", "InMemoryDataset"]


def _resolve_rank_world(rank: Optional[int], world_size: Optional[int]):
    """Default BOTH from the launcher env, or take BOTH explicitly —
    passing exactly one is a silent-wrong-shard hazard and raises."""
    if (rank is None) != (world_size is None):
        raise ValueError(
            "pass both rank and world_size, or neither (env defaults "
            "PADDLE_TRAINER_ID/PADDLE_TRAINERS_NUM apply only when both "
            "are omitted)")
    if rank is None:
        rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
        world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
    return int(rank), int(world_size)


def _worker_shard():
    """(start, step) for this dataloader worker (composes with dist rank
    sharding done by the caller)."""
    from .dataloader import get_worker_info

    info = get_worker_info()
    if info is None:
        return 0, 1
    return info.id, info.num_workers


class FileListDataset(IterableDataset):
    """File-sharded streaming dataset (data_set.cc SetFileList +
    per-thread file pickup).

    files: paths; parser(path) -> iterable of samples. Files are sharded
    rank-first (``rank``/``world_size`` — pass your dist rank, or they
    default from the launcher env) then worker-strided inside the
    DataLoader. ``set_epoch`` reshuffles the FILE ORDER deterministically
    (global file shuffle, data_set.cc's epoch reshuffle).

    CAUTION (lockstep SPMD): file-level sharding gives ranks UNEQUAL
    sample counts when files differ in size or don't divide evenly — fine
    for the reference's channel-draining PS trainers, but a lockstep dp
    step will deadlock in its collective when one rank runs out first.
    For lockstep training either make per-rank steps explicit
    (steps_per_epoch) or use InMemoryDataset.global_shuffle (even to
    within one sample)."""

    def __init__(self, files: Sequence[str], parser: Callable[[str], Iterable],
                 rank: Optional[int] = None, world_size: Optional[int] = None,
                 shuffle_files: bool = True, seed: int = 0):
        self.files = [str(f) for f in files]
        if not self.files:
            raise ValueError("FileListDataset needs at least one file")
        self.parser = parser
        rank, world_size = _resolve_rank_world(rank, world_size)
        if world_size > len(self.files):
            raise ValueError(
                f"world_size ({world_size}) exceeds the file count "
                f"({len(self.files)}): some ranks would get NO data and "
                "lockstep training would hang — split the input into at "
                "least one file per rank")
        self.rank = rank
        self.world_size = world_size
        self.shuffle_files = shuffle_files
        self.seed = seed
        self.epoch = 0

    def set_epoch(self, epoch: int):
        self.epoch = int(epoch)

    def _epoch_files(self) -> List[str]:
        order = list(range(len(self.files)))
        if self.shuffle_files:
            rng = np.random.RandomState(self.seed + self.epoch)
            rng.shuffle(order)
        mine = order[self.rank::self.world_size]
        return [self.files[i] for i in mine]

    def __iter__(self):
        files = self._epoch_files()
        w0, wn = _worker_shard()
        for path in files[w0::wn]:
            yield from self.parser(path)


class ShuffleChannel(IterableDataset):
    """Bounded shuffle buffer over any iterable dataset (the Channel +
    local-shuffle stage of data_feed.cc): keep up to ``capacity`` samples,
    emit one uniformly at random per pull. Streaming — never materializes
    the dataset."""

    def __init__(self, source: Iterable, capacity: int = 1024, seed: int = 0):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.source = source
        self.capacity = int(capacity)
        self.seed = seed
        self.epoch = 0

    def set_epoch(self, epoch: int):
        self.epoch = int(epoch)
        if hasattr(self.source, "set_epoch"):
            self.source.set_epoch(epoch)

    def __iter__(self):
        from .dataloader import get_worker_info

        info = get_worker_info()
        wid = info.id if info is not None else 0
        rng = np.random.RandomState(self.seed + 1000003 * self.epoch + wid)
        buf = []
        for sample in self.source:
            if len(buf) < self.capacity:
                buf.append(sample)
                continue
            j = rng.randint(0, self.capacity)
            out, buf[j] = buf[j], sample
            yield out
        rng.shuffle(buf)
        yield from buf


class InMemoryDataset(IterableDataset):
    """Materialized dataset with local/global shuffle (data_set.cc
    InMemoryDataset: LoadIntoMemory -> LocalShuffle/GlobalShuffle ->
    trainer consumption)."""

    def __init__(self, rank: Optional[int] = None,
                 world_size: Optional[int] = None, seed: int = 0):
        self.rank, self.world_size = _resolve_rank_world(rank, world_size)
        self.seed = seed
        self._files: List[str] = []
        self._parser: Optional[Callable] = None
        self._samples: List = []

    def set_filelist(self, files: Sequence[str]):
        self._files = [str(f) for f in files]

    def set_parser(self, parser: Callable[[str], Iterable]):
        self._parser = parser

    def load_into_memory(self):
        """Parse THIS RANK's file shard into memory (LoadIntoMemory)."""
        if self._parser is None:
            raise ValueError("set_parser first")
        self._samples = []
        for path in self._files[self.rank::self.world_size]:
            self._samples.extend(self._parser(path))
        return len(self._samples)

    def local_shuffle(self, epoch: int = 0):
        rng = np.random.RandomState(self.seed + epoch + 7919 * self.rank)
        rng.shuffle(self._samples)

    def global_shuffle(self, epoch: int = 0):
        """Rank-strided slice of ONE shared permutation over the GLOBAL
        sample index space — the reference's shuffle-exchange
        (data_set.cc GlobalShuffle over brpc) without an RPC fabric.
        Two passes so peak memory stays one RANK SHARD, not the corpus:
        pass 1 counts samples per file (streaming), then every rank draws
        the same permutation and keeps global indices r::world; pass 2
        re-parses only the files holding this rank's indices. Requires
        every rank to call with the same epoch."""
        if self._parser is None:
            raise ValueError("set_parser first")
        # pass 1: per-file counts, streaming (nothing retained)
        counts = []
        for path in self._files:
            n = 0
            for _ in self._parser(path):
                n += 1
            counts.append(n)
        total = int(np.sum(counts)) if counts else 0
        rng = np.random.RandomState(self.seed + epoch)  # SHARED stream
        order = rng.permutation(total)
        mine = order[self.rank::self.world_size]
        # map this rank's global indices to (file, in-file offset)
        starts = np.concatenate([[0], np.cumsum(counts)])
        wanted_by_file = {}
        for pos, gi in enumerate(mine):
            fi = int(np.searchsorted(starts, gi, side="right")) - 1
            wanted_by_file.setdefault(fi, []).append((int(gi - starts[fi]), pos))
        # pass 2: parse only needed files, keep only this rank's samples in
        # the permuted order
        self._samples = [None] * len(mine)
        for fi, offsets in wanted_by_file.items():
            want = dict(offsets)  # in-file offset -> output position
            for off, sample in enumerate(self._parser(self._files[fi])):
                if off in want:
                    self._samples[want[off]] = sample
        return len(self._samples)

    def get_memory_data_size(self) -> int:
        return len(self._samples)

    def __iter__(self):
        w0, wn = _worker_shard()
        return iter(self._samples[w0::wn])

    def __len__(self):
        return len(self._samples)
