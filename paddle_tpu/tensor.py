"""paddle_tpu.Tensor — the user-facing tensor.

Parity: the reference's dual tensor stack — C++ ``framework::Tensor``
(/root/reference/paddle/fluid/framework/tensor.h:89) plus dygraph ``VarBase``
(/root/reference/paddle/fluid/imperative/layer.h:66) with numpy interop from
pybind/tensor_py.h.

TPU-native redesign: one thin mutable wrapper around an immutable
``jax.Array``. No LoD (ragged batches are expressed with masks / segment ids —
see ops.sequence), no Place-keyed allocator (PJRT owns memory), no
DataLayout (XLA picks layouts). Autograd state lives here: ``stop_gradient``
(paddle's inverted requires_grad), ``grad``, and the producing tape Node.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import device as device_mod
from .autograd import tape
from .dtype import to_jax_dtype as _to_jax_dtype
from .dtype import to_paddle_dtype as _to_paddle_dtype

__all__ = ["Tensor", "to_tensor", "is_tensor"]


class Tensor:
    __slots__ = (
        "_data",
        "stop_gradient",
        "grad",
        "_node",
        "_out_idx",
        "_retain_grad",
        "name",
        "persistable",
        "trainable",
        "_hooks",
        "__weakref__",
    )

    def __init__(self, data, stop_gradient: bool = True, name: Optional[str] = None):
        if isinstance(data, Tensor):
            data = data._data
        # ShapeDtypeStructs ride as-is: the analysis planner's abstract
        # lowering (analysis/plan.py) builds full-size models whose params
        # are shape/dtype specs only — never materialized, only traced
        if not isinstance(data, (jax.Array, jax.ShapeDtypeStruct)):
            data = jnp.asarray(data)
        self._data = data
        self.stop_gradient = stop_gradient
        self.grad = None
        self._node = None
        self._out_idx = 0
        self._retain_grad = False
        self.name = name
        self.persistable = False
        self.trainable = not stop_gradient
        self._hooks = None

    # ------------------------------------------------------------------
    # basic properties
    # ------------------------------------------------------------------
    @property
    def value(self):
        """The underlying jax.Array."""
        return self._data

    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def rank(self):
        return self._data.ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def dtype(self):
        return _to_paddle_dtype(self._data.dtype)

    @property
    def place(self):
        try:
            dev = next(iter(self._data.devices()))
        except Exception:
            return device_mod.CPUPlace(0)
        if dev.platform == "tpu":
            return device_mod.TPUPlace(dev.id)
        return device_mod.CPUPlace(dev.id)

    @property
    def T(self):
        from .ops import manipulation

        return manipulation.transpose(self, list(range(self.ndim))[::-1])

    @property
    def is_leaf(self):
        return self._node is None

    # ------------------------------------------------------------------
    # conversion
    # ------------------------------------------------------------------
    def numpy(self) -> np.ndarray:
        return np.asarray(self._data)

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def astype(self, dt):
        from .ops import manipulation

        return manipulation.cast(self, dt)

    cast = astype

    def clone(self):
        from .ops import math as math_ops

        return math_ops.assign(self)

    def detach(self) -> "Tensor":
        t = Tensor(self._data, stop_gradient=True, name=self.name)
        return t

    def detach_(self):
        self._node = None
        self.stop_gradient = True
        return self

    def cpu(self):
        return Tensor(
            jax.device_put(self._data, jax.devices("cpu")[0]),
            stop_gradient=self.stop_gradient,
        )

    def to(self, place):
        p = device_mod._place_from(place)
        return Tensor(
            jax.device_put(self._data, p.jax_device()), stop_gradient=self.stop_gradient
        )

    def pin_memory(self):
        return self.cpu()

    # ------------------------------------------------------------------
    # autograd
    # ------------------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph: bool = False):
        tape.backward(self, grad_tensor, retain_graph)

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def retain_grads(self):
        self._retain_grad = True

    def register_hook(self, hook):
        """Grad hook (parity: VarBase::AddGradVarHook). Called with the grad
        Tensor when backward reaches this tensor; may return a replacement."""
        if self._hooks is None:
            self._hooks = []
        self._hooks.append(hook)
        handle_idx = len(self._hooks) - 1

        class _Handle:
            def remove(_self):
                self._hooks[handle_idx] = None

        return _Handle()

    # ------------------------------------------------------------------
    # mutation (paddle-style in-place on the wrapper)
    # ------------------------------------------------------------------
    def set_value(self, value):
        if isinstance(value, Tensor):
            if not isinstance(value._data, jax.Array):
                # symbolic Variable (static recording): record a state write
                from .static.program import handle_state_write

                if handle_state_write(self, value):
                    return self
            value = value._data
        arr = jnp.asarray(value, dtype=self._data.dtype)
        if tuple(arr.shape) != tuple(self._data.shape):
            raise ValueError(
                f"set_value shape mismatch: {arr.shape} vs {self._data.shape}"
            )
        self._data = arr
        return self

    def _set_data(self, arr):
        """Internal: rebind storage without shape check (optimizer updates)."""
        if isinstance(arr, Tensor):
            if not isinstance(arr._data, jax.Array):
                from .static.program import handle_state_write

                if handle_state_write(self, arr):
                    return
            arr = arr._data
        self._data = arr

    def fill_(self, value):
        self._data = jnp.full_like(self._data, value)
        return self

    def zero_(self):
        self._data = jnp.zeros_like(self._data)
        return self

    # ------------------------------------------------------------------
    # python protocol
    # ------------------------------------------------------------------
    def __repr__(self):
        grad_flag = f", stop_gradient={self.stop_gradient}"
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name}{grad_flag},\n"
            f"       {np.array2string(self.numpy(), prefix='       ')})"
        )

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-D tensor")
        return self._data.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __float__(self):
        return float(self.numpy())

    def __int__(self):
        return int(self.numpy())

    def __bool__(self):
        return bool(self.numpy())

    def __index__(self):
        return int(self.numpy())

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.numpy().item(), spec)
        return object.__format__(self, spec)

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def __getitem__(self, idx):
        from .ops import manipulation

        return manipulation._getitem(self, idx)

    def __setitem__(self, idx, value):
        if isinstance(value, Tensor):
            value = value._data
        idx = tuple(
            i._data if isinstance(i, Tensor) else i
            for i in (idx if isinstance(idx, tuple) else (idx,))
        )
        self._data = self._data.at[idx].set(value)

    def __hash__(self):
        return id(self)

    # arithmetic dunders are attached by ops/__init__.py via _register_methods
    @classmethod
    def _register_method(cls, name, fn):
        setattr(cls, name, fn)


def is_tensor(x) -> bool:
    return isinstance(x, Tensor)


def to_tensor(data, dtype=None, place=None, stop_gradient: bool = True) -> Tensor:
    """paddle.to_tensor parity."""
    if isinstance(data, Tensor):
        arr = data._data
    elif isinstance(data, jax.Array):
        arr = data
    else:
        arr = np.asarray(data)
        # paddle promotes python float lists to float32 by default (numpy gives f64)
        if dtype is None and arr.dtype == np.float64:
            arr = arr.astype(np.float32)
    if dtype is not None:
        arr = jnp.asarray(arr, dtype=_to_jax_dtype(dtype))
    else:
        arr = jnp.asarray(arr)
    if place is not None:
        arr = jax.device_put(arr, device_mod._place_from(place).jax_device())
    return Tensor(arr, stop_gradient=stop_gradient)


# jax pytree registration: a Tensor flattens to its array. This is what lets
# whole Layers / optimizer states cross the jit boundary as pytrees.
def _tensor_unflatten(aux, children):
    # the custom-pytree contract: unflatten must accept ARBITRARY leaf
    # objects — jax transforms (shard_map on 0.4.x, tree broadcasting)
    # rebuild trees with object() placeholders that are only inspected
    # structurally, so non-array leaves bypass jnp.asarray validation
    data = children[0]
    if isinstance(data, (jax.Array, np.ndarray, np.generic,
                         int, float, bool, complex)):
        return Tensor(data, stop_gradient=aux[0], name=aux[1])
    # reuse __init__ for every slot (single source of truth for Tensor
    # state), then plant the opaque leaf without coercion
    t = Tensor(0.0, stop_gradient=aux[0], name=aux[1])
    t._data = data
    return t


jax.tree_util.register_pytree_node(
    Tensor,
    lambda t: ((t._data,), (t.stop_gradient, t.name)),
    _tensor_unflatten,
)
