"""paddle.metric parity: Metric base + Accuracy/Precision/Recall/Auc.

Parity: /root/reference/python/paddle/metric/metrics.py (Metric:23,
Accuracy:183, Precision:285, Recall:395, Auc:504). Metrics accumulate on
HOST numpy (device work stays in the train step; metric update takes the
already-computed predictions), same split as the reference's CPU-side
metric ops.
"""
from __future__ import annotations

import abc

import numpy as np

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc",
           "accuracy", "mean_iou", "chunk_eval", "DetectionMAP",
           "precision_recall", "positive_negative_pair"]


def _to_np(x):
    if hasattr(x, "_data"):
        x = x._data
    return np.asarray(x)


class Metric(abc.ABC):
    def __init__(self):
        pass

    @abc.abstractmethod
    def reset(self):
        ...

    @abc.abstractmethod
    def update(self, *args):
        ...

    @abc.abstractmethod
    def accumulate(self):
        ...

    @abc.abstractmethod
    def name(self):
        ...

    def compute(self, *args):
        """Optional pre-processing run on device outputs; default passthrough."""
        return args


class Accuracy(Metric):
    """Top-k accuracy. compute() turns (pred, label) into per-sample
    correctness like the reference (metrics.py:183)."""

    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred = _to_np(pred)
        label = _to_np(label)
        idx = np.argsort(-pred, axis=-1)[..., : self.maxk]
        if label.ndim == pred.ndim and label.shape[-1] == 1:
            label = label[..., 0]
        correct = idx == label[..., None]
        return correct

    def update(self, correct, *args):
        correct = _to_np(correct)
        accs = []
        num = int(np.prod(correct.shape[:-1]))
        for k in self.topk:
            c = correct[..., :k].any(axis=-1).sum()
            accs.append(float(c) / max(num, 1))
            self.total[self.topk.index(k)] += float(c)
            self.count[self.topk.index(k)] += num
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    """Binary precision over probability outputs (metrics.py:285)."""

    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _to_np(preds).flatten()
        labels = _to_np(labels).flatten()
        pred_pos = np.round(preds).astype(np.int64) == 1
        self.tp += int(np.sum(pred_pos & (labels == 1)))
        self.fp += int(np.sum(pred_pos & (labels != 1)))

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    """Binary recall (metrics.py:395)."""

    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _to_np(preds).flatten()
        labels = _to_np(labels).flatten()
        pred_pos = np.round(preds).astype(np.int64) == 1
        self.tp += int(np.sum(pred_pos & (labels == 1)))
        self.fn += int(np.sum(~pred_pos & (labels == 1)))

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC AUC via histogram buckets (metrics.py:504 — same thresholded
    stat-accumulator design as the reference's auc op)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        preds = _to_np(preds)
        labels = _to_np(labels).flatten()
        if preds.ndim == 2 and preds.shape[1] == 2:
            preds = preds[:, 1]  # P(positive)
        preds = preds.flatten()
        idx = np.clip((preds * self.num_thresholds).astype(np.int64),
                      0, self.num_thresholds)
        pos = labels.astype(bool)
        nb = self.num_thresholds + 1
        self._stat_pos += np.bincount(idx[pos], minlength=nb)
        self._stat_neg += np.bincount(idx[~pos], minlength=nb)

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def accumulate(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            p, n = self._stat_pos[i], self._stat_neg[i]
            auc += n * tot_pos + p * n / 2.0
            tot_pos += p
            tot_neg += n
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        return float(auc) / (tot_pos * tot_neg)

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):  # noqa: A002
    """Functional top-k accuracy (parity: accuracy op,
    reference operators/metrics/accuracy_op.* and paddle.metric.accuracy).
    input: (N, C) scores; label: (N, 1) or (N,) int. Returns a 0-D tensor."""
    import jax.numpy as jnp

    from ..ops._primitive import unwrap, wrap

    scores = unwrap(input)
    lab = unwrap(label)
    if lab.ndim == 2:
        lab = lab[:, 0]
    topk_idx = jnp.argsort(-scores, axis=-1)[:, :k]
    hit = (topk_idx == lab[:, None].astype(topk_idx.dtype)).any(axis=1)
    return wrap(hit.mean(dtype=jnp.float32))


def mean_iou(input, label, num_classes, name=None):  # noqa: A002
    """Segmentation mean IoU (parity: mean_iou_op.h MeanIoUKernel):
    correct[c] = #(pred == label == c); wrong[c] counts both sides of every
    mismatch; per-class IoU = correct / (correct + wrong); mean over classes
    that appear. Returns (mean_iou scalar f32, out_wrong [C] i32,
    out_correct [C] i32)."""
    import jax.numpy as jnp

    from ..ops._primitive import primitive, unwrap

    @primitive(nondiff=True)
    def _miou(pred, lab):
        p = pred.reshape(-1).astype(jnp.int32)
        y = lab.reshape(-1).astype(jnp.int32)
        eq = p == y
        correct = jnp.zeros((num_classes,), jnp.int32).at[
            jnp.where(eq, p, num_classes)].add(1, mode="drop")
        wrong = jnp.zeros((num_classes,), jnp.int32)
        wrong = wrong.at[jnp.where(~eq, y, num_classes)].add(1, mode="drop")
        wrong = wrong.at[jnp.where(~eq, p, num_classes)].add(1, mode="drop")
        denom = correct + wrong
        valid = (denom > 0).sum()
        iou = correct.astype(jnp.float32) / jnp.maximum(denom, 1).astype(jnp.float32)
        mean = iou.sum() / jnp.maximum(valid, 1).astype(jnp.float32)
        return mean, wrong, correct

    return _miou(unwrap(input), unwrap(label))


def _chunk_segments(seq, scheme, num_chunk_types):
    """Segment extraction per chunk_eval_op.h GetSegments/ChunkBegin/ChunkEnd.
    Returns a set of (begin, end, type) with tags decoded as
    tag = label % num_tag_types, type = label // num_tag_types; type ==
    num_chunk_types is the 'other' (outside) class."""
    schemes = {
        "IOB": (2, 0, 1, -1, -1),
        "IOE": (2, -1, 0, 1, -1),
        "IOBES": (4, 0, 1, 2, 3),
        "plain": (1, -1, -1, -1, -1),
    }
    ntag, t_begin, t_inside, t_end, t_single = schemes[scheme]
    other = num_chunk_types

    def is_end(pt, py, t, y):
        if py == other:
            return False
        if y == other or y != py:
            return True
        if pt == t_begin or pt == t_inside:
            return t in (t_begin, t_single)
        return pt in (t_end, t_single)

    def is_begin(pt, py, t, y):
        if py == other:
            return y != other
        if y == other:
            return False
        if y != py:
            return True
        if t == t_begin or t == t_single:
            return True
        return t in (t_inside, t_end) and pt in (t_end, t_single)

    segs = set()
    in_chunk = False
    start = 0
    tag, typ = -1, other
    for i, lab in enumerate(seq):
        pt, py = tag, typ
        tag, typ = int(lab) % ntag, int(lab) // ntag
        if in_chunk and is_end(pt, py, tag, typ):
            segs.add((start, i - 1, py))
            in_chunk = False
        if is_begin(pt, py, tag, typ):
            start = i
            in_chunk = True
    if in_chunk:
        segs.add((start, len(seq) - 1, typ))
    return segs


def chunk_eval(input, label, chunk_scheme, num_chunk_types,  # noqa: A002
               excluded_chunk_types=None, seq_length=None, name=None):
    """Chunk (NER) F1 evaluation (parity: chunk_eval_op.h ChunkEvalKernel).
    input/label: (B, T) int labels (padded; ``seq_length`` gives valid
    lengths). Host op like the reference's CPU-only kernel. Returns
    (precision, recall, f1, num_infer, num_label, num_correct)."""
    import numpy as np

    import jax.numpy as jnp

    from ..ops._primitive import unwrap, wrap

    pred = np.asarray(unwrap(input))
    lab = np.asarray(unwrap(label))
    if pred.ndim == 1:
        pred, lab = pred[None], lab[None]
    B, T = pred.shape
    if seq_length is None:
        lens = np.full((B,), T, np.int64)
    else:
        lens = np.asarray(unwrap(seq_length)).astype(np.int64)
    excluded = set(excluded_chunk_types or ())
    n_inf = n_lab = n_cor = 0
    for b in range(B):
        sl = int(lens[b])
        inf_segs = {s for s in _chunk_segments(pred[b, :sl], chunk_scheme,
                                               num_chunk_types)
                    if s[2] not in excluded}
        lab_segs = {s for s in _chunk_segments(lab[b, :sl], chunk_scheme,
                                               num_chunk_types)
                    if s[2] not in excluded}
        n_inf += len(inf_segs)
        n_lab += len(lab_segs)
        n_cor += len(inf_segs & lab_segs)
    precision = n_cor / n_inf if n_inf else 0.0
    recall = n_cor / n_lab if n_lab else 0.0
    f1 = (2 * precision * recall / (precision + recall)
          if n_cor else 0.0)
    return (wrap(jnp.float32(precision)), wrap(jnp.float32(recall)),
            wrap(jnp.float32(f1)),
            wrap(jnp.int64(n_inf)), wrap(jnp.int64(n_lab)),
            wrap(jnp.int64(n_cor)))


class DetectionMAP(Metric):
    """Detection mean average precision (parity: detection_map_op.h —
    CalcTrueAndFalsePositive greedy per-class matching with visited flags
    and difficult handling, CalcMAP '11point' VOC2007 / 'integral' AP).
    Host-side metric like the reference's CPU-only kernel.

    update() takes the framework's dense+lengths detection convention:
    detections [D, 6] rows (label, score, x1, y1, x2, y2) + per-image
    det_counts [N]; ground truth gt [G, 5] rows (label, x1, y1, x2, y2)
    + gt_counts [N]; optional difficult [G] flags."""

    def __init__(self, overlap_threshold=0.5, evaluate_difficult=True,
                 ap_type="integral", background_label=0, name=None):
        assert ap_type in ("integral", "11point")
        self.overlap_threshold = float(overlap_threshold)
        self.evaluate_difficult = bool(evaluate_difficult)
        self.ap_type = ap_type
        self.background_label = int(background_label)
        self._name = name or "detection_map"
        self.reset()

    def reset(self):
        self._label_pos = {}
        self._tp = {}   # label -> list[(score, 0/1)]
        self._fp = {}

    @staticmethod
    def _iou(a, b):
        if b[0] > a[2] or b[2] < a[0] or b[1] > a[3] or b[3] < a[1]:
            return 0.0
        ix1, iy1 = max(a[0], b[0]), max(a[1], b[1])
        ix2, iy2 = min(a[2], b[2]), min(a[3], b[3])
        inter = (ix2 - ix1) * (iy2 - iy1)
        ua = ((a[2] - a[0]) * (a[3] - a[1])
              + (b[2] - b[0]) * (b[3] - b[1]) - inter)
        return inter / ua if ua > 0 else 0.0

    def update(self, detections, det_counts, gt, gt_counts, difficult=None):
        from ..ops._primitive import unwrap

        det = np.asarray(unwrap(detections), np.float64)
        dc = np.asarray(unwrap(det_counts), np.int64).reshape(-1)
        gtb = np.asarray(unwrap(gt), np.float64)
        gc = np.asarray(unwrap(gt_counts), np.int64).reshape(-1)
        if len(dc) != len(gc):
            raise ValueError(
                f"det_counts describes {len(dc)} images but gt_counts "
                f"{len(gc)} (reference detection_map enforces equal batch "
                "sizes)")
        if int(dc.sum()) != len(det) or int(gc.sum()) != len(gtb):
            raise ValueError("counts must sum to the provided row totals")
        diff = (np.zeros(len(gtb), bool) if difficult is None
                else np.asarray(unwrap(difficult)).astype(bool).reshape(-1))
        d_off = g_off = 0
        for n in range(len(dc)):
            drows = det[d_off: d_off + int(dc[n])]
            grows = gtb[g_off: g_off + int(gc[n])]
            gdiff = diff[g_off: g_off + int(gc[n])]
            d_off += int(dc[n])
            g_off += int(gc[n])
            # per-label positives count (difficult excluded unless evaluated)
            img_gt = {}
            for gi, row in enumerate(grows):
                img_gt.setdefault(int(row[0]), []).append(
                    (row[1:5], bool(gdiff[gi])))
            for label, boxes in img_gt.items():
                cnt = (len(boxes) if self.evaluate_difficult
                       else sum(1 for _, d in boxes if not d))
                if cnt:
                    self._label_pos[label] = self._label_pos.get(label, 0) + cnt
            # greedy matching per label, score-descending, visited flags
            by_label = {}
            for row in drows:
                by_label.setdefault(int(row[0]), []).append(row)
            for label, preds in by_label.items():
                gts = img_gt.get(label)
                tp = self._tp.setdefault(label, [])
                fp = self._fp.setdefault(label, [])
                if not gts:
                    for row in preds:
                        tp.append((float(row[1]), 0))
                        fp.append((float(row[1]), 1))
                    continue
                visited = [False] * len(gts)
                preds = sorted(preds, key=lambda r: -r[1])
                for row in preds:
                    box = np.clip(row[2:6], 0.0, 1.0)
                    score = float(row[1])
                    best, best_j = -1.0, 0
                    for j, (gbox, _) in enumerate(gts):
                        ov = self._iou(box, gbox)
                        if ov > best:
                            best, best_j = ov, j
                    if best > self.overlap_threshold:
                        if self.evaluate_difficult or not gts[best_j][1]:
                            if not visited[best_j]:
                                tp.append((score, 1))
                                fp.append((score, 0))
                                visited[best_j] = True
                            else:
                                tp.append((score, 0))
                                fp.append((score, 1))
                    else:
                        tp.append((score, 0))
                        fp.append((score, 1))

    def accumulate(self):
        m_ap, count = 0.0, 0
        for label, npos in self._label_pos.items():
            if npos == self.background_label:
                continue
            if label not in self._tp:
                count += 1
                continue
            tp = sorted(self._tp[label], key=lambda p: -p[0])
            fp = sorted(self._fp[label], key=lambda p: -p[0])
            tp_sum = np.cumsum([f for _, f in tp])
            fp_sum = np.cumsum([f for _, f in fp])
            precision = tp_sum / np.maximum(tp_sum + fp_sum, 1e-12)
            recall = tp_sum / npos
            if self.ap_type == "11point":
                maxp = np.zeros(11)
                start = len(recall) - 1
                for j in range(10, -1, -1):
                    for i in range(start, -1, -1):
                        if recall[i] < j / 10.0:
                            start = i
                            if j > 0:
                                maxp[j - 1] = maxp[j]
                            break
                        if maxp[j] < precision[i]:
                            maxp[j] = precision[i]
                m_ap += maxp.sum() / 11
            else:
                ap, prev = 0.0, 0.0
                for p, r in zip(precision, recall):
                    if abs(r - prev) > 1e-6:
                        ap += p * abs(r - prev)
                    prev = r
                m_ap += ap
            count += 1
        return m_ap / count if count else 0.0

    def name(self):
        return self._name


def precision_recall(max_probs, indices, labels, class_number, weights=None,
                     states_info=None, name=None):
    """Static precision_recall op (reference:
    operators/metrics/precision_recall_op.h PrecisionRecallKernel): per-class
    TP/FP/TN/FN state accumulation over top-1 predictions plus macro/micro
    metrics. Returns (batch_metrics [6], accum_metrics [6],
    accum_states [class_number, 4]) where the 6 metrics are
    [macro-P, macro-R, macro-F1, micro-P, micro-R, micro-F1] and state
    columns are [TP, FP, TN, FN]. ``max_probs`` is accepted for op-signature
    parity and unused by the math (the reference kernel reads Indices only).
    """
    idx = _to_np(indices).reshape(-1).astype(np.int64)
    lab = _to_np(labels).reshape(-1).astype(np.int64)
    c = int(class_number)
    w = (_to_np(weights).reshape(-1).astype(np.float64)
         if weights is not None else np.ones(idx.shape[0]))
    if idx.size and (idx.min() < 0 or idx.max() >= c):
        raise ValueError("precision_recall: class index out of range")
    if lab.size and (lab.min() < 0 or lab.max() >= c):
        raise ValueError("precision_recall: label out of range")

    states = np.zeros((c, 4), np.float64)  # TP FP TN FN
    hit = idx == lab
    np.add.at(states[:, 0], idx[hit], w[hit])                  # TP
    np.add.at(states[:, 1], idx[~hit], w[~hit])                # FP
    np.add.at(states[:, 3], lab[~hit], w[~hit])                # FN
    # TN: every sample adds w to all classes except its idx (and its label
    # when mispredicted)
    states[:, 2] = w.sum()
    np.subtract.at(states[:, 2], idx, w)
    np.subtract.at(states[:, 2], lab[~hit], w[~hit])

    def metrics(st):
        tp, fp, fn = st[:, 0], st[:, 1], st[:, 3]
        prec = np.where(tp + fp > 0, tp / np.maximum(tp + fp, 1e-30), 1.0)
        rec = np.where(tp + fn > 0, tp / np.maximum(tp + fn, 1e-30), 1.0)
        mac_p, mac_r = prec.mean(), rec.mean()
        mac_f = (2 * mac_p * mac_r / (mac_p + mac_r)
                 if mac_p + mac_r > 0 else 0.0)
        ttp, tfp, tfn = tp.sum(), fp.sum(), fn.sum()
        mic_p = ttp / (ttp + tfp) if ttp + tfp > 0 else 1.0
        mic_r = ttp / (ttp + tfn) if ttp + tfn > 0 else 1.0
        mic_f = (2 * mic_p * mic_r / (mic_p + mic_r)
                 if mic_p + mic_r > 0 else 0.0)
        return np.array([mac_p, mac_r, mac_f, mic_p, mic_r, mic_f])

    batch_metrics = metrics(states)
    accum_states = states.copy()
    if states_info is not None:
        accum_states += _to_np(states_info).astype(np.float64)
    accum_metrics = metrics(accum_states)
    return batch_metrics, accum_metrics, accum_states


def positive_negative_pair(score, label, query_id, weight=None,
                           accum_positive=0.0, accum_negative=0.0,
                           accum_neutral=0.0, column=-1, name=None):
    """Ranking pair statistics (reference: operators/
    positive_negative_pair_op.h): within each query group, every pair of
    documents with different labels counts toward positive (score order
    agrees with label order) or negative pairs; equal scores additionally
    count a neutral pair. Pair weight = mean of the two doc weights.
    Returns (positive, negative, neutral) including the accumulate inputs.
    """
    sc = _to_np(score).astype(np.float64)
    if sc.ndim == 1:
        sc = sc[:, None]
    col = int(column)
    if col < 0:
        col += sc.shape[1]
    s = sc[:, col]
    lab = _to_np(label).reshape(-1).astype(np.float64)
    qid = _to_np(query_id).reshape(-1).astype(np.int64)
    w = (_to_np(weight).reshape(-1).astype(np.float64)
         if weight is not None else np.ones(s.shape[0]))
    pos = float(accum_positive)
    neg = float(accum_negative)
    neu = float(accum_neutral)
    # pair enumeration per query group (bounds memory to sum of group
    # sizes squared, like the reference's per-query document lists)
    for q in np.unique(qid):
        sel = qid == q
        gs, gl, gw = s[sel], lab[sel], w[sel]
        i, j = np.triu_indices(gs.shape[0], k=1)
        m = gl[i] != gl[j]
        pw = (gw[i[m]] + gw[j[m]]) * 0.5
        ds = gs[i[m]] - gs[j[m]]
        dl = gl[i[m]] - gl[j[m]]
        pos += pw[ds * dl > 0].sum()
        # reference quirk kept: an equal-score pair adds to BOTH neutral
        # and negative (the ternary runs after the neu += w branch)
        neg += pw[ds * dl <= 0].sum()
        neu += pw[ds == 0].sum()
    return np.float64(pos), np.float64(neg), np.float64(neu)
