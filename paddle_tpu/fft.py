"""paddle_tpu.fft — discrete Fourier transform API.

Parity: python/paddle/fft.py + python/paddle/tensor/fft.py in the reference
(fft:131, fftn:442, hfftn:706, fftfreq:1149, fftshift:1245) backed there by the
``fft_c2c/fft_r2c/fft_c2r`` operators (paddle/fluid/operators/spectral_op.cc).

TPU-native redesign: every transform lowers to XLA's FFT HLO via ``jnp.fft``;
there are no separate c2c/r2c/c2r kernels to manage. The reference's ND
hermitian transforms (fftn_c2r / fftn_r2c, tensor/fft.py:1491,1546) are
composed here the same way they are there: a 1-D real<->hermitian transform
over the last axis and a complex c2c transform over the remaining axes, with
numpy ``norm`` strings applying per-axis so the composition matches the fused
reference op.
"""
from __future__ import annotations

import jax.numpy as jnp

from .dtype import to_jax_dtype
from .ops._primitive import primitive
from .tensor import Tensor

__all__ = [
    "fft", "fft2", "fftn", "ifft", "ifft2", "ifftn",
    "rfft", "rfft2", "rfftn", "irfft", "irfft2", "irfftn",
    "hfft", "hfft2", "hfftn", "ihfft", "ihfft2", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]

_NORMS = ("backward", "ortho", "forward")


def _check_norm(norm):
    if norm is None:
        return "backward"
    if norm not in _NORMS:
        raise ValueError(
            f"Unexpected norm: {norm}. Norm should be forward, backward or ortho"
        )
    return norm


def _axes_pair(s, axes, name):
    if axes is None:
        axes = (-2, -1)
    if s is not None and len(s) != len(axes):
        raise ValueError(f"Length of s ({len(s)}) and axes ({len(axes)}) must match for {name}")
    if len(axes) != 2:
        raise ValueError(f"{name} expects exactly 2 axes, got {len(axes)}")
    return s, tuple(axes)


# -- c2c ---------------------------------------------------------------------

@primitive
def _fft(x, n, axis, norm):
    return jnp.fft.fft(x, n=n, axis=axis, norm=norm)


@primitive
def _ifft(x, n, axis, norm):
    return jnp.fft.ifft(x, n=n, axis=axis, norm=norm)


@primitive
def _fftn(x, s, axes, norm):
    return jnp.fft.fftn(x, s=s, axes=axes, norm=norm)


@primitive
def _ifftn(x, s, axes, norm):
    return jnp.fft.ifftn(x, s=s, axes=axes, norm=norm)


# -- r2c / c2r ---------------------------------------------------------------

@primitive
def _rfft(x, n, axis, norm):
    return jnp.fft.rfft(x, n=n, axis=axis, norm=norm)


@primitive
def _irfft(x, n, axis, norm):
    return jnp.fft.irfft(x, n=n, axis=axis, norm=norm)


@primitive
def _rfftn(x, s, axes, norm):
    return jnp.fft.rfftn(x, s=s, axes=axes, norm=norm)


@primitive
def _irfftn(x, s, axes, norm):
    return jnp.fft.irfftn(x, s=s, axes=axes, norm=norm)


@primitive
def _hfft(x, n, axis, norm):
    return jnp.fft.hfft(x, n=n, axis=axis, norm=norm)


@primitive
def _ihfft(x, n, axis, norm):
    return jnp.fft.ihfft(x, n=n, axis=axis, norm=norm)


@primitive
def _hfftn(x, s, axes, norm):
    # c2c forward over axes[:-1], then hermitian c2r over the last axis
    # (composition mirrors fftn_c2r, reference tensor/fft.py:1546)
    if len(axes) > 1:
        x = jnp.fft.fftn(x, s=None if s is None else s[:-1], axes=axes[:-1], norm=norm)
    n_last = None if s is None else s[-1]
    return jnp.fft.hfft(x, n=n_last, axis=axes[-1], norm=norm)


@primitive
def _ihfftn(x, s, axes, norm):
    x = jnp.fft.ihfft(x, n=None if s is None else s[-1], axis=axes[-1], norm=norm)
    if len(axes) > 1:
        x = jnp.fft.ifftn(x, s=None if s is None else s[:-1], axes=axes[:-1], norm=norm)
    return x


# -- public API (reference python/paddle/tensor/fft.py signatures) -----------

def fft(x, n=None, axis=-1, norm="backward", name=None):
    return _fft(x, n, axis, _check_norm(norm))


def ifft(x, n=None, axis=-1, norm="backward", name=None):
    return _ifft(x, n, axis, _check_norm(norm))


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    s, axes = _axes_pair(s, axes, "fft2")
    return _fftn(x, s, axes, _check_norm(norm))


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    s, axes = _axes_pair(s, axes, "ifft2")
    return _ifftn(x, s, axes, _check_norm(norm))


def fftn(x, s=None, axes=None, norm="backward", name=None):
    return _fftn(x, s, axes, _check_norm(norm))


def ifftn(x, s=None, axes=None, norm="backward", name=None):
    return _ifftn(x, s, axes, _check_norm(norm))


def rfft(x, n=None, axis=-1, norm="backward", name=None):
    return _rfft(x, n, axis, _check_norm(norm))


def irfft(x, n=None, axis=-1, norm="backward", name=None):
    return _irfft(x, n, axis, _check_norm(norm))


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    s, axes = _axes_pair(s, axes, "rfft2")
    return _rfftn(x, s, axes, _check_norm(norm))


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    s, axes = _axes_pair(s, axes, "irfft2")
    return _irfftn(x, s, axes, _check_norm(norm))


def rfftn(x, s=None, axes=None, norm="backward", name=None):
    return _rfftn(x, s, axes, _check_norm(norm))


def irfftn(x, s=None, axes=None, norm="backward", name=None):
    return _irfftn(x, s, axes, _check_norm(norm))


def hfft(x, n=None, axis=-1, norm="backward", name=None):
    return _hfft(x, n, axis, _check_norm(norm))


def ihfft(x, n=None, axis=-1, norm="backward", name=None):
    return _ihfft(x, n, axis, _check_norm(norm))


def _norm_axes(x, axes):
    ndim = len(x.shape)
    if axes is None:
        axes = tuple(range(ndim))
    return tuple(a % ndim for a in axes)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    s, axes = _axes_pair(s, axes, "hfft2")
    return _hfftn(x, s, _norm_axes(x, axes), _check_norm(norm))


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    s, axes = _axes_pair(s, axes, "ihfft2")
    return _ihfftn(x, s, _norm_axes(x, axes), _check_norm(norm))


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    return _hfftn(x, s, _norm_axes(x, axes), _check_norm(norm))


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    return _ihfftn(x, s, _norm_axes(x, axes), _check_norm(norm))


# -- helpers -----------------------------------------------------------------

def fftfreq(n, d=1.0, dtype=None, name=None):
    jdt = to_jax_dtype(dtype) if dtype is not None else jnp.float32
    return Tensor(jnp.fft.fftfreq(n, d=d).astype(jdt))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    jdt = to_jax_dtype(dtype) if dtype is not None else jnp.float32
    return Tensor(jnp.fft.rfftfreq(n, d=d).astype(jdt))


@primitive
def _fftshift(x, axes):
    return jnp.fft.fftshift(x, axes=axes)


@primitive
def _ifftshift(x, axes):
    return jnp.fft.ifftshift(x, axes=axes)


def fftshift(x, axes=None, name=None):
    return _fftshift(x, axes)


def ifftshift(x, axes=None, name=None):
    return _ifftshift(x, axes)
