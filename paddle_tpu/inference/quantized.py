"""Quantized-weight deployment artifacts (ISSUE 18).

Parity: Paddle Inference consumes PaddleSlim's post-training-quantized
programs as ordinary saved inference models whose weights are int8 plus
scale tensors.  Here the artifact is framework-native: one file holding a
length-prefixed JSON meta block (format version, layer list, per-tensor
shapes/dtypes, payload CRC) followed by an npz payload of the int8
weights and their ``weight_scale`` / ``act_scale`` buffers.

The file is published through
:func:`paddle_tpu.framework.checkpoint.durable_write_bytes` (write
dot-temp sibling, fsync, atomic rename, fsync dir), so a crash mid-save
leaves the previous artifact intact; the CRC pins torn/corrupted
payloads at load — a flipped scale byte fails loudly with
:class:`ValueError` instead of silently mis-scaling every matmul.

``load_quantized`` applies the artifact onto a same-architecture fp
model in place (weights become int8, scale buffers registered), after
which the model serves through the W8A8 path exactly as if
``quantize_model_weights_`` had run locally.
"""
from __future__ import annotations

import io
import json
import struct
import zlib
from typing import Dict, List

import numpy as np

__all__ = ["save_quantized", "load_quantized", "QUANT_FORMAT_VERSION"]

QUANT_FORMAT_VERSION = 1
_MAGIC = b"PDQ8"


def _layer_map(model) -> Dict[str, object]:
    return {name or type(layer).__name__: layer
            for name, layer in model.named_sublayers(include_self=True)}


def save_quantized(model, path: str) -> List[str]:
    """Serialize ``model``'s int8 layers (weights + scales) to ``path``.

    The model must already be quantized (:func:`~paddle_tpu.quantization
    .quantize_model_weights_` / engine ``weight_dtype="int8"``).  Returns
    the layer names captured.  Raises :class:`ValueError` when the model
    holds no int8 layers — saving an fp model as a "quantized artifact"
    would only defer the surprise to load time.
    """
    from ..framework.checkpoint import durable_write_bytes
    from ..quantization.ptq import _np_dtype_name, _target_layers

    arrays: Dict[str, np.ndarray] = {}
    layers_meta: Dict[str, Dict] = {}
    for name, layer in _target_layers(model):
        if _np_dtype_name(layer.weight) != "int8":
            continue
        scale = getattr(layer, "weight_scale", None)
        if scale is None:
            raise ValueError(
                f"layer {name} has int8 weight but no weight_scale buffer")
        w = np.asarray(layer.weight._data)
        s = np.asarray(scale._data)
        arrays[f"{name}.weight"] = w
        arrays[f"{name}.weight_scale"] = s
        entry = {"weight_shape": list(w.shape),
                 "weight_dtype": str(w.dtype),
                 "scale_shape": list(s.shape)}
        act = getattr(layer, "act_scale", None)
        if act is not None:
            arrays[f"{name}.act_scale"] = np.asarray(act._data)
            entry["act_scale"] = True
        layers_meta[name] = entry
    if not layers_meta:
        raise ValueError(
            "model holds no int8 layers — run quantize_model_weights_ "
            "(or post_training_quantize_) before save_quantized")

    buf = io.BytesIO()
    np.savez(buf, **arrays)
    payload = buf.getvalue()
    meta = {
        "format": "paddle_tpu.quantized",
        "version": QUANT_FORMAT_VERSION,
        "scheme": "w8a8-per-channel-absmax",
        "layers": layers_meta,
        "payload_crc32": zlib.crc32(payload) & 0xFFFFFFFF,
        "payload_bytes": len(payload),
    }
    meta_bytes = json.dumps(meta, sort_keys=True).encode("utf-8")
    blob = (_MAGIC + struct.pack("<I", len(meta_bytes)) + meta_bytes
            + payload)
    durable_write_bytes(path, blob)
    return sorted(layers_meta)


def load_quantized(model, path: str) -> List[str]:
    """Apply a :func:`save_quantized` artifact onto ``model`` in place.

    Verifies the payload CRC before touching the model — a corrupt
    artifact raises :class:`ValueError` and leaves the model untouched.
    Returns the layer names applied.
    """
    with open(path, "rb") as f:
        blob = f.read()
    if len(blob) < len(_MAGIC) + 4 or blob[:len(_MAGIC)] != _MAGIC:
        raise ValueError(f"{path}: not a paddle_tpu quantized artifact")
    (meta_len,) = struct.unpack_from("<I", blob, len(_MAGIC))
    meta_off = len(_MAGIC) + 4
    if meta_off + meta_len > len(blob):
        raise ValueError(f"{path}: truncated meta block")
    meta = json.loads(blob[meta_off:meta_off + meta_len].decode("utf-8"))
    if meta.get("version") != QUANT_FORMAT_VERSION:
        raise ValueError(
            f"{path}: unsupported quantized-artifact version "
            f"{meta.get('version')!r}")
    payload = blob[meta_off + meta_len:]
    if len(payload) != int(meta.get("payload_bytes", -1)):
        raise ValueError(
            f"{path}: payload length {len(payload)} != recorded "
            f"{meta.get('payload_bytes')}")
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    if crc != int(meta["payload_crc32"]):
        raise ValueError(
            f"{path}: payload CRC mismatch (stored "
            f"{int(meta['payload_crc32']):#010x}, computed {crc:#010x}) — "
            "artifact corrupt, refusing to load mis-scaled weights")

    import jax.numpy as jnp

    from ..tensor import Tensor

    with np.load(io.BytesIO(payload)) as z:
        arrays = {k: z[k] for k in z.files}
    layers = _layer_map(model)
    applied = []
    for name, entry in meta["layers"].items():
        layer = layers.get(name)
        if layer is None:
            raise ValueError(
                f"{path}: artifact layer {name!r} not found in model")
        w = arrays[f"{name}.weight"]
        if list(w.shape) != list(np.asarray(layer.weight._data).shape):
            raise ValueError(
                f"{path}: layer {name!r} weight shape {list(w.shape)} != "
                f"model {list(np.asarray(layer.weight._data).shape)}")
        layer.weight._set_data(jnp.asarray(w))
        layer.register_buffer(
            "weight_scale",
            Tensor(jnp.asarray(arrays[f"{name}.weight_scale"],
                               jnp.float32)))
        if entry.get("act_scale"):
            layer.register_buffer(
                "act_scale",
                Tensor(jnp.asarray(arrays[f"{name}.act_scale"],
                                   jnp.float32)))
        applied.append(name)
    return sorted(applied)
