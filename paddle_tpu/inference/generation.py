"""KV-cache generation on EXPORTED artifacts.

Parity: the reference serves autoregressive decoding through
AnalysisPredictor over exported inference programs
(inference/api/analysis_predictor.h:86, :173 ZeroCopyRun); PaddleNLP's
FasterGeneration exports decoding loops as fused inference ops. TPU-native
design: ``save_for_generation`` exports TWO StableHLO programs —

- ``<path>.prefill``: (ids [B, T0]) → (last-token logits, K/V buffers
  [L, B, H, S, D] written at [0, T0))
- ``<path>.step``:    (tok [B, 1], pos [1], k, v) → (logits, new k, new v)
  — one incremental token against the fixed-capacity cache, O(S)
  attention via dynamic_update_slice at ``pos``

``GenerationPredictor`` drives them exactly like the eager
``models.generate`` loop, so generations match token-for-token (tested).
Both artifacts accept jit.save's precision passes, including the int8
weight-only PTQ artifact form.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = ["save_for_generation", "GenerationPredictor"]


def _attn_layers(model):
    from ..models.gpt import GPTAttention

    return [m for m in model.sublayers() if isinstance(m, GPTAttention)]


class _PrefillModule:
    """Layer-like wrapper whose forward prefills the fixed-size cache."""

    def __init__(self, model, max_seq_len):
        self.model = model
        self.max_seq_len = int(max_seq_len)

    def build_layer(self):
        from ..nn.layer import Layer
        from ..ops import creation, manipulation as manip

        model, s = self.model, self.max_seq_len
        attns = _attn_layers(model)
        cfg = model.gpt.config
        heads, hd = cfg.num_attention_heads, cfg.head_dim

        class Prefill(Layer):
            def __init__(self):
                super().__init__()
                self.gpt_model = model  # registers params for export

            def forward(self, ids):
                b = ids.shape[0]
                zeros = creation.zeros([b, heads, s, hd], dtype="float32")
                pos0 = creation.zeros([1], dtype="int32")
                for a in attns:
                    a._gen_cache = {"mode": "buffer", "k": zeros, "v": zeros,
                                    "pos": pos0}
                try:
                    logits = model(ids)
                    ks = manip.stack([a._gen_cache["k"] for a in attns])
                    vs = manip.stack([a._gen_cache["v"] for a in attns])
                finally:
                    for a in attns:
                        if hasattr(a, "_gen_cache"):
                            del a._gen_cache
                return logits[:, -1], ks, vs

        return Prefill()


class _StepModule:
    def __init__(self, model, max_seq_len):
        self.model = model
        self.max_seq_len = int(max_seq_len)

    def build_layer(self):
        from ..nn.layer import Layer
        from ..ops import creation, manipulation as manip

        model = self.model
        attns = _attn_layers(model)

        class Step(Layer):
            def __init__(self):
                super().__init__()
                self.gpt_model = model

            def forward(self, tok, pos, k_stack, v_stack):
                # tok [B, 1]; pos [1] int32; stacks [L, B, H, S, D]
                for li, a in enumerate(attns):
                    a._gen_cache = {"mode": "buffer", "k": k_stack[li],
                                    "v": v_stack[li], "pos": pos}
                try:
                    position_ids = manip.expand(
                        manip.reshape(pos, [1, 1]), [tok.shape[0], 1])
                    logits = model(tok, position_ids)
                    ks = manip.stack([a._gen_cache["k"] for a in attns])
                    vs = manip.stack([a._gen_cache["v"] for a in attns])
                finally:
                    for a in attns:
                        if hasattr(a, "_gen_cache"):
                            del a._gen_cache
                return logits[:, -1], ks, vs

        return Step()


def save_for_generation(model, path: str, max_seq_len: int, batch_size: int = -1,
                        prompt_len: int = -1, **save_config):
    """Export a GPT model's prefill + incremental-decode programs.

    ``max_seq_len``: KV-buffer capacity S (prompt + generated tokens must
    fit). ``batch_size``/``prompt_len``: -1 = symbolic (any). Extra
    ``save_config`` (e.g. precision="int8") forwards to jit.save for both
    artifacts. Learned-position configs only (rope buffer offsets are not
    wired)."""
    from ..jit import InputSpec, save as jit_save
    from ..models.gpt import GPTForPretraining

    if not isinstance(model, GPTForPretraining):
        raise TypeError("save_for_generation expects a GPTForPretraining")
    cfg = model.gpt.config
    L = cfg.num_layers
    heads, hd = cfg.num_attention_heads, cfg.head_dim
    was_training = model.training
    model.eval()
    try:
        prefill = _PrefillModule(model, max_seq_len).build_layer()
        jit_save(prefill, path + ".prefill",
                 input_spec=[InputSpec([batch_size, prompt_len], "int32")],
                 **save_config)
        step = _StepModule(model, max_seq_len).build_layer()
        jit_save(step, path + ".step", input_spec=[
            InputSpec([batch_size, 1], "int32"),
            InputSpec([1], "int32"),
            InputSpec([L, batch_size, heads, max_seq_len, hd], "float32"),
            InputSpec([L, batch_size, heads, max_seq_len, hd], "float32"),
        ], **save_config)
    finally:
        if was_training:
            model.train()


class GenerationPredictor:
    """Predictor-driven incremental decoding over save_for_generation
    artifacts (greedy; the sampling policies live in models.generate —
    deployment decoding is deterministic like the reference's inference
    demos)."""

    def __init__(self, path: str):
        from ..jit import load as jit_load

        self._prefill = jit_load(path + ".prefill")
        self._step = jit_load(path + ".step")
        self._prefill.eval()
        self._step.eval()

    def generate(self, input_ids, max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None) -> np.ndarray:
        import paddle_tpu as paddle

        ids = np.asarray(
            input_ids._data if hasattr(input_ids, "_data") else input_ids
        ).astype(np.int32)
        b, t0 = ids.shape
        logits, ks, vs = self._prefill(paddle.to_tensor(ids))
        capacity = int(ks._data.shape[3])
        if t0 + int(max_new_tokens) > capacity:
            raise ValueError(
                f"prompt ({t0}) + max_new_tokens ({int(max_new_tokens)}) "
                f"exceeds the exported KV capacity max_seq_len={capacity}; "
                "re-export with a larger max_seq_len")
        out = [ids]
        finished = np.zeros((b,), bool)
        pos = t0
        for step in range(int(max_new_tokens)):
            nxt = np.asarray(logits._data).argmax(-1).astype(np.int32)
            if eos_token_id is not None:
                # keep int32: numpy<2 promotes (python int, int32) to int64,
                # which the exported step's int32 input spec rejects
                nxt = np.where(finished, eos_token_id, nxt).astype(np.int32)
                finished = finished | (nxt == eos_token_id)
            out.append(nxt[:, None])
            if eos_token_id is not None and finished.all():
                break
            if step == int(max_new_tokens) - 1:
                break
            logits, ks, vs = self._step(
                paddle.to_tensor(nxt[:, None]),
                paddle.to_tensor(np.asarray([pos], np.int32)), ks, vs)
            pos += 1
        return np.concatenate(out, axis=1).astype(np.int64)
