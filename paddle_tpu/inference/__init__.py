"""paddle_tpu.inference — deployment predictor API.

Parity: paddle.inference (reference paddle/fluid/inference/api/
analysis_predictor.h:86 AnalysisPredictor + AnalysisConfig + the analysis
pass pipeline, analysis_predictor.cc:179 / analysis/analyzer.cc;
python wrapper python/paddle/inference/__init__.py).

TPU-native pass pipeline: the reference runs dozens of graph rewrites
(fuse passes, memory reuse, TensorRT subgraphs). Under XLA most of those
are the compiler's job, so the pass list here names the REAL actions this
predictor performs — each can be removed via the PassStrategy just like the
reference's pass_builder():

- ``stablehlo_jit_cache``  (ir_optim): route exported.call through one
  jitted closure so repeated runs replay a compiled executable per input
  shape instead of re-tracing the deserialized module.
- ``weight_device_residency``: keep the deserialized weights device-resident
  across runs (one H2D at load, zero per-run transfers).
- ``input_buffer_donation`` (enable_memory_optim): donate the feed buffers
  to the executable so XLA reuses their HBM for outputs/temps — the
  memory_optimize_pass analog.
- fusion/layout/constant-fold: absorbed by XLA compilation (documented, not
  listed as deletable passes).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

__all__ = ["Config", "Predictor", "create_predictor", "PredictorTensor",
           "PaddlePassBuilder", "save_for_generation", "GenerationPredictor",
           "save_quantized", "load_quantized"]


def __getattr__(name):
    if name in ("save_for_generation", "GenerationPredictor"):
        from . import generation

        return getattr(generation, name)
    if name in ("save_quantized", "load_quantized"):
        from . import quantized

        return getattr(quantized, name)
    raise AttributeError(name)

_DEFAULT_PASSES = [
    "stablehlo_jit_cache",
    "weight_device_residency",
]


class PaddlePassBuilder:
    """Pass-pipeline surface (parity: paddle/fluid/inference/api/
    paddle_pass_builder.h PaddlePassBuilder)."""

    def __init__(self, passes: List[str]):
        self._passes = list(passes)

    def all_passes(self) -> List[str]:
        return list(self._passes)

    def delete_pass(self, name: str):
        self._passes = [p for p in self._passes if p != name]

    def append_pass(self, name: str):
        if name not in self._passes:
            self._passes.append(name)

    def insert_pass(self, idx: int, name: str):
        if name not in self._passes:
            self._passes.insert(idx, name)

    def turn_on_debug(self):
        pass


class Config:
    """AnalysisConfig parity: model path + the real pass toggles above."""

    def __init__(self, prog_file: Optional[str] = None, params_file: Optional[str] = None):
        # accept either a path prefix (our native form) or the reference's
        # (model, params) file pair sharing a prefix
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self.model_prefix = prog_file
        self._use_device = "tpu"
        self.ir_optim = True
        self._memory_pool_mb = 0
        self._pass_builder = PaddlePassBuilder(_DEFAULT_PASSES)

    def set_model(self, prog_file: str, params_file: Optional[str] = None):
        if prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self.model_prefix = prog_file

    def enable_use_gpu(self, memory_pool_init_size_mb: int = 100, device_id: int = 0):
        self._use_device = "tpu"  # accelerator of this build
        self._memory_pool_mb = memory_pool_init_size_mb

    def disable_gpu(self):
        self._use_device = "cpu"

    def switch_ir_optim(self, flag: bool = True):
        self.ir_optim = flag
        if flag:
            self._pass_builder.append_pass("stablehlo_jit_cache")
        else:
            self._pass_builder.delete_pass("stablehlo_jit_cache")

    def enable_memory_optim(self):
        """memory_optimize_pass analog: donate feed buffers to the
        executable so their HBM is reused for outputs/temps."""
        self._pass_builder.append_pass("input_buffer_donation")

    def pass_builder(self) -> PaddlePassBuilder:
        return self._pass_builder

    def ir_optim_enabled(self) -> bool:
        return "stablehlo_jit_cache" in self._pass_builder.all_passes()


class PredictorTensor:
    """ZeroCopy tensor handle parity (api/details/zero_copy_tensor.cc)."""

    def __init__(self, name: str, owner: "Predictor", is_input: bool):
        self.name = name
        self._owner = owner
        self._is_input = is_input

    def copy_from_cpu(self, arr: np.ndarray):
        self._owner._feeds[self.name] = np.asarray(arr)

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._owner._outputs[self.name])

    def shape(self):
        a = (self._owner._feeds if self._is_input else self._owner._outputs).get(self.name)
        return list(a.shape) if a is not None else []


class Predictor:
    """AnalysisPredictor parity over a StableHLO export, executing the
    configured pass pipeline at load/run time."""

    def __init__(self, config: Config):
        import jax
        import jax.numpy as jnp

        from ..static import load_inference_model

        if not config.model_prefix:
            raise ValueError("Config has no model path")
        # a .pdmodel prefix may hold either a static-Program export
        # (static.save_inference_model) or a jit.save Layer artifact —
        # AnalysisPredictor consumes both (the reference loads any exported
        # inference program)
        import json

        with open(config.model_prefix + ".pdmeta") as f:
            meta = json.load(f)
        if "n_captures" not in meta:
            self._init_from_jit_artifact(config, meta)
            return
        prog, feed_names, fetch_names = load_inference_model(config.model_prefix, None)
        self._prog = prog
        self._feed_names = list(feed_names)
        self._fetch_names = list(fetch_names)
        self._feeds = {}
        self._outputs = {}

        passes = set(config.pass_builder().all_passes())
        self._passes = passes
        if "weight_device_residency" in passes:
            # one H2D at load; runs never re-transfer weights
            prog._captures = [jnp.asarray(c) for c in prog._captures]
        else:
            # pass removed: weights stay host-resident, re-transferred per
            # run (the observable un-optimized behavior)
            prog._captures = [np.asarray(c) for c in prog._captures]
        self._jitted = None
        if "stablehlo_jit_cache" in passes:
            exported = prog._exported
            donate = (2,) if "input_buffer_donation" in passes else ()

            def call(captures, key, *feeds):
                return exported.call(captures, key, *feeds)

            # donate the feed tuple (argnums >= 2) so XLA reuses its HBM
            self._jitted = jax.jit(
                call, donate_argnums=tuple(
                    range(2, 2 + len(self._feed_names))) if donate else ())

    def _init_from_jit_artifact(self, config: Config, meta: dict):
        """Load a jit.save (TranslatedLayer) artifact: feed/fetch names are
        positional (x0.. / out0..); ir_optim routes runs through the
        layer's exported.call under one jit closure."""
        import jax
        import jax.numpy as jnp

        from ..jit import load as jit_load

        layer = jit_load(config.model_prefix)
        layer.eval()
        self._prog = None
        self._layer = layer
        n_in = len(meta.get("input_shapes", [])) or 1
        self._feed_names = [f"x{i}" for i in range(n_in)]
        n_out = meta.get("n_outputs")
        # older artifacts lack n_outputs: resolved lazily on first run
        self._fetch_names = ([f"out{i}" for i in range(int(n_out))]
                             if n_out else None)
        self._feeds = {}
        self._outputs = {}
        self._passes = set(config.pass_builder().all_passes())
        exported = layer._exported
        params = {n: p._data for n, p in layer._loaded_params.items()}
        buffers = {n: b._data for n, b in layer._loaded_buffers.items()}
        self._jitted = None
        if "stablehlo_jit_cache" in self._passes:
            donate = "input_buffer_donation" in self._passes

            def call(params, buffers, key, *feeds):
                out, _ = exported.call(params, buffers, key, *feeds)
                return out

            self._jitted = jax.jit(
                call,
                donate_argnums=tuple(range(3, 3 + n_in)) if donate else ())
        self._jit_state = (params, buffers)

    # -- reference API --------------------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._feed_names)

    def get_output_names(self) -> List[str]:
        return list(self._fetch_names or [])

    def get_input_handle(self, name: str) -> PredictorTensor:
        return PredictorTensor(name, self, True)

    def get_output_handle(self, name: str) -> PredictorTensor:
        return PredictorTensor(name, self, False)

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """ZeroCopyRun parity; optionally positional inputs like the v2 API."""
        import jax
        import jax.numpy as jnp

        if inputs is not None:
            for n, a in zip(self._feed_names, inputs):
                self._feeds[n] = np.asarray(a)
        missing = [n for n in self._feed_names if n not in self._feeds]
        if missing:
            raise ValueError(f"missing inputs: {missing}")
        if self._prog is None:  # jit.save artifact mode
            params, buffers = self._jit_state
            feeds = [jnp.asarray(self._feeds[n]) for n in self._feed_names]
            if self._jitted is not None:
                outs = self._jitted(params, buffers, jax.random.PRNGKey(0), *feeds)
            else:
                outs, _ = self._layer._exported.call(
                    params, buffers, jax.random.PRNGKey(0), *feeds)
            outs = [np.asarray(o) for o in outs]
            if self._fetch_names is None:
                self._fetch_names = [f"out{i}" for i in range(len(outs))]
        elif self._jitted is not None:
            feeds = [jnp.asarray(self._feeds[n]) for n in self._feed_names]
            outs = self._jitted(self._prog._captures, jax.random.PRNGKey(0), *feeds)
            outs = [np.asarray(o) for o in outs]
        else:
            outs = self._prog.run(self._feeds)
        self._outputs = dict(zip(self._fetch_names, outs))
        return [self._outputs[n] for n in self._fetch_names]


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
