"""paddle_tpu.inference — deployment predictor API.

Parity: paddle.inference (reference paddle/fluid/inference/api/
analysis_predictor.h:86 AnalysisPredictor + AnalysisConfig; python wrapper
python/paddle/inference/__init__.py). The reference's pass pipeline /
TensorRT subgraphs are replaced by XLA: a predictor executes a deserialized
StableHLO program exported by ``paddle.static.save_inference_model`` or
``paddle.jit.save`` — already fused and TPU-lowerable.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

__all__ = ["Config", "Predictor", "create_predictor", "PredictorTensor"]


class Config:
    """AnalysisConfig parity: holds the model path; device/ir toggles are
    accepted and recorded (XLA owns optimization/placement)."""

    def __init__(self, prog_file: Optional[str] = None, params_file: Optional[str] = None):
        # accept either a path prefix (our native form) or the reference's
        # (model, params) file pair sharing a prefix
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self.model_prefix = prog_file
        self._use_device = "tpu"
        self.ir_optim = True
        self._memory_pool_mb = 0

    def set_model(self, prog_file: str, params_file: Optional[str] = None):
        if prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self.model_prefix = prog_file

    def enable_use_gpu(self, memory_pool_init_size_mb: int = 100, device_id: int = 0):
        self._use_device = "tpu"  # accelerator of this build
        self._memory_pool_mb = memory_pool_init_size_mb

    def disable_gpu(self):
        self._use_device = "cpu"

    def switch_ir_optim(self, flag: bool = True):
        self.ir_optim = flag

    def enable_memory_optim(self):
        pass


class PredictorTensor:
    """ZeroCopy tensor handle parity (api/details/zero_copy_tensor.cc)."""

    def __init__(self, name: str, owner: "Predictor", is_input: bool):
        self.name = name
        self._owner = owner
        self._is_input = is_input

    def copy_from_cpu(self, arr: np.ndarray):
        self._owner._feeds[self.name] = np.asarray(arr)

    def copy_to_cpu(self) -> np.ndarray:
        return self._owner._outputs[self.name]

    def shape(self):
        a = (self._owner._feeds if self._is_input else self._owner._outputs).get(self.name)
        return list(a.shape) if a is not None else []


class Predictor:
    """AnalysisPredictor parity over a StableHLO export."""

    def __init__(self, config: Config):
        from ..static import load_inference_model

        if not config.model_prefix:
            raise ValueError("Config has no model path")
        prog, feed_names, fetch_names = load_inference_model(config.model_prefix, None)
        self._prog = prog
        self._feed_names = list(feed_names)
        self._fetch_names = list(fetch_names)
        self._feeds = {}
        self._outputs = {}

    # -- reference API --------------------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._feed_names)

    def get_output_names(self) -> List[str]:
        return list(self._fetch_names)

    def get_input_handle(self, name: str) -> PredictorTensor:
        return PredictorTensor(name, self, True)

    def get_output_handle(self, name: str) -> PredictorTensor:
        return PredictorTensor(name, self, False)

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """ZeroCopyRun parity; optionally positional inputs like the v2 API."""
        if inputs is not None:
            for n, a in zip(self._feed_names, inputs):
                self._feeds[n] = np.asarray(a)
        missing = [n for n in self._feed_names if n not in self._feeds]
        if missing:
            raise ValueError(f"missing inputs: {missing}")
        outs = self._prog.run(self._feeds)
        self._outputs = dict(zip(self._fetch_names, outs))
        return [self._outputs[n] for n in self._fetch_names]


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)
