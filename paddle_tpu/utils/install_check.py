"""paddle.utils.run_check parity (reference utils/install_check.py): train a
tiny model end-to-end and report the device fleet."""
from __future__ import annotations

import numpy as np

__all__ = ["run_check"]


def run_check():
    import jax

    import paddle_tpu as paddle

    devs = jax.devices()
    print(f"paddle_tpu is installed; found {len(devs)} device(s): "
          f"{devs[0].platform}")
    net = paddle.nn.Linear(4, 2)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    x = paddle.to_tensor(np.random.rand(8, 4).astype("float32"))
    losses = []
    for _ in range(5):
        loss = (net(x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] <= losses[0], "training smoke failed"
    print("paddle_tpu works! single-device train smoke passed "
          f"(loss {losses[0]:.4f} -> {losses[-1]:.4f})")
