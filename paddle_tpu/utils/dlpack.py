"""DLPack interop (parity: python/paddle/utils/dlpack.py to_dlpack /
from_dlpack) over jax's zero-copy dlpack bridge."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor import Tensor

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Tensor -> DLPack provider (implements __dlpack__/__dlpack_device__;
    modern consumers' from_dlpack take this directly, zero-copy where the
    backend allows)."""
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def from_dlpack(capsule):
    """DLPack capsule (or __dlpack__ provider, e.g. a torch tensor) ->
    Tensor."""
    return Tensor(jnp.from_dlpack(capsule))
