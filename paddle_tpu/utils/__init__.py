"""paddle_tpu.utils — misc user utilities.

Parity: python/paddle/utils (download.py get_weights_path_from_url,
lazy_import/try_import, deprecated decorator, install_check.py run_check).
"""
from . import download  # noqa: F401
from .download import get_weights_path_from_url  # noqa: F401
from .install_check import run_check  # noqa: F401
from .lazy_import import try_import  # noqa: F401

__all__ = ["download", "get_weights_path_from_url", "try_import", "run_check",
           "deprecated", "require_version"]


def deprecated(update_to: str = "", since: str = "", reason: str = ""):
    """Decorator emitting a DeprecationWarning (parity: utils/deprecated.py)."""
    import functools
    import warnings

    def wrap(fn):
        @functools.wraps(fn)
        def inner(*a, **k):
            msg = f"API {fn.__module__}.{fn.__name__} is deprecated"
            if since:
                msg += f" since {since}"
            if update_to:
                msg += f", use {update_to} instead"
            if reason:
                msg += f" ({reason})"
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*a, **k)

        return inner

    return wrap


def require_version(min_version: str, max_version: str = None):
    """Check the installed framework version (parity: utils/__init__.py
    require_version)."""
    from .. import __version__

    def parse(v):
        return tuple(int(x) for x in v.split(".")[:3])

    cur = parse(__version__)
    if parse(min_version) > cur:
        raise Exception(
            f"version {min_version} required, installed {__version__}"
        )
    if max_version and parse(max_version) < cur:
        raise Exception(
            f"version <= {max_version} required, installed {__version__}"
        )
    return True


def __getattr__(name):
    if name in ("unique_name", "dlpack", "cpp_extension"):
        import importlib

        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    if name == "profiler":
        from .. import profiler as mod

        return mod
    raise AttributeError(name)
