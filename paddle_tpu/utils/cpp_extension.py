"""Custom C++ op building (parity: python/paddle/utils/cpp_extension/ —
CppExtension/CUDAExtension/load/setup over setuptools + nvcc).

TPU-native: custom device kernels are Pallas (ops/pallas/); this module
covers the HOST-side native extension path the reference also serves —
compile C++ to a shared library with g++ and bind via ctypes (the same
toolchain paddle_tpu/core/native uses; pybind11 is not in this image)."""
from __future__ import annotations

import os
import subprocess
import sysconfig

__all__ = ["CppExtension", "CUDAExtension", "load", "get_build_directory"]


def get_build_directory():
    d = os.environ.get("PADDLE_TPU_EXTENSION_DIR",
                       os.path.join(os.path.expanduser("~"), ".cache",
                                    "paddle_tpu_extensions"))
    os.makedirs(d, exist_ok=True)
    return d


class CppExtension:
    """Build spec for a host C++ extension (setuptools-style)."""

    def __init__(self, sources, include_dirs=None, extra_compile_args=None,
                 extra_link_args=None, name=None, **kw):
        self.sources = list(sources)
        self.include_dirs = list(include_dirs or [])
        self.extra_compile_args = list(extra_compile_args or [])
        self.extra_link_args = list(extra_link_args or [])
        self.name = name


def CUDAExtension(*args, **kwargs):  # noqa: N802
    """No CUDA on this build: device kernels are Pallas. Raises with
    direction rather than silently producing a CPU stub."""
    raise RuntimeError(
        "CUDAExtension is unavailable on the TPU build — write device "
        "kernels with Pallas (see paddle_tpu/ops/pallas) and host code via "
        "CppExtension/load")


def load(name, sources, extra_cxx_cflags=None, extra_include_paths=None,
         extra_ldflags=None, build_directory=None, verbose=False, **kw):
    """Compile C++ sources into <build_dir>/<name>.so with g++ and return a
    ctypes.CDLL handle (parity: cpp_extension.load's JIT path)."""
    import ctypes

    build_dir = build_directory or get_build_directory()
    out = os.path.join(build_dir, f"{name}.so")
    srcs = [str(s) for s in sources]
    newest_src = max(os.path.getmtime(s) for s in srcs)
    if not os.path.exists(out) or os.path.getmtime(out) < newest_src:
        cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", "-o", out]
        for inc in (extra_include_paths or []):
            cmd += ["-I", str(inc)]
        cmd += ["-I", sysconfig.get_paths()["include"]]
        cmd += (extra_cxx_cflags or [])
        cmd += srcs
        cmd += (extra_ldflags or [])
        if verbose:
            print(" ".join(cmd))
        subprocess.run(cmd, check=True, capture_output=not verbose)
    return ctypes.CDLL(out)
