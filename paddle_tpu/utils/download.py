"""Weight-file resolution (parity: python/paddle/utils/download.py).

This environment has zero network egress, so URLs resolve strictly against
the local cache (``~/.cache/paddle_tpu/weights`` or ``$PADDLE_TPU_HOME``); a
missing file raises with instructions instead of downloading.
"""
from __future__ import annotations

import hashlib
import os

__all__ = ["get_weights_path_from_url", "cache_dir"]


def cache_dir() -> str:
    root = os.environ.get(
        "PADDLE_TPU_HOME", os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu")
    )
    d = os.path.join(root, "weights")
    os.makedirs(d, exist_ok=True)
    return d


def _md5check(path: str, md5sum: str) -> bool:
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest() == md5sum


def get_weights_path_from_url(url: str, md5sum: str = None) -> str:
    fname = os.path.basename(url)
    path = os.path.join(cache_dir(), fname)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"pretrained weights {fname!r} not in local cache {cache_dir()!r} "
            "and network downloads are disabled; place the file there manually"
        )
    if md5sum and not _md5check(path, md5sum):
        raise IOError(f"md5 mismatch for cached file {path}")
    return path
