"""try_import (parity: python/paddle/utils/lazy_import.py)."""
import importlib

__all__ = ["try_import"]


def try_import(module_name: str, err_msg: str = None):
    try:
        return importlib.import_module(module_name)
    except ImportError:
        msg = err_msg or (
            f"Optional dependency {module_name!r} is required for this "
            f"feature; it is not installed in this environment"
        )
        raise ImportError(msg) from None
