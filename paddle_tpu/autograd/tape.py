"""Eager autograd tape.

Parity: the reference's dygraph engine — ``imperative::Tracer::TraceOp``
records a grad-op graph (/root/reference/paddle/fluid/imperative/tracer.cc:146,
CreateGradOpNode :236) and ``BasicEngine::Execute``
(/root/reference/paddle/fluid/imperative/basic_engine.cc:379) replays it with
ref-counted topological order and gradient accumulation
(gradient_accumulator.cc).

TPU-native redesign: instead of per-op hand-written grad kernels, each traced
op captures a ``jax.vjp`` closure (XLA computes and fuses the backward pass).
The tape is a Wengert list — reverse creation order is a valid topological
order, which replaces the reference's ref-count scheduling. Double-grad
(create_graph) re-enters the same machinery because vjp closures are
themselves traceable.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = [
    "Node",
    "no_grad",
    "enable_grad",
    "is_grad_enabled",
    "set_grad_enabled",
    "backward",
    "grad",
]


class _State(threading.local):
    def __init__(self):
        self.grad_enabled = True
        self.counter = 0


_state = _State()


def is_grad_enabled() -> bool:
    return _state.grad_enabled


def set_grad_enabled(mode: bool):
    _state.grad_enabled = bool(mode)


@contextlib.contextmanager
def no_grad():
    prev = _state.grad_enabled
    _state.grad_enabled = False
    try:
        yield
    finally:
        _state.grad_enabled = prev


@contextlib.contextmanager
def enable_grad():
    prev = _state.grad_enabled
    _state.grad_enabled = True
    try:
        yield
    finally:
        _state.grad_enabled = prev


class Node:
    """One taped op: maps output cotangents to input cotangents.

    ``vjp_fn`` is the closure returned by ``jax.vjp`` over the op's
    differentiable inputs; ``inputs`` are the input Tensors in the same order.
    ``pure_fn`` (when available) is the pure function the vjp was derived
    from — double grad re-derives the vjp through the taped-op machinery so
    the backward itself lands on the tape (parity: the reference's
    PartialGradEngine create_graph, partial_grad_engine.cc:1088, which
    re-enters TraceOp for each grad op).
    """

    __slots__ = ("vjp_fn", "inputs", "out_avals", "index", "name", "released",
                 "pure_fn", "has_aux", "tuple_out")

    def __init__(self, vjp_fn: Callable, inputs: Sequence[Any], out_avals, name: str = "",
                 pure_fn: Optional[Callable] = None, has_aux: bool = False,
                 tuple_out: Optional[bool] = None):
        self.vjp_fn = vjp_fn
        self.inputs = list(inputs)
        self.out_avals = list(out_avals)  # (shape, dtype) per output
        _state.counter += 1
        self.index = _state.counter
        self.name = name
        self.released = False
        self.pure_fn = pure_fn
        self.has_aux = has_aux
        # whether the differentiated function returned a tuple (the vjp
        # cotangent must mirror that structure exactly — a 1-tuple output
        # still needs a 1-tuple cotangent); None = infer from arity
        self.tuple_out = tuple_out

    def cot_struct(self, out_cots):
        multi = self.tuple_out if self.tuple_out is not None else len(out_cots) > 1
        return tuple(out_cots) if multi else out_cots[0]

    def __repr__(self):
        return f"<Node #{self.index} {self.name}>"


def _accumulate(t, g):
    """Accumulate cotangent g into tensor t's .grad (paddle semantics: grads
    accumulate across backward() calls until clear_grad). ``g`` may be a
    taped Tensor (create_graph): the graph is preserved on ``.grad``."""
    from ..tensor import Tensor  # local import to avoid cycle

    if isinstance(g, Tensor):
        gt = g
        if gt._data.dtype != t._data.dtype:
            from ..ops.manipulation import cast

            gt = cast(gt, t._data.dtype)  # taped cast keeps the graph
        if t._hooks:
            for h in t._hooks:
                if h is None:
                    continue
                r = h(gt)
                if r is not None:
                    gt = r
        t.grad = gt if t.grad is None else t.grad + gt
        return
    if g.dtype != t._data.dtype:
        g = g.astype(t._data.dtype)
    if t._hooks:
        for h in t._hooks:
            if h is None:
                continue
            r = h(Tensor(g, stop_gradient=True))
            if r is not None:
                g = r._data if hasattr(r, "_data") else jnp.asarray(g)
    if t.grad is None:
        t.grad = Tensor(g, stop_gradient=True)
    else:
        t.grad = Tensor(t.grad._data + g, stop_gradient=True)


def _apply_vjp(node: Node, out_cots, create_graph: bool):
    """Map output cotangents to input cotangents. With ``create_graph`` the
    vjp is re-derived THROUGH the taped-op machinery so the backward ops
    land on the tape (enabling grad-of-grad)."""
    if not create_graph:
        return node.vjp_fn(node.cot_struct(out_cots))
    if node.pure_fn is None:
        raise RuntimeError(
            f"op '{node.name}' does not record a re-differentiable function; "
            "create_graph is unavailable through it")
    from ..ops._primitive import primitive

    n_in = len(node.inputs)
    multi = node.tuple_out if node.tuple_out is not None else len(node.out_avals) > 1
    pure_fn, has_aux = node.pure_fn, node.has_aux

    @primitive(name=f"{node.name}_grad")
    def vjp_op(*args):
        prim, cots = args[:n_in], args[n_in:]
        if has_aux:
            _, f, _ = jax.vjp(pure_fn, *prim, has_aux=True)
        else:
            _, f = jax.vjp(pure_fn, *prim)
        return f(tuple(cots) if multi else cots[0])

    res = vjp_op(*node.inputs, *out_cots)
    return res if isinstance(res, tuple) else (res,)


def backward(tensor, grad_tensor=None, retain_graph: bool = False, only_into=None,
             create_graph: bool = False):
    """Run reverse-mode autodiff from ``tensor`` to all reachable leaves.

    Parity: Tensor.backward / BasicEngine. Cotangents propagate node-by-node
    in reverse creation order; leaf tensors (stop_gradient=False with no
    producing node) and retained non-leaves receive ``.grad``.

    ``only_into``: optional set of tensor ids — when given, ``.grad`` is only
    written for those tensors (used by ``grad()`` to avoid polluting other
    leaves' slots). ``create_graph``: record the backward itself on the tape
    (double grad; implies retain_graph).
    """
    from ..tensor import Tensor

    if create_graph:
        retain_graph = True

    def _wrap_cot(arr):
        return Tensor(arr, stop_gradient=True) if create_graph else arr

    def acc(t, g):
        if only_into is None or id(t) in only_into:
            _accumulate(t, g)

    if tensor._node is None:
        if not tensor.stop_gradient:
            # a leaf: d(t)/d(t) = 1
            g = jnp.ones_like(tensor._data) if grad_tensor is None else grad_tensor._data
            acc(tensor, _wrap_cot(g))
        return

    if grad_tensor is None:
        if tensor._data.size != 1:
            raise RuntimeError(
                "backward() on a non-scalar tensor requires an explicit grad_tensor"
            )
        seed_grad = _wrap_cot(jnp.ones_like(tensor._data))
    elif create_graph and isinstance(grad_tensor, Tensor):
        seed_grad = grad_tensor  # keep any graph on the seed
    else:
        seed_grad = _wrap_cot(
            grad_tensor._data if hasattr(grad_tensor, "_data") else jnp.asarray(grad_tensor))

    # Gather reachable subgraph. Any released node in the cone means the
    # graph was freed by a prior backward() — error, like the reference
    # engine (basic_engine.cc asserts grad-op buffers are live).
    nodes = {}
    stack = [tensor._node]
    while stack:
        n = stack.pop()
        if n.index in nodes:
            continue
        if n.released:
            raise RuntimeError(
                "Trying to backward through a graph that has already been "
                "freed; pass retain_graph=True to the first backward() call"
            )
        nodes[n.index] = n
        for inp in n.inputs:
            if inp._node is not None:
                stack.append(inp._node)

    # cotangent buckets: keyed by (node index, out position) for op outputs.
    cots = {}
    cots[(tensor._node.index, tensor._out_idx)] = seed_grad

    for idx in sorted(nodes, reverse=True):
        node = nodes[idx]
        out_cots = []
        any_seen = False
        for pos, (shape, dt) in enumerate(node.out_avals):
            g = cots.pop((idx, pos), None)
            if g is None:
                g = _wrap_cot(jnp.zeros(shape, dt))
            else:
                any_seen = True
            out_cots.append(g)
        if not any_seen:
            continue
        in_cots = _apply_vjp(node, out_cots, create_graph)
        for inp, g in zip(node.inputs, in_cots):
            if g is None or inp.stop_gradient:
                continue
            if inp._node is not None:
                k = (inp._node.index, inp._out_idx)
                if inp._retain_grad:
                    acc(inp, g)
                cots[k] = g if k not in cots else cots[k] + g
            else:
                acc(inp, g)
        if not retain_graph:
            node.released = True
            node.vjp_fn = None
            node.inputs = []


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph: Optional[bool] = None,
    create_graph: bool = False,
    allow_unused: bool = False,
):
    """``paddle.grad`` parity (reference: imperative/partial_grad_engine.cc).

    Computes grads of ``outputs`` w.r.t. ``inputs`` without touching ``.grad``
    slots of other leaves. ``create_graph=True`` records the backward pass
    itself on the tape (the returned grads carry grad history), enabling
    double grad exactly like the reference's PartialGradEngine
    (partial_grad_engine.cc:1088, matmul_v2_grad_grad etc.).
    """
    from ..tensor import Tensor

    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    grad_outputs = grad_outputs if isinstance(grad_outputs, (list, tuple)) else [grad_outputs]
    retain = (True if retain_graph is None else retain_graph) or create_graph

    # Temporarily swap .grad slots, run backward, harvest, restore.
    saved = [(t, t.grad, t._retain_grad) for t in inputs]
    for t in inputs:
        t.grad = None
        t._retain_grad = True
    wanted = {id(t) for t in inputs}
    try:
        for o, go in zip(outputs, grad_outputs):
            backward(o, go, retain_graph=retain, only_into=wanted,
                     create_graph=create_graph)
        results = []
        for t in inputs:
            if t.grad is None:
                if not allow_unused:
                    raise RuntimeError(
                        "One of the differentiated tensors appears unused; "
                        "pass allow_unused=True to return None for it"
                    )
                results.append(None)
            elif create_graph:
                results.append(t.grad)  # keep the recorded backward graph
            else:
                results.append(Tensor(t.grad._data, stop_gradient=True))
    finally:
        for t, g, r in saved:
            t.grad, t._retain_grad = g, r
    return results
