"""PyLayer: user-defined eager ops with custom backward.

Parity: ``paddle.autograd.PyLayer`` (reference
python/paddle/autograd/py_layer.py; C++ side py_layer_op
/root/reference/paddle/fluid/operators/py_layer_op.cc).

TPU-native redesign: the reference routes custom backward through a dedicated
``py_layer`` operator holding Python callables. Here a PyLayer is just a tape
Node whose vjp closure calls the user's ``backward`` — no operator machinery.
The forward runs eagerly under ``no_grad`` (its internal graph is discarded;
only the user-provided backward defines the derivative), exactly matching the
reference's semantics where forward ops are not double-recorded.
"""
from __future__ import annotations

from typing import Any

import jax.numpy as jnp

from . import tape

__all__ = ["PyLayer", "PyLayerContext"]


class PyLayerContext:
    """Context passed to forward/backward; carries saved tensors and any
    user attributes (parity: PyLayerContext.save_for_backward/saved_tensor)."""

    def __init__(self):
        self._saved = ()

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    def saved_tensor(self):
        return self._saved


def _is_tensor(x) -> bool:
    from ..tensor import Tensor

    return isinstance(x, Tensor)


class PyLayer:
    """Subclass with ``forward(ctx, *args)`` and ``backward(ctx, *grads)``
    staticmethods; call via ``MyLayer.apply(*args)``.

    ``backward`` must return one gradient (or ``None``) per Tensor argument of
    ``forward``, in order — the reference enforces the same contract.
    """

    @staticmethod
    def forward(ctx: PyLayerContext, *args: Any, **kwargs: Any):
        raise NotImplementedError(
            "PyLayer subclasses must implement a forward staticmethod"
        )

    @staticmethod
    def backward(ctx: PyLayerContext, *grads: Any):
        raise NotImplementedError(
            "PyLayer subclasses must implement a backward staticmethod"
        )

    @classmethod
    def apply(cls, *args: Any, **kwargs: Any):
        from ..tensor import Tensor

        ctx = PyLayerContext()
        tensor_inputs = [a for a in args if _is_tensor(a)] + [
            v for v in kwargs.values() if _is_tensor(v)
        ]

        with tape.no_grad():
            outs = cls.forward(ctx, *args, **kwargs)

        multi_out = isinstance(outs, (tuple, list))
        out_seq = list(outs) if multi_out else [outs]
        tensor_out_pos = [i for i, o in enumerate(out_seq) if _is_tensor(o)]
        if not tensor_out_pos:
            raise ValueError("PyLayer.forward must return at least one Tensor")

        need_grad = tape.is_grad_enabled() and any(
            not t.stop_gradient and jnp.issubdtype(t._data.dtype, jnp.inexact)
            for t in tensor_inputs
        )
        if not need_grad:
            return outs if multi_out else out_seq[0]

        n_outs = len(tensor_out_pos)

        def vjp_fn(cots):
            cot_seq = cots if isinstance(cots, tuple) else (cots,)
            grad_args = [Tensor(g, stop_gradient=True) for g in cot_seq]
            with tape.no_grad():
                grads = cls.backward(ctx, *grad_args)
            grads = grads if isinstance(grads, (tuple, list)) else (grads,)
            if len(grads) != len(tensor_inputs):
                raise ValueError(
                    f"{cls.__name__}.backward returned {len(grads)} gradients "
                    f"for {len(tensor_inputs)} Tensor inputs"
                )
            return tuple(
                None if g is None else (g._data if _is_tensor(g) else jnp.asarray(g))
                for g in grads
            )

        node = tape.Node(
            vjp_fn,
            tensor_inputs,
            [(out_seq[i]._data.shape, out_seq[i]._data.dtype) for i in tensor_out_pos],
            name=f"py_layer:{cls.__name__}",
        )
        for pos, i in enumerate(tensor_out_pos):
            t = Tensor(out_seq[i]._data, stop_gradient=False)
            t._node = node
            t._out_idx = pos
            out_seq[i] = t

        if not multi_out:
            return out_seq[0]
        return tuple(out_seq) if isinstance(outs, tuple) else out_seq
