"""Functional higher-order autodiff: vjp / jvp / jacobian / hessian.

Parity: ``paddle.autograd`` functional API (reference
python/paddle/autograd/functional.py — vjp:30, jvp:94, jacobian:164,
hessian:310).

TPU-native redesign: the reference double-differentiates its eager grad-op
graph; here the user function (built from framework ops) is lifted to a pure
jax function and jax's composable transforms (``jax.vjp``/``jvp``/``jacrev``/
``jacfwd``) supply the derivatives, so arbitrary-order nesting works and XLA
compiles the whole thing.
"""
from __future__ import annotations

from typing import Callable, Sequence, Union

import jax
import jax.numpy as jnp

__all__ = ["vjp", "jvp", "jacobian", "hessian"]


def _as_list(xs) -> list:
    return list(xs) if isinstance(xs, (list, tuple)) else [xs]


def _unwrap(x):
    from ..tensor import Tensor

    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _wrap(a):
    from ..tensor import Tensor

    return Tensor(a, stop_gradient=True)


def _lift(func: Callable, n_in: int):
    """Lift a Tensor->Tensor function to a pure jax-array function."""
    from .. import autograd
    from ..tensor import Tensor

    def pure(*arrs):
        with autograd.no_grad():
            ts = [Tensor(a, stop_gradient=True) for a in arrs]
            out = func(*ts) if n_in > 1 else func(ts[0])
        outs = _as_list(out)
        arrs_out = [_unwrap(o) for o in outs]
        return tuple(arrs_out) if isinstance(out, (list, tuple)) else arrs_out[0]

    return pure


def _wrap_like(arrs, template):
    if isinstance(template, (list, tuple)):
        return tuple(_wrap(a) for a in arrs)
    return _wrap(arrs[0] if isinstance(arrs, (list, tuple)) else arrs)


def vjp(func: Callable, xs, v=None):
    """Vector-Jacobian product. Returns ``(func(xs), vjp_result)``.

    ``v`` defaults to ones like the (single) output, matching the reference.
    """
    xs_list = _as_list(xs)
    arrs = [_unwrap(x) for x in xs_list]
    pure = _lift(func, len(xs_list))
    out, vjp_fn = jax.vjp(pure, *arrs)

    if v is None:
        cot = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        v_list = _as_list(v)
        cot = tuple(_unwrap(g) for g in v_list)
        if not isinstance(out, tuple):
            cot = cot[0]
    grads = vjp_fn(cot)
    out_t = jax.tree_util.tree_map(_wrap, out)
    grads_t = _wrap_like(grads, xs)
    return out_t, grads_t


def jvp(func: Callable, xs, v=None):
    """Jacobian-vector product (forward mode). Returns ``(func(xs), jvp)``."""
    xs_list = _as_list(xs)
    arrs = [_unwrap(x) for x in xs_list]
    pure = _lift(func, len(xs_list))
    if v is None:
        tangents = tuple(jnp.ones_like(a) for a in arrs)
    else:
        tangents = tuple(_unwrap(t) for t in _as_list(v))
    out, tangent_out = jax.jvp(pure, tuple(arrs), tangents)
    return (
        jax.tree_util.tree_map(_wrap, out),
        jax.tree_util.tree_map(_wrap, tangent_out),
    )


def jacobian(func: Callable, xs, create_graph: bool = False, allow_unused: bool = False):
    """Jacobian of ``func`` at ``xs`` via reverse mode.

    Single input, single output → a Tensor of shape ``out.shape + in.shape``.
    Multiple inputs and/or outputs → nested tuples, reference layout.
    """
    xs_list = _as_list(xs)
    arrs = [_unwrap(x) for x in xs_list]
    pure = _lift(func, len(xs_list))
    jac = jax.jacrev(pure, argnums=tuple(range(len(arrs))))(*arrs)

    probe = jax.eval_shape(pure, *arrs)
    multi_out = isinstance(probe, tuple)
    multi_in = isinstance(xs, (list, tuple))

    if not multi_out:
        jac = (jac,)
    rows = []
    for per_out in jac:  # per output: tuple over inputs
        per_out = per_out if isinstance(per_out, tuple) else (per_out,)
        cols = tuple(_wrap(j) for j in per_out)
        rows.append(cols if multi_in else cols[0])
    if not multi_out:
        return rows[0]
    return tuple(rows)


def hessian(func: Callable, xs, create_graph: bool = False, allow_unused: bool = False):
    """Hessian of a scalar-output ``func`` at ``xs`` (fwd-over-rev)."""
    xs_list = _as_list(xs)
    arrs = [_unwrap(x) for x in xs_list]
    pure = _lift(func, len(xs_list))

    def scalar(*a):
        out = pure(*a)
        out0 = out[0] if isinstance(out, tuple) else out
        if out0.size != 1:
            raise ValueError("hessian requires a scalar-output function")
        return out0.reshape(())

    hes = jax.jacfwd(jax.jacrev(scalar, argnums=tuple(range(len(arrs)))),
                     argnums=tuple(range(len(arrs))))(*arrs)
    multi_in = isinstance(xs, (list, tuple))
    if not multi_in:
        return _wrap(hes[0][0])
    return tuple(tuple(_wrap(h) for h in row) for row in hes)
