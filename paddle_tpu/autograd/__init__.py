"""paddle_tpu.autograd — eager autodiff (tape), PyLayer, functional API."""
from .tape import (  # noqa: F401
    backward,
    enable_grad,
    grad,
    is_grad_enabled,
    no_grad,
    set_grad_enabled,
)

__all__ = [
    "backward",
    "grad",
    "no_grad",
    "enable_grad",
    "is_grad_enabled",
    "set_grad_enabled",
    "PyLayer",
    "PyLayerContext",
]


def __getattr__(name):
    # PyLayer / functional live in submodules that import ops; load lazily to
    # keep the core import graph acyclic.
    if name in ("PyLayer", "PyLayerContext"):
        from . import py_layer

        return getattr(py_layer, name)
    if name in ("jacobian", "hessian", "vjp", "jvp"):
        from . import functional

        return getattr(functional, name)
    raise AttributeError(name)
