"""Point-to-point primitives over mesh axes.

Parity: send_v2/recv_v2 + partial_send/partial_recv
(/root/reference/paddle/fluid/operators/collective/send_v2_op.cc,
python/paddle/distributed/fleet/meta_parallel/pp_utils/p2p_communication.py).

TPU-native: p2p is ``lax.ppermute`` over the 'pp' axis — XLA lowers it to a
collective-permute on ICI. Under SPMD there is no asymmetric send/recv; both
sides participate in one permute, which is how the pipeline schedule is
expressed (one fused program instead of paired NCCL calls).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..tensor import Tensor
from .group import Group, get_default_group

__all__ = ["shift", "ppermute_to", "ppermute_from", "send_recv_forward", "send_recv_backward"]


def _axis(group: Optional[Group]):
    g = group or get_default_group()
    return g.axis_name


def _axis_n(axis_name):
    return lax.axis_size(axis_name)


def shift(x, offset: int = 1, group: Optional[Group] = None, wrap: bool = True):
    """Rotate values along the group axis: rank r's value goes to r+offset.

    The pipeline forward pass is shift(+1); backward is shift(-1). With
    wrap=False the wrapped-around entry is zeroed (edge stages ignore it).
    """
    axis_name = _axis(group)
    arr = x._data if isinstance(x, Tensor) else x
    n = _axis_n(axis_name)
    perm = [(i, (i + offset) % n) for i in range(n)]
    out = lax.ppermute(arr, axis_name, perm)
    if not wrap:
        idx = lax.axis_index(axis_name)
        if offset > 0:
            mask = idx >= offset
        else:
            mask = idx < n + offset
        out = jnp.where(mask, out, jnp.zeros_like(out))
    return Tensor(out) if isinstance(x, Tensor) else out


def ppermute_to(x, dst: int, group: Optional[Group] = None):
    """All ranks contribute; only rank src→dst edge carries data (send_v2)."""
    axis_name = _axis(group)
    arr = x._data if isinstance(x, Tensor) else x
    n = _axis_n(axis_name)
    idx = lax.axis_index(axis_name)
    # a permutation ring through dst: r -> dst for this rank is not a
    # permutation; use gather-at-dst semantics instead
    gathered = lax.all_gather(arr, axis_name)
    out = jnp.where(idx == dst, gathered[idx], arr)
    return Tensor(out) if isinstance(x, Tensor) else out


def ppermute_from(x, src: int, group: Optional[Group] = None):
    """recv_v2: every rank reads src's value (SPMD superset of p2p recv)."""
    axis_name = _axis(group)
    arr = x._data if isinstance(x, Tensor) else x
    gathered = lax.all_gather(arr, axis_name)
    out = gathered[src]
    return Tensor(out) if isinstance(x, Tensor) else out


def send_recv_forward(x, group=None):
    """1F1B steady-state helper: pass activations to the next stage."""
    return shift(x, 1, group, wrap=False)


def send_recv_backward(g, group=None):
    """Pass gradients to the previous stage."""
    return shift(g, -1, group, wrap=False)
