"""Parallel environment + global mesh bootstrap.

Parity: ParallelEnv (fluid/dygraph/parallel.py:72 — reads PADDLE_TRAINER_ID /
PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS set by the launcher,
launch_utils.py:490-501) and init_parallel_env (distributed/parallel.py).

TPU-native: multi-host bootstrap is jax.distributed.initialize (the TPU
runtime rendezvous replaces the reference's TCP nccl-id exchange). The global
**device mesh** is process-wide state: every parallelism axis (dp/fsdp/mp/pp/
sp/ep) lives on one jax.sharding.Mesh created here.
"""
from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np

__all__ = [
    "ParallelEnv",
    "init_parallel_env",
    "get_rank",
    "get_world_size",
    "get_mesh",
    "set_mesh",
    "init_mesh",
    "clear_mesh",
]

_global_mesh = None
_initialized = False


class ParallelEnv:
    """Reads the launcher env contract (Appendix B of SURVEY)."""

    def __init__(self):
        self._rank = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        self._world_size = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
        self._device_id = int(os.getenv("FLAGS_selected_tpus", os.getenv("FLAGS_selected_gpus", "0")).split(",")[0])
        self._current_endpoint = os.getenv("PADDLE_CURRENT_ENDPOINT", "")
        self._trainer_endpoints = os.getenv("PADDLE_TRAINER_ENDPOINTS", "").split(",")

    @property
    def rank(self):
        return self._rank

    @property
    def world_size(self):
        return self._world_size

    @property
    def device_id(self):
        return self._device_id

    @property
    def current_endpoint(self):
        return self._current_endpoint

    @property
    def trainer_endpoints(self):
        return self._trainer_endpoints

    # legacy aliases
    local_rank = rank
    nranks = world_size
    dev_id = device_id


def init_parallel_env():
    """Initialize multi-process jax (multi-host TPU pods) if the launcher env
    says we're one of several processes; otherwise single-controller mode."""
    global _initialized
    if _initialized:
        return ParallelEnv()
    env = ParallelEnv()
    if env.world_size > 1 and os.getenv("PADDLE_TPU_SINGLE_CONTROLLER", "0") != "1":
        import jax

        coordinator = env.trainer_endpoints[0] if env.trainer_endpoints[0] else None
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=env.world_size,
            process_id=env.rank,
        )
    from .group import Group, _set_default_group

    _set_default_group(Group(id=0, axis_name=None))
    _initialized = True
    return env


def get_rank(group=None) -> int:
    import jax

    if group is not None and group.ranks:
        return group.get_group_rank(ParallelEnv().rank)
    try:
        return jax.process_index()
    except Exception:
        return ParallelEnv().rank


def get_world_size(group=None) -> int:
    if group is not None:
        return group.nranks
    env_n = ParallelEnv().world_size
    if env_n > 1:
        return env_n
    import jax

    try:
        return jax.process_count() if jax.process_count() > 1 else 1
    except Exception:
        return 1


# ---------------------------------------------------------------------------
# the global mesh
# ---------------------------------------------------------------------------


def init_mesh(axes: Dict[str, int], devices=None):
    """Create + install the global mesh, e.g. init_mesh({'dp': 2, 'mp': 4}).

    Axis order is layout-significant: later axes are placed on
    faster/closer device dimensions (keep 'mp' innermost so tensor-parallel
    collectives ride the fastest ICI links, like the reference's ring order
    in fleet/base/topology.py).
    """
    import jax
    from jax.sharding import Mesh

    global _global_mesh
    if devices is None:
        devices = np.array(jax.devices())
    total = int(np.prod(list(axes.values())))
    if total > len(np.ravel(devices)):
        raise ValueError(f"mesh needs {total} devices, have {len(np.ravel(devices))}")
    dev_grid = np.array(np.ravel(devices)[:total]).reshape(tuple(axes.values()))
    _global_mesh = Mesh(dev_grid, tuple(axes.keys()))
    return _global_mesh


def set_mesh(mesh):
    global _global_mesh
    _global_mesh = mesh
    return mesh


def clear_mesh():
    """Uninstall the global mesh (tests / re-init)."""
    global _global_mesh
    _global_mesh = None


def get_mesh():
    return _global_mesh


def _axis_size(axis_name: str) -> int:
    if _global_mesh is None or axis_name not in _global_mesh.shape:
        return 1
    return int(_global_mesh.shape[axis_name])
