"""Meta-optimizer strategies: DGC and LocalSGD.

Parity: fleet meta-optimizers (reference
python/paddle/distributed/fleet/meta_optimizers/dgc_optimizer.py +
operators/optimizers/dgc_momentum_op.* and dgc_op.*;
localsgd_optimizer.py LocalSGDOptimizer:28 / AdaptiveLocalSGDOptimizer:234).

TPU-native notes:
- DGC (Deep Gradient Compression): the reference sparsifies grads before
  NCCL allreduce to save bandwidth. Under GSPMD, XLA owns the collective, so
  the *compression semantics* (momentum correction, residual accumulation,
  top-k masking with warmup ramp, dgc_momentum_op update rule) are kept as a
  pure optimizer update — masked components accumulate locally and release
  later exactly as in the reference; bandwidth shaping is delegated to XLA.
- LocalSGD: workers run k local steps then average parameters over the 'dp'
  mesh axis (one pmean per sync instead of per-step gradient allreduce).
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ...optimizer.optimizer import Optimizer

__all__ = ["DGCMomentum", "LocalSGDOptimizer", "AdaptiveLocalSGDOptimizer"]


class DGCMomentum(Optimizer):
    """Momentum with Deep Gradient Compression (dgc_momentum_op parity).

    Update rule (reference dgc_op.cc semantics):
        u = m * u + g                  (momentum correction)
        v = v + u                      (residual accumulation)
        mask = |v| >= top-(1-s) quantile
        g_comm = v * mask;  v = v * (1 - mask)
        p = p - lr * g_comm
    Sparsity ``s`` ramps from ``sparsity[0]`` to ``sparsity[-1]`` over
    ``rampup_step`` steps starting at ``rampup_begin_step``; before the ramp
    begins the update is plain (dense) momentum.
    """

    _slot_names = ("u", "v")

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, rampup_begin_step=0, rampup_step=1,
                 sparsity=(0.999,), weight_decay=None, grad_clip=None,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = float(momentum)
        self._use_nesterov = bool(use_nesterov)
        self._rampup_begin = int(rampup_begin_step)
        self._rampup_step = max(int(rampup_step), 1)
        self._sparsity = tuple(float(s) for s in sparsity)

    def _hyper(self):
        return (self._momentum, self._use_nesterov, self._rampup_begin,
                self._rampup_step, self._sparsity)

    @staticmethod
    def _update(p, g, slots, lr, step, hyper):
        mu, nesterov, begin, ramp, sparsity = hyper
        u = mu * slots["u"] + g
        v = slots["v"] + (g + mu * u if nesterov else u)
        dense_phase = step <= begin

        def dense(_):
            # plain momentum: whole v releases each step
            return v, jnp.zeros_like(v), u

        def sparse(_):
            # sparsity schedule (trace-time shapes, runtime step)
            frac = jnp.clip((step - begin).astype(jnp.float32) / ramp, 0.0, 1.0)
            levels = jnp.asarray(sparsity, jnp.float32)
            idx = jnp.minimum(
                (frac * (len(sparsity) - 1)).astype(jnp.int32), len(sparsity) - 1
            ) if len(sparsity) > 1 else jnp.int32(0)
            s = levels[idx]
            flat = jnp.abs(v.reshape(-1)).astype(jnp.float32)
            thresh = jnp.quantile(flat, jnp.clip(s, 0.0, 1.0))
            mask = jnp.abs(v) >= thresh.astype(v.dtype)
            # send masked v; residual stays; momentum factor masking zeroes
            # u where v was sent (DGC paper §3.2)
            return (jnp.where(mask, v, 0),
                    jnp.where(mask, jnp.zeros_like(v), v),
                    jnp.where(mask, jnp.zeros_like(u), u))

        # lax.cond keeps the quantile sort out of the dense warmup phase
        g_comm, v_new, u_new = jax.lax.cond(dense_phase, dense, sparse, None)
        p_new = p - lr.astype(p.dtype) * g_comm
        return p_new, {"u": u_new, "v": v_new}


class LocalSGDOptimizer:
    """Run ``k_steps`` local updates, then average parameters over the data-
    parallel mesh axis (parity: localsgd_optimizer.py:28).

    Wraps any inner optimizer; transparent before ``begin_step``.
    """

    def __init__(self, inner, k_steps: int = 1, begin_step: int = 1,
                 dp_axis: str = "dp"):
        self._inner = inner
        self.k_steps = max(int(k_steps), 1)
        self.begin_step = int(begin_step)
        self.dp_axis = dp_axis
        self._step_count = 0
        self._sync_fn = None  # jitted averager, built once (no per-sync retrace)

    # -- sync -----------------------------------------------------------
    def _world(self) -> int:
        from ..env import get_mesh

        mesh = get_mesh()
        if mesh is None or self.dp_axis not in mesh.shape:
            return 1
        return int(mesh.shape[self.dp_axis])

    def _sync_params(self):
        if self._world() <= 1:
            return
        params = [p for p in self._inner._param_groups]
        if self._sync_fn is None:
            from jax.sharding import PartitionSpec as P

            from ..spmd import run_on_mesh

            spec = tuple(P() for _ in params)
            axis = self.dp_axis

            def avg(*xs):
                return tuple(jax.lax.pmean(x, axis) for x in xs)

            self._sync_fn = run_on_mesh(avg, in_specs=spec, out_specs=spec)
        out = self._sync_fn(*[p._data for p in params])
        for p, a in zip(params, out):
            p._set_data(a)

    def _current_k(self) -> int:
        return self.k_steps

    # -- optimizer surface ---------------------------------------------
    def step(self):
        self._inner.step()
        self._step_count += 1
        if self._step_count >= self.begin_step and \
                self._step_count % self._current_k() == 0:
            self._sync_params()

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        return None, []

    # -- functional (jitted GSPMD) surface -------------------------------
    # Under the pjit trainer, params are replicated and XLA averages grads
    # every step — exact synchronous SGD, i.e. LocalSGD with k=1. The
    # divergent-replica optimization (skipping per-step reduce) is only
    # expressible in the eager ``.step()`` loop, so the functional path
    # delegates to the inner optimizer and says so once instead of silently
    # pretending k_steps applies.
    def init_state(self, params_tree):
        if self.k_steps > 1:
            import warnings

            warnings.warn(
                "LocalSGD k_steps>1 only affects the eager .step() loop; the "
                "jitted GSPMD trainer averages gradients every step (exact "
                "sync-SGD, k=1). Proceeding with the inner optimizer.",
                stacklevel=2)
        return self._inner.init_state(params_tree)

    def apply_gradients(self, params_tree, grads_tree, state, lr=None):
        return self._inner.apply_gradients(params_tree, grads_tree, state, lr=lr)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class AdaptiveLocalSGDOptimizer(LocalSGDOptimizer):
    """LocalSGD with loss-adaptive sync interval (parity:
    localsgd_optimizer.py AdaptiveLocalSGDOptimizer:234 — the reference picks
    k from the ratio of initial to current loss; lower loss → larger k)."""

    def __init__(self, inner, init_k_steps: int = 1, begin_step: int = 1,
                 max_k_steps: int = 16, dp_axis: str = "dp"):
        super().__init__(inner, init_k_steps, begin_step, dp_axis)
        self.init_k_steps = int(init_k_steps)
        self.max_k_steps = int(max_k_steps)
        self._loss0: Optional[float] = None
        self._last_loss: Optional[float] = None

    def record_loss(self, loss):
        """Feed the latest loss so k can adapt. ``minimize`` does this
        automatically; ``.step()``-style loops should call it each step."""
        val = float(loss)
        if self._loss0 is None:
            self._loss0 = max(val, 1e-12)
        self._last_loss = val

    def minimize(self, loss, **kw):
        self.record_loss(loss)
        return super().minimize(loss, **kw)

    def _current_k(self) -> int:
        if self._loss0 is None or self._last_loss is None or self._last_loss <= 0:
            return self.init_k_steps
        k = int(math.sqrt(self._loss0 / self._last_loss) * self.init_k_steps)
        return max(1, min(k, self.max_k_steps))
