"""Collective communication ops.

Parity: the reference's operators/collective/ op set (SURVEY §2.6):
c_allreduce_{sum,max,min,prod}, c_allgather, c_reducescatter, c_broadcast,
c_scatter, alltoall, send_v2/recv_v2, barrier, c_concat, c_split, and the MoE
pair global_scatter/global_gather.

TPU-native dual mode per op:
- **inside shard_map** (arrays carry a bound axis name): lowers to the XLA
  collective (lax.psum / all_gather / psum_scatter / all_to_all / ppermute)
  over the group's mesh axis — this is the production path; XLA schedules it
  on ICI with no stream-sync ops (replacing c_sync_comm_stream etc.).
- **eager, single process**: world_size==1 → identity (same as the reference
  when nranks==1); world>1 eager is routed through a jitted shard_map over
  the global mesh when the tensor is sharded over the group axis.

EAGER SEMANTICS FOR UNSHARDED TENSORS (world > 1) — read this before
porting reference eager-collective code: with one controller process there
is exactly one copy of an unsharded tensor, so "each rank's tensor"
degenerates to the replicated-eager model (every virtual rank holds the
SAME value). Ops whose replicated closed form is exact run it:
all_reduce(x) = world * x for SUM (each rank contributed the same x),
all_gather = tile, broadcast = identity. Ops whose outputs would be
rank-divergent (reduce_scatter slices, scatter, alltoall) CANNOT exist in
this model and raise a teachable RuntimeError directing you to
shard_map/run_on_mesh, where each shard genuinely is a rank. This differs
from the reference's c_allreduce on a multi-process launch, where ranks
hold independent values — that situation is expressed here by sharding
the tensor over the group axis (then the op lowers to the XLA collective).
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..tensor import Tensor
from .group import Group, ReduceOp, get_default_group

__all__ = [
    "all_reduce",
    "all_gather",
    "reduce_scatter",
    "broadcast",
    "reduce",
    "scatter",
    "alltoall",
    "alltoall_single",
    "send",
    "recv",
    "barrier",
    "wait",
    "split_group_axis",
]


def _axis(group: Optional[Group]):
    g = group or get_default_group()
    return g.axis_name


def _in_trace(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _unwrap(t):
    return t._data if isinstance(t, Tensor) else t


def _rewrap(t, arr):
    if isinstance(t, Tensor):
        t._set_data(arr)
        return t
    return arr


def _axis_bound(axis_name) -> bool:
    """True when we're tracing inside shard_map/pmap with this axis bound."""
    if axis_name is None:
        return False
    try:
        lax.axis_index(axis_name)
        return True
    except NameError:
        return False
    except Exception:
        return False


def all_reduce(tensor, op=ReduceOp.SUM, group: Optional[Group] = None, sync_op: bool = True, use_calc_stream: bool = None):
    """c_allreduce_* parity (c_allreduce_op.h).

    Eager semantics under the single-controller model: an eager tensor is
    REPLICATED across the group's virtual ranks (there is one Python
    program), so the reduction has a closed form — sum = n*x, max/min/avg =
    x, prod = x**n. This makes the reference's dygraph metric-reduction
    idiom (`all_reduce(loss); loss /= nranks`) exact. Rank-divergent data
    lives in sharded arrays and reduces inside shard_map (the bound-axis
    path)."""
    axis = _axis(group)
    x = _unwrap(tensor)
    if _axis_bound(axis):
        if op == ReduceOp.SUM:
            out = lax.psum(x, axis)
        elif op == ReduceOp.MAX:
            out = lax.pmax(x, axis)
        elif op == ReduceOp.MIN:
            out = lax.pmin(x, axis)
        elif op == ReduceOp.AVG:
            out = lax.pmean(x, axis)
        elif op == ReduceOp.PROD:
            out = jnp.exp(lax.psum(jnp.log(x.astype(jnp.float32)), axis)).astype(x.dtype)
        else:
            raise ValueError(f"bad op {op}")
        return _rewrap(tensor, out)
    n = (group or get_default_group()).nranks
    if n <= 1:
        return tensor
    if op == ReduceOp.SUM:
        return _rewrap(tensor, x * n)
    if op in (ReduceOp.MAX, ReduceOp.MIN, ReduceOp.AVG):
        return tensor
    if op == ReduceOp.PROD:
        return _rewrap(tensor, x**n)
    raise ValueError(f"bad op {op}")


def reduce(tensor, dst: int = 0, op=ReduceOp.SUM, group=None, sync_op=True):
    """c_reduce_* parity: allreduce then non-dst ranks keep local (SPMD can't
    have divergent outputs, so every rank gets the reduced value — a superset
    of the reference semantics that downstream code tolerates)."""
    return all_reduce(tensor, op, group, sync_op)


def all_gather(tensor_or_list, tensor=None, group=None, sync_op=True, axis: int = 0):
    """c_allgather parity. Two call forms: paddle's
    all_gather(out_list, tensor) and functional all_gather(tensor)->stacked."""
    if isinstance(tensor_or_list, list):
        out = all_gather(tensor, group=group, axis=axis)
        n = (group or get_default_group()).nranks
        parts = jnp.split(_unwrap(out), n, axis=axis)
        tensor_or_list.clear()
        tensor_or_list.extend(Tensor(p) for p in parts)
        return tensor_or_list
    x = _unwrap(tensor_or_list)
    ax_name = _axis(group)
    if _axis_bound(ax_name):
        out = lax.all_gather(x, ax_name, axis=axis, tiled=True)
        return _rewrap(tensor_or_list, out) if not isinstance(tensor_or_list, Tensor) else Tensor(out)
    n = (group or get_default_group()).nranks
    if n <= 1:
        return tensor_or_list
    # replicated-eager: every virtual rank holds the same tensor, so the
    # gather is n tiled copies (exact under the single-controller model)
    out = jnp.concatenate([x] * n, axis=axis)
    # list inputs (tensor_list out-param form) were handled above; a raw
    # array input gets the gathered array back, same as the axis-bound path
    return Tensor(out) if isinstance(tensor_or_list, Tensor) else out


def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM, group=None, sync_op=True, axis: int = 0):
    """c_reducescatter parity."""
    x = _unwrap(tensor if tensor_list is None else tensor_list)
    ax_name = _axis(group)
    if _axis_bound(ax_name):
        out = lax.psum_scatter(x, ax_name, scatter_dimension=axis, tiled=True)
        return Tensor(out) if isinstance(tensor, Tensor) else out
    if (group or get_default_group()).nranks <= 1:
        return tensor
    raise RuntimeError(
        "eager reduce_scatter: " + 'rank-divergent outputs cannot exist in replicated-eager mode (one controller); run inside shard_map/run_on_mesh where each shard is a rank')


def broadcast(tensor, src: int = 0, group=None, sync_op=True):
    """c_broadcast parity: under SPMD every shard takes src's value."""
    x = _unwrap(tensor)
    ax_name = _axis(group)
    if _axis_bound(ax_name):
        # select src's shard and broadcast it: all_gather then index src
        gathered = lax.all_gather(x, ax_name)  # [n, ...]
        out = gathered[src]
        return _rewrap(tensor, out)
    # replicated-eager: every virtual rank already holds src's value
    return tensor


def scatter(tensor, tensor_list=None, src: int = 0, group=None, sync_op=True):
    """c_scatter parity: src's list entry i goes to rank i."""
    ax_name = _axis(group)
    if tensor_list is not None and _axis_bound(ax_name):
        stacked = jnp.stack([_unwrap(t) for t in tensor_list])
        idx = lax.axis_index(ax_name)
        out = stacked[idx]
        return _rewrap(tensor, out)
    if (group or get_default_group()).nranks <= 1:
        if tensor_list is not None:
            return _rewrap(tensor, _unwrap(tensor_list[0]))
        return tensor
    raise RuntimeError(
        "eager scatter: " + 'rank-divergent outputs cannot exist in replicated-eager mode (one controller); run inside shard_map/run_on_mesh where each shard is a rank')


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    """alltoall parity: rank r sends in[i] to rank i; receives into out[r]."""
    ax_name = _axis(group)
    if isinstance(in_tensor_list, (list, tuple)):
        x = jnp.stack([_unwrap(t) for t in in_tensor_list])
    else:
        x = _unwrap(in_tensor_list)
    if _axis_bound(ax_name):
        out = lax.all_to_all(x, ax_name, split_axis=0, concat_axis=0, tiled=False)
        if isinstance(in_tensor_list, (list, tuple)):
            parts = [Tensor(out[i]) for i in range(out.shape[0])]
            if out_tensor_list is not None:
                out_tensor_list.clear()
                out_tensor_list.extend(parts)
                return out_tensor_list
            return parts
        return out
    if (group or get_default_group()).nranks <= 1:
        return in_tensor_list
    raise RuntimeError(
        "eager alltoall: " + 'rank-divergent outputs cannot exist in replicated-eager mode (one controller); run inside shard_map/run_on_mesh where each shard is a rank')


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None, out_split_sizes=None, group=None, sync_op=True):
    x = _unwrap(in_tensor)
    ax_name = _axis(group)
    if _axis_bound(ax_name):
        n = lax.axis_size(ax_name)
        parts = x.reshape((n, x.shape[0] // n) + x.shape[1:])
        out = lax.all_to_all(parts, ax_name, split_axis=0, concat_axis=0, tiled=True)
        out = out.reshape(x.shape)
        if out_tensor is not None:
            return _rewrap(out_tensor, out)
        return Tensor(out)
    if (group or get_default_group()).nranks <= 1:
        return in_tensor
    raise RuntimeError(
        "eager alltoall_single: " + 'rank-divergent outputs cannot exist in replicated-eager mode (one controller); run inside shard_map/run_on_mesh where each shard is a rank')


def send(tensor, dst: int = 0, group=None, sync_op=True):
    """send_v2 parity — under SPMD expressed as ppermute toward dst. Pair
    with recv on the peer (pipeline p2p uses p2p.py's ppermute helpers)."""
    from .p2p_utils import ppermute_to

    return ppermute_to(tensor, dst, group)


def recv(tensor, src: int = 0, group=None, sync_op=True):
    from .p2p_utils import ppermute_from

    return ppermute_from(tensor, src, group)


def barrier(group=None):
    """barrier parity: a tiny psum forces a rendezvous under SPMD; a no-op in
    single-controller eager mode (the controller is trivially synchronized)."""
    ax_name = _axis(group)
    if _axis_bound(ax_name):
        lax.psum(jnp.ones(()), ax_name)
    return None


def wait(tensor, group=None, use_calc_stream=True):
    """c_wait_* parity: XLA owns stream ordering; block_until_ready for the
    eager caller."""
    x = _unwrap(tensor)
    if hasattr(x, "block_until_ready") and not _in_trace(x):
        x.block_until_ready()
    return tensor


def split_group_axis(x, group=None, axis: int = 0):
    """c_split parity: keep this rank's slice along ``axis``."""
    ax_name = _axis(group)
    arr = _unwrap(x)
    if _axis_bound(ax_name):
        n = lax.axis_size(ax_name)
        idx = lax.axis_index(ax_name)
        size = arr.shape[axis] // n
        out = lax.dynamic_slice_in_dim(arr, idx * size, size, axis=axis)
        return Tensor(out) if isinstance(x, Tensor) else out
    return x


def isend(tensor, dst: int = 0, group=None):
    """Async-flavored send (parity: paddle.distributed.isend). XLA schedules
    communication itself, so this is `send` returning a completed-task
    handle with `.wait()`. Outside an SPMD trace (no bound axis) it is a
    self-send no-op, like barrier."""
    if _axis_bound(_axis(group)):
        send(tensor, dst=dst, group=group, sync_op=False)
    return _DoneTask()


def irecv(tensor, src: int = 0, group=None):
    """Async-flavored recv (parity: paddle.distributed.irecv)."""
    if _axis_bound(_axis(group)):
        out = recv(tensor, src=src, group=group, sync_op=False)
    else:
        out = tensor
    return _DoneTask(out)


class _DoneTask:
    """Completed-communication handle: XLA has no user-visible in-flight
    state, so is_completed is always True (the reference's task wraps a
    ProcessGroup work object)."""

    def __init__(self, result=None):
        self._result = result

    def is_completed(self):
        return True

    def wait(self):
        return self._result


def all_gather_object(object_list, obj, group=None):
    """Gather picklable python objects from every rank (parity:
    paddle.distributed.all_gather_object): pickle -> uint8 tensor ->
    padded all_gather -> unpickle."""
    import pickle

    import numpy as np

    from ..tensor import Tensor

    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    n = int(payload.size)
    # exchange sizes first so rank payloads can be padded identically
    import jax.numpy as jnp

    size_t = Tensor(jnp.asarray(np.array([n], np.int32)))
    sizes = []
    all_gather(sizes, size_t, group=group)
    max_n = int(max(int(np.asarray(s._data)[0]) for s in sizes))
    padded = np.zeros(max_n, np.uint8)
    padded[:n] = payload
    gathered = []
    all_gather(gathered, Tensor(jnp.asarray(padded)), group=group)
    object_list.clear()
    for s, g in zip(sizes, gathered):
        ln = int(np.asarray(s._data)[0])
        object_list.append(pickle.loads(bytes(np.asarray(g._data)[:ln])))
    return object_list
