"""Hybrid-parallel topology.

Parity: /root/reference/python/paddle/distributed/fleet/base/topology.py —
``CommunicateTopology`` (:36, cartesian rank mesh), ``HybridCommunicateGroup``
(:117, builds dp/mp/pp/sharding comm groups + p2p groups :225), ``ParallelMode``
enum (:29).

TPU-native: the cartesian topology IS a jax.sharding.Mesh; "creating a comm
group" costs nothing (groups are axis names). HybridCommunicateGroup also
*installs* the global mesh so pjit/shard_map see the same axes the user's
Fleet config declared.
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence

import numpy as np

from .env import init_mesh
from .group import Group, new_group

__all__ = ["ParallelMode", "CommunicateTopology", "HybridCommunicateGroup"]


class ParallelMode:
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3
    SEGMENT_PARALLEL = 4  # sequence/context parallel (TPU-native addition)
    EXPERT_PARALLEL = 5


class CommunicateTopology:
    def __init__(self, hybrid_group_names: Sequence[str] = ("data", "pipe", "sharding", "model"),
                 dims: Sequence[int] = (1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = list(itertools.product(*[range(d) for d in dims]))
        self._rank2coord = {i: c for i, c in enumerate(self.coordinate)}
        self._coord2rank = {c: i for i, c in enumerate(self.coordinate)}

    def get_hybrid_group_names(self) -> List[str]:
        return self._parallel_names

    def get_dim(self, axis_name: str) -> int:
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self) -> int:
        return int(np.prod(self._dims))

    def get_rank(self, **kwargs) -> int:
        coord = tuple(kwargs[name] for name in self._parallel_names)
        return self._coord2rank[coord]

    def get_coord(self, rank: int):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name: str, index: int) -> List[int]:
        axis = self._parallel_names.index(axis_name)
        return sorted(r for r, c in self._rank2coord.items() if c[axis] == index)

    def get_comm_list(self, axis_name: str) -> List[List[int]]:
        """All rank-groups that vary only along axis_name."""
        axis = self._parallel_names.index(axis_name)
        other = [i for i in range(len(self._dims)) if i != axis]
        groups = []
        for combo in itertools.product(*[range(self._dims[i]) for i in other]):
            ranks = []
            for v in range(self._dims[axis]):
                coord = [0] * len(self._dims)
                for i, o in zip(combo, other):
                    coord[o] = i
                coord[axis] = v
                ranks.append(self._coord2rank[tuple(coord)])
            groups.append(ranks)
        return groups

    def get_rank_from_stage(self, global_rank: int, **kwargs) -> int:
        coord = list(self.get_coord(global_rank))
        for k, v in kwargs.items():
            coord[self._parallel_names.index(k)] = v
        return self._coord2rank[tuple(coord)]


class HybridCommunicateGroup:
    """Builds all parallel groups from degrees and installs the global mesh.

    Axis order (data, pipe, sharding, sp, model) keeps 'model' innermost so
    TP collectives ride the fastest ICI dimension.
    """

    _AXIS_TO_MESH = {"data": "dp", "pipe": "pp", "sharding": "sharding", "sep": "sp", "model": "mp"}

    def __init__(self, topology: Optional[CommunicateTopology] = None, *,
                 dp_degree: int = 1, mp_degree: int = 1, pp_degree: int = 1,
                 sharding_degree: int = 1, sep_degree: int = 1, rank: Optional[int] = None):
        if topology is not None:
            self._topo = topology
        else:
            names, dims = [], []
            for n, d in (("data", dp_degree), ("pipe", pp_degree),
                         ("sharding", sharding_degree), ("sep", sep_degree),
                         ("model", mp_degree)):
                names.append(n)
                dims.append(d)
            self._topo = CommunicateTopology(names, dims)
        names = self._topo.get_hybrid_group_names()
        self._dp_degree = self._topo.get_dim("data") if "data" in names else 1
        self._mp_degree = self._topo.get_dim("model") if "model" in names else 1
        self._pp_degree = self._topo.get_dim("pipe") if "pipe" in names else 1
        self._sharding_degree = self._topo.get_dim("sharding") if "sharding" in names else 1
        self._sep_degree = self._topo.get_dim("sep") if "sep" in names else 1

        from .env import get_rank

        self.global_rank = rank if rank is not None else get_rank()

        # install the global mesh with only the >1 axes (plus dp always)
        mesh_axes: Dict[str, int] = {}
        for name in names:
            mesh_name = self._AXIS_TO_MESH.get(name, name)
            mesh_axes[mesh_name] = self._topo.get_dim(name)
        try:
            self.mesh = init_mesh(mesh_axes)
        except ValueError:
            self.mesh = None  # not enough local devices (multi-process mode)

        self._dp_group = new_group(axis_name="dp")
        self._mp_group = new_group(axis_name="mp")
        self._pp_group = new_group(axis_name="pp")
        self._sharding_group = new_group(axis_name="sharding")
        self._sep_group = new_group(axis_name="sp")
        self._check_group = Group(ranks=list(range(self._topo.world_size())))

    # ------------------------------------------------------------------
    def get_parallel_mode(self):
        if self._mp_degree == 1 and self._pp_degree == 1 and self._sharding_degree > 1:
            return ParallelMode.SHARDING_PARALLEL
        if self._mp_degree == 1 and self._pp_degree == 1:
            return ParallelMode.DATA_PARALLEL
        if self._mp_degree > 1 and self._pp_degree == 1:
            return ParallelMode.TENSOR_PARALLEL
        if self._pp_degree > 1:
            return ParallelMode.PIPELINE_PARALLEL
        return ParallelMode.DATA_PARALLEL

    def _coord(self):
        return self._topo.get_coord(self.global_rank)

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    # data parallel
    def get_data_parallel_rank(self):
        return self._coord()[self._topo.get_hybrid_group_names().index("data")]

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._dp_group

    def get_data_parallel_group_src_rank(self):
        return self._topo.get_axis_list("data", 0)[0] if self._dp_degree else 0

    # model (tensor) parallel
    def get_model_parallel_rank(self):
        return self._coord()[self._topo.get_hybrid_group_names().index("model")]

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._mp_group

    def get_model_parallel_group_src_rank(self):
        return 0

    # pipeline
    def get_stage_id(self):
        return self._coord()[self._topo.get_hybrid_group_names().index("pipe")]

    def get_pipe_parallel_rank(self):
        return self.get_stage_id()

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._pp_group

    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1

    # sharding
    def get_sharding_parallel_rank(self):
        return self._coord()[self._topo.get_hybrid_group_names().index("sharding")]

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._sharding_group

    def get_sharding_parallel_group_src_rank(self):
        return 0

    # sequence parallel (TPU-native addition; absent in the reference §5.7)
    def get_sep_parallel_rank(self):
        names = self._topo.get_hybrid_group_names()
        return self._coord()[names.index("sep")] if "sep" in names else 0

    def get_sep_parallel_world_size(self):
        return self._sep_degree

    def get_sep_parallel_group(self):
        return self._sep_group

    def get_check_parallel_group(self):
        return self._check_group

    def get_rank_from_stage(self, stage_id: int, **kwargs):
        return self._topo.get_rank_from_stage(self.global_rank, pipe=stage_id, **kwargs)
