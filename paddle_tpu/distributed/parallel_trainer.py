"""Hybrid-parallel jitted train steps — the production TPU training path.

Parity role: this module is the TPU-native replacement for the reference's
whole static-graph distributed pipeline — fleet meta-optimizers rewriting
ProgramDesc (sharding_optimizer.py, raw_program_optimizer.py,
pipeline_optimizer.py), ParallelExecutor SSA graphs, and the dygraph
HybridParallelOptimizer step loop. One function composition:

    loss(params, batch) → value_and_grad → [clip] → opt.apply_gradients

jitted over the global mesh with:
- batch sharded over 'dp' (data parallel; XLA inserts the grad all-reduce,
  replacing AllReduceOpHandle / c_allreduce_sum insertion),
- params sharded per their ``partition_spec`` ('mp' for TP layers; 'fsdp'
  dim-0 sharding for ZeRO-3),
- optimizer slots sharded over the sharding axis (ZeRO-1/2),
- jax.checkpoint on declared segments (recompute),
- microbatch lax.scan for gradient merge / pipeline accumulation,
- bf16 compute with fp32 master weights (amp O2).
"""
from __future__ import annotations

import functools
import time
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding

from ..nn.layer import Layer
from ..observability import trace as obstrace
from ..observability.flight import flight_recorder
from ..profiler.scope import scope as prof_scope
from ..profiler.scope import timer_registry, timers_enabled
from ..tensor import Tensor
from .env import get_mesh
from .spmd import P, sanitize_spec

__all__ = ["ParallelTrainer", "build_pipeline_step"]


def _spec_of(p, default=P()):
    return getattr(p, "partition_spec", default) or default


def _fsdp_spec(shape, axis: str, n: int, existing: P):
    """Shard dim0 (or first divisible dim) over the fsdp axis if free."""
    dims = list(existing) + [None] * (len(shape) - len(existing))
    used = {a for d in dims if d is not None for a in ((d,) if isinstance(d, str) else tuple(d))}
    if axis in used:
        return P(*dims)
    for i, s in enumerate(shape):
        if dims[i] is None and s % n == 0 and s >= n:
            dims[i] = axis
            break
    return P(*dims)


class ParallelTrainer:
    """Builds and runs the jitted hybrid train step for a Layer model.

    Usage::

        trainer = ParallelTrainer(model, loss_fn, optimizer, strategy)
        loss = trainer.step(x_batch, y_batch)      # compiled once
        trainer.sync_to_model()                    # write arrays back
    """

    def __init__(
        self,
        model: Layer,
        loss_fn: Callable,
        optimizer,
        *,
        dp_axis: Optional[str] = "dp",
        fsdp_axis: Optional[str] = None,
        slot_shard_axis: Optional[str] = None,
        compute_dtype=None,
        recompute: bool = False,
        accumulate_steps: int = 1,
        donate: bool = True,
        scaler=None,
        sentinel=None,
        offload_optimizer: bool = False,
        strategy=None,
        remat_policy=None,
        abstract: bool = False,
    ):
        # DistributedStrategy wiring (the meta-optimizer config surface):
        # sharding_configs.optimize_offload ≙ offload_helper.py,
        # gradient_merge / recompute flags ≙ their meta-optimizers
        if strategy is not None:
            if getattr(strategy, "sharding", False):
                cfgs = strategy.sharding_configs
                offload_optimizer = offload_optimizer or bool(
                    cfgs.get("optimize_offload", False))
                if fsdp_axis is None and int(cfgs.get("stage", 1)) >= 2:
                    fsdp_axis = "sharding"
            if getattr(strategy, "recompute", False):
                recompute = True
            if getattr(strategy, "gradient_merge", False):
                accumulate_steps = max(
                    accumulate_steps,
                    int(strategy.gradient_merge_configs.get("k_steps", 1)))
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        mesh = get_mesh()
        if mesh is None:
            raise RuntimeError("install a mesh first (fleet.init / init_mesh)")
        self.mesh = mesh
        self.dp_axis = dp_axis if dp_axis in mesh.shape else None
        self.fsdp_axis = fsdp_axis if fsdp_axis and fsdp_axis in mesh.shape else None
        # ZeRO-1/2 without param sharding: shard ONLY the optimizer slots
        # over this axis (the planner's lowered candidates use it to price
        # slot sharding with a replicated local batch)
        self.slot_shard_axis = (slot_shard_axis
                                if slot_shard_axis and slot_shard_axis in mesh.shape
                                else None)
        self.compute_dtype = compute_dtype
        self.recompute = recompute
        self.accumulate_steps = accumulate_steps
        self.donate = donate
        # planner-emitted remat policy (analysis.plan.RematPolicy): applied
        # here so the jitted step this trainer builds IS the priced program;
        # a disabled policy must leave the jaxpr untouched (bit-for-bit)
        self.remat_policy = remat_policy
        if remat_policy is not None:
            remat_policy.apply(self)
        # abstract mode: params/opt-state/buffers are ShapeDtypeStructs and
        # the jitted step is only ever TRACED (make_jaxpr/eval_shape), never
        # dispatched — the planner lowers full-size candidates through the
        # exact _build() code path without allocating device memory
        self.abstract = bool(abstract)
        self.step_count = 0  # host step counter (telemetry spans + flight)

        # in-graph dynamic loss scaling (amp ops check_finite_and_unscale +
        # update_loss_scaling as pure functions in the jitted step)
        self._scaler = scaler if (scaler is not None and scaler.is_enable()) else None
        if self._scaler is not None:
            self.scale_state = {
                "loss_scale": jnp.asarray(scaler.get_loss_scaling(), jnp.float32),
                "good_steps": jnp.asarray(scaler._good_steps, jnp.int32),
                "bad_steps": jnp.asarray(scaler._bad_steps, jnp.int32),
            }
        else:
            self.scale_state = {}

        # in-step anomaly sentinel (resilience.sentinel): rolling loss
        # statistics ride in the jitted step's carry exactly like the
        # scaler's scale_state; disabled ⇒ empty pytree ⇒ identical jaxpr
        self._sentinel = sentinel if (
            sentinel is not None and sentinel.enabled) else None
        if self._sentinel is not None:
            from ..resilience.sentinel import sentinel_init_state

            self.sentinel_state = sentinel_init_state()
        else:
            self.sentinel_state = {}

        # --- parameter placement ---------------------------------------
        self._param_tensors = dict(model.named_parameters())
        self._buffer_tensors = dict(model.named_buffers())
        self.param_specs: Dict[str, P] = {}
        for n, p in self._param_tensors.items():
            spec = sanitize_spec(_spec_of(p), mesh)
            if self.fsdp_axis:
                spec = _fsdp_spec(tuple(p._data.shape), self.fsdp_axis,
                                  int(mesh.shape[self.fsdp_axis]), spec)
            self.param_specs[n] = spec
        if self.abstract:
            if offload_optimizer:
                raise NotImplementedError(
                    "abstract lowering with offload_optimizer is not "
                    "composed (the update runs host-side, outside the "
                    "jitted step the planner prices)")
            self._init_abstract_state()
            return

        def _owned_put(arr, sharding):
            # device_put ALIASES the source buffer when the placement
            # already matches (a distinct wrapper over the same memory —
            # e.g. any replicated array on a 1-device mesh).  With donation
            # on, the jitted step would then delete the model Tensor's own
            # buffer out from under eager reads; force an owned copy.
            if donate:
                arr = jnp.copy(arr)
            return jax.device_put(arr, sharding)

        self.params = {
            n: _owned_put(p._data, NamedSharding(mesh, self.param_specs[n]))
            for n, p in self._param_tensors.items()
        }
        self.buffers = {
            n: _owned_put(b._data, NamedSharding(mesh, P()))
            for n, b in self._buffer_tensors.items()
        }

        # --- ZeRO-offload: master params + slots live in HOST pinned
        # memory, the device step only produces grads (reference:
        # sharding/offload_helper.py — fp32 masters + moments on CPU,
        # updates computed there, cast params copied back) ----------------
        self.offload = bool(offload_optimizer)
        if self.offload:
            if self._scaler is not None:
                raise NotImplementedError(
                    "offload_optimizer with a GradScaler is not composed yet")
            if self._sentinel is not None:
                raise NotImplementedError(
                    "offload_optimizer with an anomaly sentinel is not "
                    "composed yet (the update runs host-side)")
            import numpy as np

            from ..core import PinnedPool

            self._cpu = jax.local_devices(backend="cpu")[0]
            self._pool = PinnedPool()

            def _host_buf(arr, dtype=None):
                buf = self._pool.alloc_array(
                    tuple(arr.shape), dtype or np.float32)
                np.copyto(buf, np.asarray(arr, buf.dtype))
                return buf

            self._master = {n: _host_buf(p._data)
                            for n, p in self._param_tensors.items()}
            with jax.default_device(self._cpu):
                st = optimizer.init_state(
                    {n: jnp.asarray(a) for n, a in self._master.items()})
            self._host_slots = jax.tree_util.tree_map(
                lambda a: _host_buf(a, np.asarray(a).dtype), st["slots"])
            self._host_step = st["step"]
            self.opt_state = None  # nothing optimizer-side on device
            self._jit_step = None
            self._jit_eval = None
            return

        # --- optimizer state placement (ZeRO-1/2 ≙ slot sharding) ------
        self.opt_state = optimizer.init_state(self.params)
        shard_axis = self.slot_shard_axis or self.fsdp_axis or self.dp_axis
        if shard_axis:
            n_shard = int(mesh.shape[shard_axis])
            slot_specs = jax.tree_util.tree_map(
                lambda a: _fsdp_spec(tuple(a.shape), shard_axis, n_shard, P()),
                self.opt_state["slots"],
            )
            self.opt_state = {
                "slots": jax.tree_util.tree_map(
                    lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
                    self.opt_state["slots"], slot_specs,
                ),
                "step": self.opt_state["step"],
            }

        # every opt-state leaf must live on the mesh (the scalar step etc.)
        self.opt_state = jax.tree_util.tree_map(
            lambda a: a if (isinstance(a, jax.Array)
                            and isinstance(a.sharding, NamedSharding))
            else jax.device_put(jnp.asarray(a), NamedSharding(mesh, P())),
            self.opt_state,
        )

        self._jit_step = None
        self._jit_eval = None

    # ------------------------------------------------------------------
    def _init_abstract_state(self):
        """Abstract-mode state: the same placement DECISIONS as the concrete
        path (param specs, ZeRO slot sharding, replication) recorded as
        in_shardings over the real mesh, but every array is a
        ShapeDtypeStruct — nothing is allocated, the step is only traced."""
        import numpy as np

        mesh = self.mesh

        def _sds(arr):
            if isinstance(arr, jax.ShapeDtypeStruct):
                return arr
            return jax.ShapeDtypeStruct(tuple(arr.shape), np.dtype(arr.dtype))

        self.params = {n: _sds(p._data)
                       for n, p in self._param_tensors.items()}
        self.buffers = {n: _sds(b._data)
                        for n, b in self._buffer_tensors.items()}
        self.offload = False
        self.opt_state = jax.eval_shape(self.optimizer.init_state,
                                        self.params)
        shard_axis = self.slot_shard_axis or self.fsdp_axis or self.dp_axis
        repl = NamedSharding(mesh, P())
        if shard_axis:
            n_shard = int(mesh.shape[shard_axis])
            slot_sh = jax.tree_util.tree_map(
                lambda a: NamedSharding(mesh, _fsdp_spec(
                    tuple(a.shape), shard_axis, n_shard, P())),
                self.opt_state["slots"])
        else:
            slot_sh = jax.tree_util.tree_map(lambda a: repl,
                                             self.opt_state["slots"])
        # mirror of the concrete path's `a.sharding` read in _build()
        self._opt_shardings = {
            "slots": slot_sh,
            "step": repl,
        }
        self._jit_step = None
        self._jit_eval = None

    def lowered_step_args(self, xb, yb, rng_key=None, lr: float = 1e-4):
        """The abstract argument tuple for tracing ``_jit_step`` —
        ShapeDtypeStruct state plus the caller's batch specs (the planner's
        AnalysisTarget args)."""
        from ..random import split_key

        if rng_key is None:
            rng_key = split_key()
        return (self.params, self.opt_state, self.buffers, xb, yb, rng_key,
                self.scale_state, self.sentinel_state,
                jnp.asarray(lr, jnp.float32))

    # ------------------------------------------------------------------
    def _loss_from_tree(self, params, buffers, xb, yb, rng_key):
        """Pure loss: swap arrays into the model, run forward+loss."""
        from ..autograd import tape
        from ..random import get_rng_state, set_rng_state

        saved = get_rng_state()
        set_rng_state(rng_key)
        try:
            with tape.no_grad():
                if self.compute_dtype is not None:
                    cparams = {
                        n: (a.astype(self.compute_dtype)
                            if jnp.issubdtype(a.dtype, jnp.floating) else a)
                        for n, a in params.items()
                    }
                else:
                    cparams = params
                out, new_buffers = self.model.functional_call_with_state(
                    cparams, buffers, Tensor(xb)
                )
                loss = self.loss_fn(out, Tensor(yb))
        finally:
            set_rng_state(saved)
        loss_arr = loss._data if isinstance(loss, Tensor) else loss
        return loss_arr.astype(jnp.float32), new_buffers

    def _build(self):
        mesh = self.mesh
        acc = self.accumulate_steps
        dp = self.dp_axis

        loss_fn = self._loss_from_tree
        if self.recompute:
            # remat the forward; XLA recomputes activations in backward
            loss_fn = jax.checkpoint(loss_fn, static_argnums=())

        use_scaling = self._scaler is not None
        if use_scaling:
            incr_every = int(self._scaler._incr_every_n_steps)
            incr_ratio = float(self._scaler._incr_ratio)
            decr_ratio = float(self._scaler._decr_ratio)
            decr_every = int(self._scaler._decr_every_n_nan_or_inf)
            dynamic = bool(self._scaler.is_use_dynamic_loss_scaling())
        use_sentinel = self._sentinel is not None
        if use_sentinel:
            from ..resilience.sentinel import SENTINEL_OK, sentinel_observe

            sent_cfg = self._sentinel

        def step(params, opt_state, buffers, xb, yb, rng_key, scale_state,
                 sent_state, lr):
            scale = scale_state["loss_scale"] if use_scaling else None

            base_loss_fn = loss_fn
            if use_scaling:
                def loss_fn_(p, b, mx, my, k):
                    l, nb = base_loss_fn(p, b, mx, my, k)
                    return l * scale, nb
            else:
                loss_fn_ = base_loss_fn

            if acc <= 1:
                with prof_scope("trainer.loss_grad"):
                    (loss, new_buffers), grads = jax.value_and_grad(loss_fn_, has_aux=True)(
                        params, buffers, xb, yb, rng_key
                    )
            else:
                # gradient merge (reference: gradient_merge_optimizer.py) as
                # a lax.scan over microbatches
                micro_x = xb.reshape((acc, xb.shape[0] // acc) + xb.shape[1:])
                micro_y = yb.reshape((acc, yb.shape[0] // acc) + yb.shape[1:])
                keys = jax.random.split(rng_key, acc)

                def body(carry, mb):
                    g_acc, l_acc, bufs = carry
                    mx, my, k = mb
                    (l, nb), g = jax.value_and_grad(loss_fn_, has_aux=True)(
                        params, bufs, mx, my, k
                    )
                    g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
                    return (g_acc, l_acc + l, nb), None

                zero_g = jax.tree_util.tree_map(
                    lambda a: jnp.zeros(a.shape, jnp.float32), params
                )
                with prof_scope("trainer.loss_grad"):
                    (grads, loss_sum, new_buffers), _ = jax.lax.scan(
                        body, (zero_g, jnp.zeros((), jnp.float32), buffers),
                        (micro_x, micro_y, keys),
                    )
                grads = jax.tree_util.tree_map(lambda g: g / acc, grads)
                loss = loss_sum / acc

            if use_scaling:
                # check_finite_and_unscale
                grads = jax.tree_util.tree_map(lambda g: g / scale, grads)
                loss = loss / scale
            finite = None
            if use_scaling or (use_sentinel and sent_cfg.check_nonfinite):
                finite = jnp.asarray(True)
                for g in jax.tree_util.tree_leaves(grads):
                    finite = finite & jnp.all(jnp.isfinite(g))

            # anomaly sentinel: classify the (unscaled) loss against the
            # rolling statistics; `ok` gates the update (skip policy) AND
            # drives the GradScaler machine, so a loss spike is treated as
            # a bad step and shrinks the scale (skip-and-rescale)
            if use_sentinel:
                code, new_sent = sentinel_observe(
                    sent_state, loss, finite, sent_cfg)
                ok = code == SENTINEL_OK
                if finite is not None:
                    ok = ok & finite  # check_nonfinite=False still skips
            else:
                new_sent = sent_state
                ok = finite

            with prof_scope("trainer.optimizer_apply"):
                new_params, new_opt = self.optimizer.apply_gradients(
                    params, grads, opt_state, lr=lr)
            if ok is not None:
                keep = lambda new, old: jax.tree_util.tree_map(
                    lambda a, b: jnp.where(ok, a, b), new, old)
                new_params = keep(new_params, params)
                new_opt = keep(new_opt, opt_state)
            if use_scaling:
                # update_loss_scaling state machine (mirror of the eager
                # GradScaler.update incl. static-scale + decr_every modes)
                if dynamic:
                    good = jnp.where(ok, scale_state["good_steps"] + 1, 0)
                    bad = jnp.where(ok, 0, scale_state["bad_steps"] + 1)
                    grown = jnp.where(good >= incr_every, scale * incr_ratio, scale)
                    good = jnp.where(good >= incr_every, 0, good)
                    shrunk = jnp.where(bad >= decr_every,
                                       jnp.maximum(scale * decr_ratio, 1.0), scale)
                    bad = jnp.where(bad >= decr_every, 0, bad)
                    new_scale = jnp.where(ok, grown, shrunk)
                    new_scale_state = {"loss_scale": new_scale,
                                       "good_steps": good, "bad_steps": bad}
                else:
                    new_scale_state = scale_state
            else:
                new_scale_state = scale_state

            return (new_params, new_opt, new_buffers, loss, new_scale_state,
                    new_sent)

        param_sh = {n: NamedSharding(mesh, s) for n, s in self.param_specs.items()}

        if self.offload:
            # device computes grads only; the update runs host-side
            def grad_step(params, buffers, xb, yb, rng_key):
                if acc <= 1:
                    (l, nb), g = jax.value_and_grad(loss_fn, has_aux=True)(
                        params, buffers, xb, yb, rng_key)
                    return g, l, nb
                micro_x = xb.reshape((acc, xb.shape[0] // acc) + xb.shape[1:])
                micro_y = yb.reshape((acc, yb.shape[0] // acc) + yb.shape[1:])
                keys = jax.random.split(rng_key, acc)

                def body(carry, mb):
                    g_acc, l_acc, bufs = carry
                    mx, my, k = mb
                    (l, nb), g = jax.value_and_grad(loss_fn, has_aux=True)(
                        params, bufs, mx, my, k)
                    return (jax.tree_util.tree_map(jnp.add, g_acc, g),
                            l_acc + l, nb), None

                zero_g = jax.tree_util.tree_map(
                    lambda a: jnp.zeros(a.shape, jnp.float32), params)
                (g, l_sum, nb), _ = jax.lax.scan(
                    body, (zero_g, jnp.zeros((), jnp.float32), buffers),
                    (micro_x, micro_y, keys))
                g = jax.tree_util.tree_map(lambda x: x / acc, g)
                return g, l_sum / acc, nb

            buf_sh0 = {n: NamedSharding(mesh, P()) for n in self.buffers}
            batch_sh0 = NamedSharding(mesh, P(dp) if dp else P())
            repl0 = NamedSharding(mesh, P())
            self._jit_step = jax.jit(
                grad_step,
                in_shardings=(param_sh, buf_sh0, batch_sh0, batch_sh0, None),
                out_shardings=({n: repl0 for n in self.params}, repl0, buf_sh0),
            )
            return

        if self.abstract:
            # ShapeDtypeStructs carry no placement; the recorded decisions
            # from _init_abstract_state are the in_shardings
            opt_sh = self._opt_shardings
        else:
            opt_sh = jax.tree_util.tree_map(
                lambda a: a.sharding if isinstance(a, jax.Array) else None,
                self.opt_state,
            )
        buf_sh = {n: NamedSharding(mesh, P()) for n in self.buffers}
        batch_sh = NamedSharding(mesh, P(dp) if dp else P())
        repl = NamedSharding(mesh, P())
        scale_sh = {k: repl for k in self.scale_state}
        sent_sh = {k: repl for k in self.sentinel_state}
        self._jit_step = jax.jit(
            step,
            in_shardings=(param_sh, opt_sh, buf_sh, batch_sh, batch_sh, None,
                          scale_sh, sent_sh, None),
            # pin outputs to the input placements so donated buffers round-
            # trip bit-identically across steps
            out_shardings=(param_sh, opt_sh, buf_sh, repl, scale_sh, sent_sh),
            # donate every carried-state arg, not just params/opt: buffers
            # (BN running stats) and the scaler/sentinel carries also round-
            # trip through the step, and an un-donated round-trip is a
            # silent HBM copy per step (analysis donation-miss finding, r9;
            # step() rebinds all five from the outputs, so the stale inputs
            # are never read again)
            donate_argnums=(0, 1, 2, 6, 7) if self.donate else (),
        )

    # ------------------------------------------------------------------
    def step(self, x, y):
        from ..random import split_key

        if self.abstract:
            raise RuntimeError(
                "abstract trainer: the jitted step exists only to be traced "
                "(analysis/plan.py candidate pricing); build a concrete "
                "ParallelTrainer to execute")
        if self._jit_step is None:
            self._build()
        xb = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        yb = y._data if isinstance(y, Tensor) else jnp.asarray(y)
        if self.offload:
            grads, loss, self.buffers = self._jit_step(
                self.params, self.buffers, xb, yb, split_key())
            self._host_apply(grads)
            return Tensor(loss)
        # lr enters as a runtime scalar so LR schedules take effect on the
        # compiled step (read at trace time it would be baked as a constant)
        lr_now = jnp.asarray(float(self.optimizer.get_lr()), jnp.float32)
        t0 = time.perf_counter() if timers_enabled() else None
        step_idx = self.step_count
        self.step_count += 1
        # the key is kept so sanitize_step can replay THIS step faithfully
        # (a fresh key would draw different dropout masks); the key arg is
        # not donated, so the array stays readable after the step
        self.last_step_key = key = split_key()
        with obstrace.span("train.step", step=step_idx):
            (self.params, self.opt_state, self.buffers, loss,
             self.scale_state, self.sentinel_state) = self._jit_step(
                self.params, self.opt_state, self.buffers, xb, yb, key,
                self.scale_state, self.sentinel_state, lr_now,
            )
        if t0 is not None:
            timer_registry.record("trainer.step.host_dispatch",
                                  time.perf_counter() - t0)
        fr = flight_recorder()
        if fr.armed or obstrace.tracing_enabled():
            # pin the current step so a crash dump can name where it died
            fr.note(step=step_idx)
        return Tensor(loss)

    def _host_apply(self, grads):
        """ZeRO-offload update: D2H grads → fp32 master update on the host
        CPU backend (slots in pinned-pool buffers) → H2D cast params."""
        import numpy as np

        host_grads = {
            n: jax.device_put(np.asarray(g), self._cpu) for n, g in grads.items()
        }
        with jax.default_device(self._cpu):
            masters = {n: jnp.asarray(a) for n, a in self._master.items()}
            state = {
                "slots": jax.tree_util.tree_map(jnp.asarray, self._host_slots),
                "step": self._host_step,
            }
            new_master, new_state = self.optimizer.apply_gradients(
                masters, host_grads, state)
        for n, a in new_master.items():
            np.copyto(self._master[n], np.asarray(a))
        jax.tree_util.tree_map(
            lambda dst, src: np.copyto(dst, np.asarray(src)),
            self._host_slots, new_state["slots"])
        self._host_step = new_state["step"]
        mesh = self.mesh
        for n in self.params:
            self.params[n] = jax.device_put(
                self._master[n].astype(self.params[n].dtype),
                NamedSharding(mesh, self.param_specs[n]))

    def eval_step(self, x, y):
        from ..random import split_key

        if self._jit_eval is None:
            def ev(params, buffers, xb, yb, key):
                loss, _ = self._loss_from_tree(params, buffers, xb, yb, key)
                return loss

            self._jit_eval = jax.jit(ev)
        xb = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        yb = y._data if isinstance(y, Tensor) else jnp.asarray(y)
        return Tensor(self._jit_eval(self.params, self.buffers, xb, yb, split_key()))

    def sync_to_model(self):
        """Write the trained arrays back into the Layer's Tensors.

        With donation on, the model gets OWNED copies: handing it the live
        ``self.params``/``self.buffers`` arrays would let the next
        ``step()`` donate them away and leave the model's Tensors holding
        deleted buffers (same aliasing discipline as ``capture_state``)."""
        own = (lambda a: jnp.copy(a)) if self.donate else (lambda a: a)
        for n, arr in self.params.items():
            self._param_tensors[n]._set_data(own(arr))
        for n, arr in self.buffers.items():
            self._buffer_tensors[n]._set_data(own(arr))
        self.sync_scaler()

    def sync_scaler(self):
        """Write the in-graph scale state back into the GradScaler so its
        state_dict()/get_loss_scaling() reflect training (checkpointing)."""
        if self._scaler is not None and self.scale_state:
            self._scaler._scale = float(self.scale_state["loss_scale"])
            self._scaler._good_steps = int(self.scale_state["good_steps"])
            self._scaler._bad_steps = int(self.scale_state["bad_steps"])

    # -- resilience hooks ----------------------------------------------
    def sanitize_step(self, x, y, *, state=None, key=None, config=None):
        """Replay ONE train step eqn-by-eqn under the analysis sanitizer
        and return its :class:`~paddle_tpu.analysis.sanitizer.SanitizeResult`
        — the ``FLAGS_check_nan_inf`` "which eqn made the NaN" answer the
        in-graph sentinel cannot give.

        ``state`` is an optional :meth:`capture_state` snapshot (replay the
        *failing* step from just before it ran); default is the live state.
        ``key`` defaults to the LAST step()'s RNG key, so a stochastic
        model (dropout) replays the failing step's exact masks.  The
        replay binds each primitive eagerly with donation stripped, so the
        live training state is untouched."""
        from ..analysis.sanitizer import sanitize
        from ..random import split_key

        if self.offload:
            raise NotImplementedError(
                "sanitize_step with offload_optimizer is not composed yet")
        if self._jit_step is None:
            self._build()
        if state is not None:
            params = {n: jnp.asarray(a) for n, a in state["params"].items()}
            opt_state = jax.tree_util.tree_map(jnp.asarray,
                                               state["opt_state"])
            buffers = {n: jnp.asarray(a)
                       for n, a in state["buffers"].items()}
            scale = {k: jnp.asarray(v)
                     for k, v in state.get("scale_state", {}).items()}
            sent = {k: jnp.asarray(v)
                    for k, v in state.get("sentinel_state", {}).items()}
        else:
            params, opt_state, buffers = (self.params, self.opt_state,
                                          self.buffers)
            scale, sent = self.scale_state, self.sentinel_state
        xb = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        yb = y._data if isinstance(y, Tensor) else jnp.asarray(y)
        lr_now = jnp.asarray(float(self.optimizer.get_lr()), jnp.float32)
        if key is None:
            key = getattr(self, "last_step_key", None)
        if key is None:
            key = split_key()
        args = (params, opt_state, buffers, xb, yb, key, scale, sent,
                lr_now)
        return sanitize(self._jit_step, args, config=config)

    def sentinel_report(self):
        """Host copy of the sentinel statistics ({} when disabled)."""
        if not self.sentinel_state:
            return {}
        from ..resilience.sentinel import sentinel_to_host

        return sentinel_to_host(self.sentinel_state)

    def capture_state(self):
        """Checkpointable snapshot of the full jitted-training state:
        params, optimizer slots, buffers, in-graph scale state and sentinel
        statistics. Leaves are HOST numpy copies — with ``donate=True`` the
        next step() deletes the current device buffers, so a snapshot that
        merely referenced them would be dead by the time an emergency save
        (or a sentinel rollback several steps later) reads it. Hand the dict
        straight to CheckpointManager.save."""
        if self.offload:
            raise NotImplementedError(
                "capture_state with offload_optimizer is not composed yet")
        import numpy as np

        # np.array (not asarray): on the CPU backend asarray can alias the
        # device buffer zero-copy, which donation would invalidate just the
        # same — force an owned copy
        return jax.tree_util.tree_map(lambda a: np.array(a), {
            "params": dict(self.params),
            "opt_state": self.opt_state,
            "buffers": dict(self.buffers),
            "scale_state": dict(self.scale_state),
            "sentinel_state": dict(self.sentinel_state),
        })

    def state_layout(self):
        """JSON-able sharding metadata for a :meth:`capture_state` snapshot
        — per-param PartitionSpec entries plus the mesh axis sizes they were
        captured under. Pass as ``CheckpointManager.save(layout=...)`` so a
        later load on a DIFFERENT topology knows how the arrays were laid
        out (the snapshot arrays themselves are global host copies; the
        in-process reshard happens in :meth:`restore_state`)."""
        def entries(spec):
            return [list(e) if isinstance(e, (tuple, list)) else e
                    for e in spec]

        mesh_axes = {str(k): int(v) for k, v in self.mesh.shape.items()}
        return {
            f"/params/{n}": {"axes": entries(self.param_specs[n]),
                             "mesh": mesh_axes}
            for n in self.params
        }

    def restore_state(self, state):
        """Inverse of :meth:`capture_state`: re-place every leaf on the mesh
        with its live sharding (a checkpoint loaded on a different topology
        reshards here — validated first, so an extent the new mesh cannot
        divide raises :class:`CheckpointReshardError` instead of an opaque
        XLA failure). Restores scaler/sentinel carries only when both the
        snapshot and this trainer have them enabled."""
        from ..framework.checkpoint import _check_reshardable

        mesh = self.mesh
        for n, a in state["params"].items():
            if n in self.param_specs:
                _check_reshardable(f"params/{n}", jnp.shape(a),
                                   self.param_specs[n], mesh)
        self.params = {
            n: jax.device_put(jnp.asarray(a),
                              NamedSharding(mesh, self.param_specs[n]))
            for n, a in state["params"].items()
        }
        self.opt_state = jax.tree_util.tree_map(
            lambda old, new: jax.device_put(
                jnp.asarray(new, getattr(old, "dtype", None)), old.sharding),
            self.opt_state, state["opt_state"])
        self.buffers = {
            n: jax.device_put(jnp.asarray(a), NamedSharding(mesh, P()))
            for n, a in state["buffers"].items()
        }
        repl = NamedSharding(mesh, P())
        if self.scale_state and state.get("scale_state"):
            self.scale_state = {
                k: jax.device_put(jnp.asarray(v, self.scale_state[k].dtype),
                                  repl)
                for k, v in state["scale_state"].items()
            }
            self.sync_scaler()
        if self.sentinel_state and state.get("sentinel_state"):
            self.sentinel_state = {
                k: jax.device_put(jnp.asarray(v, self.sentinel_state[k].dtype),
                                  repl)
                for k, v in state["sentinel_state"].items()
            }


def build_pipeline_step(pipe_layer, hcg, optimizer, accumulate_steps: int = 1,
                        scaler=None, sentinel=None):
    """PipelineLayer train step. On a mesh with pp > 1 this builds the REAL
    ppermute-scan stage-parallel program
    (meta_parallel.pipeline_schedule.build_pipeline_layer_step); when the
    layer stack has no pipelineable uniform body, it falls back LOUDLY to
    microbatch accumulation over the full stage sequence under GSPMD
    (correct semantics, no stage parallelism)."""
    mesh = get_mesh()
    pp_degree = int(mesh.shape.get("pp", 1)) if mesh is not None else 1
    if pp_degree > 1 and scaler is None:
        from .meta_parallel.pipeline_schedule import build_pipeline_layer_step

        n_virtual = int(getattr(pipe_layer, "_num_virtual_pipeline_stages", 1) or 1)
        try:
            step = build_pipeline_layer_step(
                pipe_layer, optimizer,
                microbatches=max(accumulate_steps, 1),
                num_virtual_stages=n_virtual, mesh=mesh, sentinel=sentinel)
        except ValueError as e:
            import warnings

            warnings.warn(
                f"PipelineParallel: falling back to the NON-pipelined GSPMD "
                f"step ({e}); pp={pp_degree} will not overlap stages",
                RuntimeWarning, stacklevel=2)
        else:
            # no per-step sync: copying every sharded weight back into the
            # eager Tensors each step would serialize against the jitted
            # step — PipelineParallel syncs lazily before eval/state_dict
            def run(x, y):
                return Tensor(step(x, y))

            run._pipeline_step = step
            return run
    loss_fn = pipe_layer._loss_fn or (lambda out, y: out.mean())
    trainer = ParallelTrainer(
        pipe_layer,
        lambda out, y: loss_fn(out, y),
        optimizer,
        accumulate_steps=accumulate_steps,
        scaler=scaler,
        sentinel=sentinel,
    )

    def run(x, y):
        loss = trainer.step(x, y)
        trainer.sync_to_model()
        return loss

    run._trainer = trainer
    return run
