"""TensorParallel model wrapper.

Parity: meta_parallel/tensor_parallel.py in the reference — broadcasts mp
params from rank 0 and syncs inputs. TPU-native: parameter "broadcast" is a
device_put with the layer's partition_spec (replicated specs are identical on
every shard by construction), so this wrapper mostly installs shardings.
"""
from __future__ import annotations

from ...nn.layer import Layer
from ..spmd import P, shard_array

__all__ = ["TensorParallel"]


class TensorParallel(Layer):
    def __init__(self, layers: Layer, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        import jax

        for _, p in layers.named_parameters():
            spec = getattr(p, "partition_spec", P())
            if not isinstance(p._data, jax.core.Tracer):
                try:
                    shard_array(p, spec)
                except Exception:
                    pass  # mesh absent (pure-eager unit tests)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__.get("_sub_layers", {}).get("_layers"), name)
